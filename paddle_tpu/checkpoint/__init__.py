"""Topology-independent sharded checkpoints + elastic resize.

Every host writes only the variable shards it owns plus a JSON manifest
(var → global shape/dtype, shard → dim-0 slice extents, writer
topology, content digests, monotonic step id); restore reads manifests,
plans per-host reads, and re-shards to ANY target layout — N→M
pservers, a different pipeline stage count, ZeRO on or off — with a
two-phase commit (everything lands under ``_tmp``, then one atomic
rename) so a crash mid-save can never yield a loadable half-checkpoint
and restore always picks the newest COMPLETE step.  The survey's §5
checkpoint/resume discipline generalized the way DeepSpeed universal
checkpoints and Orbax do, for exactly the elastic failure mode the §2.8
runtime (PRs 2/6) keeps jobs alive through.

Modules: :mod:`manifest` (the shard catalog), :mod:`store` (two-phase
commit step directories), :mod:`reshard` (the restore planner),
:mod:`snapshot` (async no-pause snapshotter), :mod:`elastic` (scope
save/restore, fleet-cut helpers, registry-gauge resize controller).
Integration points: ``DistributeTranspilerConfig.checkpoint_sharded``
(pserver shards + restart/resize hydration),
``ParallelExecutor.save_sharded_state`` (ZeRO layouts),
``pipeline.PipelineTrainer.save_checkpoint`` (stage layouts),
``distributed.notify_checkpoint`` (the fleet cut), and
``TaskMaster.stamp_checkpoint`` (cut-step publication).
"""
from . import elastic, manifest, reshard, snapshot, store  # noqa: F401
from .elastic import (ElasticController, restore_scope, save_scope,
                      scope_snapshotter, wait_step_complete)
from .manifest import Manifest
from .reshard import load_locals, load_vars
from .snapshot import AsyncSnapshotter
from .store import (CheckpointError, commit_single, complete_steps,
                    inflight_steps, latest_complete_step, load_manifest,
                    prune, try_commit, verify_step, write_piece)

__all__ = [
    "AsyncSnapshotter", "CheckpointError", "ElasticController", "Manifest",
    "commit_single", "complete_steps", "inflight_steps",
    "latest_complete_step", "load_locals", "load_manifest", "load_vars",
    "prune", "restore_scope", "save_scope", "scope_snapshotter",
    "try_commit", "verify_step", "wait_step_complete", "write_piece",
]
