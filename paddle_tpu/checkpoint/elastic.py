"""Elastic resize: fleets whose state survives a mesh change.

The mechanics of growing/shrinking a fleet on this checkpoint plane:

1. **Cut**: the trainer (or an operator) picks a step id and asks every
   state owner to snapshot it — pservers via ``checkpoint_notify`` with
   an explicit step (``distributed.notify_checkpoint``), executors /
   pipeline trainers via their save helpers.  In sync mode the round
   barrier IS the consistent cut; the master additionally stamps the
   cut step through its snapshot/publish path
   (``TaskMaster.stamp_checkpoint``) so every standby mirror and every
   late joiner agrees on which step the fleet cut at.
2. **Commit**: each writer's piece lands under ``_tmp``;
   :func:`wait_step_complete` polls the shared root until the atomic
   commit rename happens.  A writer killed mid-snapshot simply means
   the step never commits — restore picks the previous COMPLETE step.
3. **Reshard + rejoin**: the NEW fleet (any size) transpiles its own
   layout and hydrates from the manifest — each joining host reads
   exactly the rows it now owns (``reshard.load_locals``); a departing
   host's rows are simply read by whoever owns them now.  Reader/task
   leases follow via the TaskMaster's existing health-driven requeue.

:class:`ElasticController` turns the registry's live lease/health
gauges into resize decisions (how many workers of a role are ALIVE vs
a target) — the policy half; the state mechanics above are the half
that makes acting on the decision safe.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from . import reshard as _reshard
from . import store as _store
from .snapshot import AsyncSnapshotter
from .store import CheckpointError

__all__ = ["save_scope", "restore_scope", "scope_snapshotter",
           "wait_step_complete", "ElasticController"]

RNG_STATE_VAR = "@RNG_STATE@"


def _persistable_names(program, scope) -> List[str]:
    names = [v.name for v in program.global_block.vars.values()
             if v.persistable and v.name != RNG_STATE_VAR]
    return [n for n in names if scope.find_var(n) is not None]


def _collect_scope(scope, names) -> Dict[str, np.ndarray]:
    """Host snapshot of scope vars with overlapped device→host readback:
    kick every ``copy_to_host_async`` first, then materialize — the
    waits overlap instead of serializing (the send host op's pattern)."""
    vals = {n: scope.find_var(n) for n in names}
    for v in vals.values():
        start = getattr(v, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # pragma: no cover - committed buffers etc.
                pass
    return {n: np.asarray(v) for n, v in vals.items() if v is not None}


def save_scope(root: str, step: int, program, scope,
               writer: str = "host0",
               topology: Optional[dict] = None) -> str:
    """Synchronous single-writer checkpoint of a program's persistable
    state (the plain one-host cell of the reshard matrix).  Every var is
    written as a whole shard under its own name, so any other layout can
    re-shard from it and it can absorb any other layout's manifest."""
    arrays = _collect_scope(scope, _persistable_names(program, scope))
    topo = {"kind": "local", **(topology or {})}
    return _store.commit_single(root, step, writer, arrays, topology=topo)


def restore_scope(root: str, program, scope, step: Optional[int] = None,
                  verify: bool = True, strict: bool = True) -> int:
    """Restore a program's persistable state from the newest (or given)
    COMPLETE step, re-sharding from WHATEVER topology wrote it.  With
    ``strict`` a persistable var missing from the manifest is an error
    (a silently-uninitialized param is a wrong-answer factory); relaxed
    mode skips it.  Returns the restored step id."""
    if step is None:
        step = _store.latest_complete_step(root)
        if step is None:
            raise CheckpointError(
                f"no COMPLETE checkpoint step under {root!r}")
    man = _store.load_manifest(root, step)
    have = man.vars()
    names = [v.name for v in program.global_block.vars.values()
             if v.persistable and v.name != RNG_STATE_VAR]
    missing = [n for n in names if n not in have]
    if missing and strict:
        raise CheckpointError(
            f"checkpoint step {step} under {root!r} is missing "
            f"persistable vars {missing[:8]} (of {len(names)}); pass "
            "strict=False to restore the intersection")
    wants = {n: (None, None) for n in names if n in have}
    vals = _reshard.load_vars(root, step, wants, verify=verify)
    for n, v in vals.items():
        scope.set_var(n, v)
    return step


def scope_snapshotter(root: str, program, scope, writer: str = "host0",
                      topology: Optional[dict] = None,
                      keep: Optional[int] = None) -> AsyncSnapshotter:
    """Async no-pause snapshotter over an executor scope: call
    ``snapshot(step)`` from the training loop between steps; collect is
    one overlapped host readback, serialization/fsync/commit run on the
    background thread.  The persistable set is re-probed per snapshot —
    state that enters the scope later (lazily-created optimizer
    accumulators, a snapshotter built before startup ran) is picked up
    instead of silently missing from every committed step."""

    def collect(step):
        return _collect_scope(scope, _persistable_names(program, scope))

    return AsyncSnapshotter(root, writer, collect,
                            topology={"kind": "local", **(topology or {})},
                            expected_writers=[writer], keep=keep)


def wait_step_complete(root: str, step: int, timeout: float = 60.0,
                       poll: float = 0.05,
                       expected_writers=None) -> bool:
    """Poll (and opportunistically commit) until ``step`` is COMPLETE.
    The caller that triggered a fleet cut uses this to learn the commit
    landed before acting on it (e.g. before tearing the old fleet
    down).  Returns False on timeout — only-COMPLETE-steps semantics
    mean a False here leaves the previous checkpoint authoritative."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            if _store.try_commit(root, step, expected_writers):
                return True
        except CheckpointError:
            # a torn piece set can never commit; report timeout-style
            pass
        if time.monotonic() >= deadline:
            return False
        time.sleep(poll)


class ElasticController:
    """Resize decisions from the registry's live lease/health gauges.

    Polls the discovery registry's health table (the same one the
    fleet-health plane and the master's dead-requeue consume) and
    reports, per role, who is ALIVE — the input to a grow/shrink
    decision against a target size.  Deciding is cheap and read-only;
    *acting* is the caller's move (the ``distributed.supervisor``
    actuator, or an operator starting workers pointed at the checkpoint
    root / retiring leases), with the checkpoint plane making the
    action safe.

    ``hysteresis``: flap damping — a non-hold decision requires that
    many CONSECUTIVE same-direction observations before it fires.  A
    worker blinking SUSPECT→DEAD→HEALTHY across one missed lease term
    must not trigger a grow (and then a shrink when it reappears): one
    divergent observation resets the streak, so only a condition that
    persists across the window acts.  The default of 1 keeps the old
    immediate behavior."""

    def __init__(self, registry_ep: str, poll_ttl: float = 2.0,
                 hysteresis: int = 1):
        from ..distributed import transport as _transport
        self.registry_ep = registry_ep
        self.poll_ttl = poll_ttl
        self.hysteresis = max(1, int(hysteresis))
        self._client = _transport.RPCClient(0)
        self._cache = {"t": float("-inf"), "table": {}}
        # lease-snapshot cache for the capacity dimension (headroom
        # rides lease DATA, not the health table)
        self._snap_cache = {"t": float("-inf"), "data": {}}
        # per-role [direction, consecutive observations] streak
        self._streak: Dict[str, list] = {}

    def fleet_view(self, refresh: bool = False) -> Dict[str, dict]:
        """{worker: {state, role, ...}} from the registry health table,
        cached for ``poll_ttl``."""
        from ..distributed import registry as _registry_mod
        now = time.monotonic()
        if refresh or now - self._cache["t"] >= self.poll_ttl:
            self._cache["t"] = now
            self._cache["table"] = _registry_mod.fetch_health(
                self._client, self.registry_ep,
                connect_timeout=min(2.0, max(0.5, self.poll_ttl)))
        return self._cache["table"]

    def alive(self, role: str) -> List[str]:
        from ..observability import health as _health
        return sorted(w for w, info in self.fleet_view().items()
                      if info.get("role") == role
                      and info.get("state") != _health.DEAD)

    def slo_breaches(self, role: Optional[str] = None) -> Dict[str, list]:
        """Workers whose heartbeat ``slo`` dimension reports breach
        (observability/slo.py rides the health payload): {worker:
        [breached rule names]}.  A breach is a decision INPUT, never a
        resize by itself — :meth:`decide` reports it alongside the
        liveness-driven action so the supervisor/operator can see a
        fleet that is alive but missing its SLOs, damped by the same
        hysteresis discipline (the supervisor requires consecutive
        observations before flagging)."""
        return {w: list(info.get("slo_rules") or [])
                for w, info in self.fleet_view().items()
                if (role is None or info.get("role") == role)
                and info.get("slo") == "breach"}

    def headroom(self, role: Optional[str] = None) -> Dict[str, dict]:
        """Capacity headroom per lease, read from the registry's lease
        DATA payloads (serving/decode servers publish ``headroom_frac``
        / ``binding_phase`` / ``predicted_max_qps`` there iff
        FLAGS_capacity_attribution is on at the replica): {lease key:
        {headroom_frac, binding_phase, ...}}.  ``role`` filters by the
        announce key prefix (``SERVING`` → ``serving/``, ``DECODE`` →
        ``decode/``).  Like :meth:`slo_breaches`, this is an
        INFORMATIONAL decision input — empty when no replica publishes
        capacity (flags off fleet-wide)."""
        from ..distributed import registry as _registry_mod
        now = time.monotonic()
        # lazy init: controllers built without __init__ (test doubles
        # stubbing fleet_view) still get a working cache
        cache = getattr(self, "_snap_cache", None)
        if cache is None:
            cache = self._snap_cache = {"t": float("-inf"), "data": {}}
        if now - self._snap_cache["t"] >= self.poll_ttl:
            self._snap_cache["t"] = now
            try:
                snap = _registry_mod.fetch_snapshot(
                    self._client, self.registry_ep,
                    connect_timeout=min(2.0, max(0.5, self.poll_ttl)))
                self._snap_cache["data"] = dict(snap.get("data") or {})
            except Exception:
                pass    # registry blip: keep the last view
        prefix = {"SERVING": "serving/", "DECODE": "decode/"}.get(
            (role or "").upper())
        out = {}
        for key, data in self._snap_cache["data"].items():
            if prefix is not None and not key.startswith(prefix):
                continue
            if isinstance(data, dict) and "headroom_frac" in data:
                out[key] = {k: data[k] for k in
                            ("headroom_frac", "binding_phase",
                             "predicted_max_qps") if k in data}
        return out

    def memory_headroom(self, role: Optional[str] = None) -> Dict[str, dict]:
        """Measured memory headroom per lease, read from the same lease
        DATA payloads as :meth:`headroom` (servers publish
        ``memory_headroom_frac`` / ``memory_bytes`` there iff
        FLAGS_memory_attribution is on at the replica): {lease key:
        {memory_headroom_frac, memory_bytes, ...}}.  ``role`` filters by
        the announce key prefix like :meth:`headroom`.  INFORMATIONAL —
        empty when no replica publishes memory (flags off fleet-wide)."""
        # reuse headroom()'s snapshot cache discipline (one registry
        # poll feeds both planes)
        self.headroom(role)
        prefix = {"SERVING": "serving/", "DECODE": "decode/"}.get(
            (role or "").upper())
        out = {}
        for key, data in self._snap_cache["data"].items():
            if prefix is not None and not key.startswith(prefix):
                continue
            if isinstance(data, dict) and "memory_headroom_frac" in data:
                out[key] = {k: data[k] for k in
                            ("memory_headroom_frac", "memory_bytes",
                             "memory_parked_bytes", "memory_leak")
                            if k in data}
        return out

    def decide(self, role: str, target: int) -> dict:
        """Grow/shrink recommendation for ``role`` against ``target``
        live workers: {"action": "grow"|"shrink"|"hold", "delta": n,
        "alive": [...], "raw": the undamped direction, "streak": how
        many consecutive observations agreed, "needed": hysteresis}.
        Each call is one observation; ``action`` stays "hold" until
        ``hysteresis`` consecutive calls agree on a direction."""
        alive = self.alive(role)
        obs_t = self._cache["t"]
        n = len(alive)
        raw = "hold" if n == target else ("grow" if n < target
                                          else "shrink")
        if raw == "hold":
            self._streak.pop(role, None)
            streak = 0
        else:
            st = self._streak.get(role)
            if st is not None and st[0] == raw:
                # a repeated decide against the SAME cached table is the
                # same observation — only a fresh poll extends the streak
                if obs_t != st[2]:
                    st[1] += 1
                    st[2] = obs_t
            else:
                st = [raw, 1, obs_t]
                self._streak[role] = st
            streak = st[1]
        action = raw if streak >= self.hysteresis else "hold"
        out = {"action": action, "raw": raw, "streak": streak,
               "needed": self.hysteresis, "delta": abs(target - n),
               "alive": alive, "target": target}
        # SLO breach state rides the same (cached) fleet view as an
        # INFORMATIONAL dimension: it never changes `action` here —
        # liveness decides counts; usefulness is the supervisor's /
        # operator's damped signal (decisions stay HOLD-safe)
        breaches = self.slo_breaches(role)
        if breaches:
            out["slo_breaches"] = breaches
        # capacity headroom is the same HOLD-safe discipline: it rides
        # the decision as `capacity`, never changes `action` (the
        # direct input for a future saturation-driven grow — item 4(a)
        # — without automating it here), and is absent when no replica
        # publishes it (flags off ⇒ byte-identical decisions)
        cap = self.headroom(role)
        if cap:
            out["capacity"] = cap
        # measured memory headroom rides the same way: HOLD-safe,
        # informational, absent when no replica publishes it
        mem = self.memory_headroom(role)
        if mem:
            out["memory"] = mem
        return out
