"""Async snapshotter: checkpoints that never pause the step loop.

Phase split:

- **collect** (caller thread): ``collect_fn(step)`` returns the host
  arrays to persist.  Callers keep this cheap-and-coherent — the
  pserver's collector snapshots under the writer-block locks and kicks
  ``copy_to_host_async`` on every device value before materializing
  (PR 10 ``_read_var`` coherence + overlapped readback), an executor
  collector just reads the scope between steps.  This is the ONLY part
  the training thread pays for.
- **serialize + fsync + commit** (background thread): the npz
  serialization, digesting, fsync and two-phase commit all run off the
  step loop.  While a snapshot is in flight a new request is *skipped*
  (counted), never queued — checkpointing degrades to a lower cadence
  under pressure instead of stalling training.

Observability: ``checkpoint.{snapshots,skipped_inflight,bytes,commits,
faults}`` counters, ``checkpoint.inflight`` / ``checkpoint.save_ms`` /
``checkpoint.collect_ms`` / ``checkpoint.last_step`` gauges, a
``checkpoint`` /statusz provider listing every live snapshotter, and a
flight-recorder note per fault class (collect / write / commit).
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from ..observability import debug_server as _debug_server
from ..observability import flight as _flight
from ..observability import stats as _obs_stats
from ..observability.trace import flags_on as _telemetry_on
from . import store as _store

__all__ = ["AsyncSnapshotter"]

_ckpt_metrics = None
_live: "weakref.WeakSet" = weakref.WeakSet()


def _cm():
    global _ckpt_metrics
    m = _ckpt_metrics
    if m is None:
        import types as _t
        sc = _obs_stats.scope("checkpoint")
        m = _t.SimpleNamespace(
            snapshots=sc.counter(
                "snapshots", "async snapshots accepted (collect started)"),
            skipped=sc.counter(
                "skipped_inflight",
                "snapshot requests skipped because a previous snapshot "
                "was still writing (cadence degraded, loop never blocked)"),
            bytes=sc.counter("bytes", "checkpoint bytes written to disk"),
            commits=sc.counter(
                "commits", "two-phase commits this process completed"),
            faults=sc.counter(
                "faults", "checkpoint faults by any class (collect/"
                "write/commit); each leaves a flight note"),
            inflight=sc.gauge(
                "inflight", "async snapshot writes currently in flight"),
            save_ms=sc.gauge(
                "save_ms", "background serialize+fsync+commit wall of "
                "the last snapshot (off the step loop)"),
            collect_ms=sc.gauge(
                "collect_ms", "caller-thread collect wall of the last "
                "snapshot (the ONLY step-loop cost)"),
            last_step=sc.gauge("last_step", "last committed/written step"),
        )
        _ckpt_metrics = m
    return m


def _statusz() -> dict:
    return {"snapshotters": [s.status() for s in list(_live)]}


_debug_server.register_provider("checkpoint", _statusz)


def _mem_pool_snapshot() -> dict:
    """Host bytes pinned by in-flight snapshot buffers, summed over
    every live snapshotter (memory anatomy ledger callback)."""
    snaps = list(_live)
    used = sum(s._inflight_bytes for s in snaps)
    return {"used": used,
            "inflight_writers": sum(1 for s in snaps
                                    if s._inflight_bytes)}


def _register_memory_pool() -> None:
    from ..observability import memory as _memory
    if _memory.enabled():
        _memory.pool("checkpoint_staging", "host", _mem_pool_snapshot)


class AsyncSnapshotter:
    """Write sharded checkpoint pieces off the step loop.

    ``collect_fn(step) -> {local_name: host array}`` runs on the CALLER
    thread (keep it lock-coherent and cheap); everything else runs on a
    single background thread per snapshotter.  ``extents`` maps local
    names to manifest extents (see store.write_piece); ``keep`` prunes
    old COMPLETE steps after each commit this process wins."""

    def __init__(self, root: str, writer: str,
                 collect_fn: Callable[[int], Dict[str, np.ndarray]],
                 extents: Optional[Dict[str, dict]] = None,
                 topology: Optional[dict] = None,
                 expected_writers: Optional[Sequence[str]] = None,
                 keep: Optional[int] = None):
        self.root = root
        self.writer = writer
        self.collect_fn = collect_fn
        self.extents = extents
        self.topology = topology
        self.expected_writers = (sorted(expected_writers)
                                 if expected_writers else None)
        self.keep = keep
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._last = {"step": None, "save_ms": None, "collect_ms": None,
                      "bytes": 0, "committed": False, "fault": None}
        self.faults = 0
        self.snapshots = 0
        self.skipped = 0
        self._inflight_bytes = 0   # host bytes pinned by an in-flight write
        _live.add(self)
        _register_memory_pool()

    # -- public -----------------------------------------------------------
    def snapshot(self, step: int, wait: bool = False) -> bool:
        """Request a snapshot of ``step``.  Returns False (counted) when
        a previous snapshot is still in flight — never blocks the caller
        on serialization.  ``wait=True`` joins the write (tests,
        shutdown barriers)."""
        collect_exc = None
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                self.skipped += 1
                if _telemetry_on():
                    _cm().skipped.inc()
                return False
            t0 = time.perf_counter()
            try:
                arrays = self.collect_fn(step)
            except Exception as e:
                # _fault re-takes the (non-reentrant) lock — record the
                # exception and handle it OUTSIDE the with-block
                collect_exc = e
            else:
                collect_ms = (time.perf_counter() - t0) * 1e3
                self._last["collect_ms"] = round(collect_ms, 3)
                if _telemetry_on():
                    _cm().snapshots.inc()
                    _cm().collect_ms.set(collect_ms)
                    _cm().inflight.set(1)
                self.snapshots += 1
                self._inflight_bytes = sum(
                    int(np.asarray(a).nbytes) for a in arrays.values())
                from ..observability import memory as _memory
                _memory.note_event("alloc", "checkpoint_staging",
                                   self._inflight_bytes, step=step)
                t = threading.Thread(target=self._write,
                                     args=(step, arrays), daemon=True,
                                     name=f"ckpt-{self.writer}")
                self._thread = t
                t.start()
        if collect_exc is not None:
            self._fault("collect", step, collect_exc)
            return False
        if wait:
            t.join()
        return True

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Join any in-flight write (shutdown path).  True when idle."""
        with self._lock:
            t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
            return not t.is_alive()
        return True

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain (bounded when ``timeout`` given) and unregister.  A
        write wedged past the timeout (dead mount mid-fsync) is
        abandoned to its daemon thread rather than hanging shutdown —
        an uncommitted piece is exactly what the two-phase commit
        tolerates."""
        self.flush(timeout)
        _live.discard(self)

    def status(self) -> dict:
        with self._lock:
            inflight = self._thread is not None and self._thread.is_alive()
            d = dict(self._last)
        d.update({"root": self.root, "writer": self.writer,
                  "inflight": inflight, "snapshots": self.snapshots,
                  "skipped_inflight": self.skipped, "faults": self.faults})
        return d

    # -- background -------------------------------------------------------
    def _write(self, step: int, arrays: Dict[str, np.ndarray]) -> None:
        from ..distributed import faults as _faults
        t0 = time.perf_counter()
        try:
            # chaos hook: kill_after:ckpt_piece dies HERE, mid-snapshot —
            # the two-phase commit must leave only COMPLETE steps behind
            _faults.event("ckpt_piece")
            _store.write_piece(
                self.root, step, self.writer, arrays,
                extents=self.extents, topology=self.topology,
                expected_writers=self.expected_writers)
            nbytes = sum(int(np.asarray(a).nbytes)
                         for a in arrays.values())
        except Exception as e:
            self._fault("write", step, e)
            return
        finally:
            if _telemetry_on():
                _cm().inflight.set(0)
            if self._inflight_bytes:
                from ..observability import memory as _memory
                _memory.note_event("free", "checkpoint_staging",
                                   self._inflight_bytes, step=step)
                self._inflight_bytes = 0
        committed = False
        try:
            committed = _store.try_commit(self.root, step,
                                          self.expected_writers)
        except Exception as e:
            self._fault("commit", step, e)
            return
        save_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._last.update({"step": step, "bytes": nbytes,
                               "save_ms": round(save_ms, 3),
                               "committed": committed, "fault": None})
        if _telemetry_on():
            m = _cm()
            m.bytes.inc(nbytes)
            m.save_ms.set(save_ms)
            m.last_step.set(step)
            if committed:
                m.commits.inc()
        if committed and self.keep:
            try:
                _store.prune(self.root, keep=self.keep)
            except Exception as e:   # retention is best-effort
                _flight.note("ckpt_prune_failed", root=self.root,
                             error=repr(e)[:200])

    def _fault(self, phase: str, step: int, e: Exception) -> None:
        self.faults += 1
        with self._lock:
            self._last["fault"] = f"{phase}: {e!r}"[:200]
        if _telemetry_on():
            _cm().faults.inc()
            _cm().inflight.set(0)
        _flight.note("ckpt_fault", phase=phase, step=step,
                     writer=self.writer, root=self.root,
                     error=repr(e)[:200])
