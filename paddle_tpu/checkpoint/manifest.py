"""Checkpoint manifests: the topology-independent shard catalog.

A checkpoint step is a set of *shard files* (one ``.npz`` per writer)
plus a ``MANIFEST.json`` describing, for every variable,

- its GLOBAL identity: name, global shape, dtype — independent of how
  any particular fleet sliced it;
- the shards covering it: which file, which npz key, which contiguous
  dim-0 row range ``[offset, offset + rows)`` of the global array, and a
  content digest;
- the topology that WROTE it (#pservers, dp/pp/ZeRO layout, sync mode) —
  recorded for operators and debuggers, never *required* by restore:
  the whole point is that restore plans reads from extents alone, so a
  checkpoint written under any layout re-shards onto any other
  (the DeepSpeed universal-checkpoint / Orbax discipline).

Replicated variables (LR schedule state, per-section scalar optimizer
accumulators like ``beta1_pow`` — values identical on every writer by
construction) carry ``offset = None``; every writer may record its
copy and restore reads any one of them.

Writers each produce a *manifest piece* (``manifest-<writer>.json``,
same schema, only their own shards); the committer merges pieces into
the final ``MANIFEST.json`` (store.py owns the two-phase commit).
"""
from __future__ import annotations

import json
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["FORMAT_VERSION", "Manifest", "array_digest", "merge_pieces",
           "shard_entry"]

FORMAT_VERSION = 1


def array_digest(arr: np.ndarray) -> str:
    """Content digest of one shard array (crc32 over the raw C-order
    bytes, prefixed so the algorithm can evolve)."""
    arr = np.ascontiguousarray(arr)
    return "crc32:%08x" % (zlib.crc32(arr.tobytes()) & 0xFFFFFFFF)


def file_digest(data: bytes) -> str:
    """Digest of a whole shard FILE — verifiable with stdlib alone
    (tools/ckpt_admin.py runs on hosts without numpy)."""
    return "crc32:%08x" % (zlib.crc32(data) & 0xFFFFFFFF)


def shard_entry(var: str, key: str, file: str, writer: str,
                shape: Sequence[int], dtype: str, digest: str,
                offset: Optional[int] = None,
                global_shape: Optional[Sequence[int]] = None) -> dict:
    """One shard record.  ``offset=None`` marks a replicated copy (any
    writer's copy restores the var); otherwise the shard covers global
    rows ``[offset, offset + shape[0])``."""
    return {
        "var": var, "key": key, "file": file, "writer": writer,
        "shape": [int(s) for s in shape], "dtype": str(dtype),
        "digest": digest,
        "offset": None if offset is None else int(offset),
        "global_shape": [int(s) for s in (global_shape
                                          if global_shape is not None
                                          else shape)],
    }


class Manifest:
    """In-memory view of a (piece or merged) manifest."""

    def __init__(self, step: int, topology: Optional[dict] = None,
                 writers: Optional[List[str]] = None,
                 shards: Optional[List[dict]] = None,
                 files: Optional[Dict[str, dict]] = None,
                 expected_writers: Optional[List[str]] = None):
        self.step = int(step)
        self.topology = dict(topology or {})
        self.writers = list(writers or [])
        self.shards = list(shards or [])
        self.files = dict(files or {})
        # recorded by each piece so a committer (or an admin tool) can
        # tell a complete piece set from a partial one without any
        # out-of-band coordination
        self.expected_writers = (list(expected_writers)
                                 if expected_writers is not None else None)

    # -- var catalog -------------------------------------------------------
    def vars(self) -> Dict[str, dict]:
        """{var: {"global_shape", "dtype", "replicated"}} derived from
        the shard list (the shards are the source of truth; a derived
        catalog cannot drift from them)."""
        out: Dict[str, dict] = {}
        for s in self.shards:
            ent = out.setdefault(s["var"], {
                "global_shape": list(s["global_shape"]),
                "dtype": s["dtype"],
                "replicated": s["offset"] is None,
            })
            if list(s["global_shape"]) != ent["global_shape"] \
                    or s["dtype"] != ent["dtype"]:
                raise ValueError(
                    f"manifest inconsistency for var {s['var']!r}: shard "
                    f"{s['key']!r} declares global shape "
                    f"{s['global_shape']}/{s['dtype']} but another shard "
                    f"declared {ent['global_shape']}/{ent['dtype']}")
        return out

    def shards_of(self, var: str) -> List[dict]:
        return [s for s in self.shards if s["var"] == var]

    def nbytes(self) -> int:
        return sum(int(f.get("nbytes", 0)) for f in self.files.values())

    # -- (de)serialization -------------------------------------------------
    def to_dict(self) -> dict:
        d = {
            "format_version": FORMAT_VERSION,
            "step": self.step,
            "topology": self.topology,
            "writers": self.writers,
            "shards": self.shards,
            "files": self.files,
        }
        if self.expected_writers is not None:
            d["expected_writers"] = self.expected_writers
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Manifest":
        ver = int(d.get("format_version", 0))
        if ver > FORMAT_VERSION:
            raise ValueError(
                f"checkpoint manifest format {ver} is newer than this "
                f"build understands ({FORMAT_VERSION}); upgrade before "
                "restoring")
        return cls(step=d["step"], topology=d.get("topology"),
                   writers=d.get("writers"), shards=d.get("shards"),
                   files=d.get("files"),
                   expected_writers=d.get("expected_writers"))

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def loads(cls, text: str) -> "Manifest":
        return cls.from_dict(json.loads(text))


def merge_pieces(pieces: List[Manifest]) -> Manifest:
    """Merge per-writer manifest pieces into the final step manifest.
    Validates step agreement and cross-writer var consistency (a var
    two writers disagree on — shape or dtype — is a torn checkpoint
    and must fail the COMMIT, not a later restore)."""
    if not pieces:
        raise ValueError("no manifest pieces to merge")
    step = pieces[0].step
    merged = Manifest(step, topology=pieces[0].topology)
    seen_writers = set()
    for p in pieces:
        if p.step != step:
            raise ValueError(
                f"manifest pieces disagree on step: {p.step} vs {step}")
        for w in p.writers:
            if w in seen_writers:
                raise ValueError(f"duplicate manifest piece for writer "
                                 f"{w!r} at step {step}")
            seen_writers.add(w)
        merged.writers.extend(p.writers)
        merged.shards.extend(p.shards)
        merged.files.update(p.files)
        if p.expected_writers:
            merged.expected_writers = list(p.expected_writers)
    merged.writers.sort()
    merged.vars()    # consistency check across writers
    return merged
