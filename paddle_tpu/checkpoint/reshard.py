"""Restore planner: re-shard a committed checkpoint onto ANY layout.

Restore never cares what fleet wrote a checkpoint.  A reader declares
*wants* — for each variable, either the whole global array or a
contiguous dim-0 row range — and the planner maps each want onto the
manifest's shard extents, reads only the shard files it needs (each
file opened once per restore), slices, and reassembles.  That is the
whole topology-independence contract: N pservers → M pservers (both
directions), ZeRO on ↔ off, pipeline stages → one host, all reduce to
the same row-range arithmetic.

Every failure names its variable and rows: a coverage gap (the written
shards do not cover a wanted range) or an overlap disagreement is a
torn/foreign checkpoint and must be loud, never a silent zero-fill.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import store as _store
from .manifest import Manifest, array_digest
from .store import CheckpointError

__all__ = ["plan_reads", "load_vars", "load_locals"]


def plan_reads(man: Manifest, var: str,
               offset: Optional[int], rows: Optional[int]) -> List[dict]:
    """Shard reads covering ``var`` rows ``[offset, offset+rows)`` —
    or any one replicated copy when the manifest's shards for it are
    replicated.  Returns ``[{"shard", "lo", "hi"}]`` with lo/hi local
    to the shard array.  Raises CheckpointError on unknown vars and
    coverage gaps."""
    shards = man.shards_of(var)
    if not shards:
        raise CheckpointError(
            f"checkpoint step {man.step} has no variable {var!r} "
            f"(has: {sorted(man.vars())[:20]}...)")
    replicated = [s for s in shards if s["offset"] is None]
    if replicated:
        return [{"shard": replicated[0], "lo": 0,
                 "hi": replicated[0]["shape"][0]
                 if replicated[0]["shape"] else 0}]
    gshape = shards[0]["global_shape"]
    if not gshape:
        # 0-d var: only whole-array shards exist; any copy restores it
        return [{"shard": shards[0], "lo": 0, "hi": 0}]
    total = int(gshape[0])
    if offset is None:
        offset, rows = 0, total
    if rows is None:
        rows = total - offset
    if offset < 0 or rows < 0 or offset + rows > total:
        raise CheckpointError(
            f"restore of {var!r} wants rows [{offset}, {offset + rows}) "
            f"outside the global shape {gshape}")
    ordered = sorted(shards, key=lambda s: s["offset"])
    # overlap disagreements are LOUD: two dense shards claiming the
    # same rows means two writers disagreed about ownership (a torn or
    # misconfigured save) — restore must refuse, never silently pick
    # whichever sorts first.  (Replicated copies are the sanctioned
    # duplication mechanism and were handled above.)
    prev = None
    for s in ordered:
        if prev is not None and s["offset"] < prev["offset"] + \
                prev["shape"][0]:
            raise CheckpointError(
                f"restore of {var!r}: shards {prev['key']!r} (writer "
                f"{prev['writer']}) and {s['key']!r} (writer "
                f"{s['writer']}) overlap on rows — ambiguous "
                "checkpoint, refusing to restore")
        prev = s
    want_lo, want_hi = offset, offset + rows
    plan, cover = [], want_lo
    for s in ordered:
        s_lo, s_hi = s["offset"], s["offset"] + s["shape"][0]
        if s_hi <= cover or s_lo >= want_hi:
            continue
        if s_lo > cover:
            raise CheckpointError(
                f"restore of {var!r}: rows [{cover}, {s_lo}) are covered "
                f"by no shard (writers {man.writers}) — torn or "
                "incompatible checkpoint")
        lo = max(cover, s_lo)
        plan.append({"shard": s, "lo": lo - s_lo,
                     "hi": min(want_hi, s_hi) - s_lo})
        cover = min(want_hi, s_hi)
        if cover >= want_hi:
            break
    if cover < want_hi:
        raise CheckpointError(
            f"restore of {var!r}: rows [{cover}, {want_hi}) are covered "
            f"by no shard (writers {man.writers})")
    return plan


def _gather(man: Manifest, sdir: str, wants: List[Tuple[str, dict]],
            verify: bool) -> Dict[str, np.ndarray]:
    """Execute planned reads for ``wants = [(out_name, want), ...]``
    where want = {"var", "offset", "rows"}.  Opens each shard file once
    and digest-verifies each USED shard array once."""
    catalog = man.vars()
    plans: Dict[str, List[dict]] = {}
    need_files: Dict[str, List[str]] = {}
    for out_name, w in wants:
        plan = plan_reads(man, w["var"], w.get("offset"), w.get("rows"))
        plans[out_name] = plan
        for p in plan:
            need_files.setdefault(p["shard"]["file"], []).append(out_name)

    loaded: Dict[Tuple[str, str], np.ndarray] = {}
    verified = set()
    for fn in sorted(need_files):
        path = os.path.join(sdir, fn)
        try:
            data = np.load(path)
        except FileNotFoundError:
            raise CheckpointError(
                f"checkpoint shard file {path!r} named by the manifest "
                "is missing")
        except Exception as e:
            raise CheckpointError(
                f"checkpoint shard file {path!r} is unreadable/corrupt: "
                f"{e!r}")
        with data:
            keys_needed = {p["shard"]["key"]
                           for out_name in set(need_files[fn])
                           for p in plans[out_name]
                           if p["shard"]["file"] == fn}
            for key in sorted(keys_needed):
                if key not in data.files:
                    raise CheckpointError(
                        f"shard key {key!r} missing from {path!r}")
                arr = data[key]
                shard = next(p["shard"] for ps in plans.values()
                             for p in ps if p["shard"]["key"] == key
                             and p["shard"]["file"] == fn)
                if verify and (fn, key) not in verified:
                    if array_digest(arr) != shard["digest"]:
                        raise CheckpointError(
                            f"var {shard['var']!r} shard {key!r} in "
                            f"{path!r} fails its content digest — "
                            "refusing to restore corrupt state")
                    verified.add((fn, key))
                loaded[(fn, key)] = arr

    out: Dict[str, np.ndarray] = {}
    for out_name, w in wants:
        plan = plans[out_name]
        info = catalog[w["var"]]
        first = loaded[(plan[0]["shard"]["file"], plan[0]["shard"]["key"])]
        if plan[0]["shard"]["offset"] is None or first.ndim == 0:
            # replicated (any copy) or 0-d (whole-array shards only).
            # A DENSE want against a replicated shard still gets only
            # its rows — a reader's extent table must not care whether
            # the writer stored the var sharded or replicated
            arr = np.array(first)
            off, rows = w.get("offset"), w.get("rows")
            if off is not None and arr.ndim >= 1:
                hi = arr.shape[0] if rows is None else off + rows
                if off < 0 or hi > arr.shape[0]:
                    raise CheckpointError(
                        f"restore of {w['var']!r}: rows [{off}, {hi}) "
                        f"outside the replicated copy's shape "
                        f"{arr.shape}")
                arr = arr[off:hi]
            out[out_name] = arr
            continue
        parts = [loaded[(p["shard"]["file"], p["shard"]["key"])]
                 [p["lo"]:p["hi"]] for p in plan]
        arr = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
        out[out_name] = np.array(arr, dtype=info["dtype"], copy=True)
    return out


def load_vars(root: str, step: Optional[int] = None,
              wants: Optional[Dict[str, Tuple[Optional[int],
                                              Optional[int]]]] = None,
              verify: bool = True) -> Dict[str, np.ndarray]:
    """Load global variables from the newest (or given) COMPLETE step.

    ``wants`` maps var → ``(offset, rows)`` (``(None, None)`` or absent
    map = full arrays for every var in the manifest).  Returns
    {var: np.ndarray} keyed by GLOBAL names."""
    if step is None:
        step = _store.latest_complete_step(root)
        if step is None:
            raise CheckpointError(
                f"no COMPLETE checkpoint step under {root!r}")
    man = _store.load_manifest(root, step)
    if wants is None:
        wants = {v: (None, None) for v in man.vars()}
    pairs = [(name, {"var": name, "offset": off, "rows": rows})
             for name, (off, rows) in sorted(wants.items())]
    return _gather(man, _store.step_dir(root, step), pairs, verify)


def load_locals(root: str, step: Optional[int],
                wants: Dict[str, dict],
                verify: bool = True) -> Dict[str, np.ndarray]:
    """Load LOCAL-named slices: ``wants`` maps each local (layout-
    specific) name to ``{"var": global, "offset": int|None, "rows":
    int|None}`` — the restore side of a shard-extent table (e.g. a
    pserver hydrating its sections from any writer topology).  Returns
    {local_name: np.ndarray}."""
    if step is None:
        step = _store.latest_complete_step(root)
        if step is None:
            raise CheckpointError(
                f"no COMPLETE checkpoint step under {root!r}")
    man = _store.load_manifest(root, step)
    return _gather(man, _store.step_dir(root, step),
                   sorted(wants.items()), verify)
