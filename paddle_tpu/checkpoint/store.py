"""On-disk checkpoint store: two-phase commit over step directories.

Layout under a checkpoint root::

    root/
      _tmp/step_00000012/        # phase 1: every writer lands here
        shard-ps0.npz            # writer ps0's shard arrays
        manifest-ps0.json        # writer ps0's manifest piece
        MANIFEST.json            # written by the committer, atomically
      step_00000012/             # phase 2: ONE atomic directory rename
        ...                      # (the commit marker IS the final name)

Phase 1: each writer serializes its shards + manifest piece into the
SHARED in-flight directory ``_tmp/step_<N>/`` — every individual file
lands via unique-tmp + ``os.replace`` + fsync, so a torn write can
never masquerade as a complete piece.  Phase 2: once every expected
writer's piece is present, any caller's :func:`try_commit` merges the
pieces into ``MANIFEST.json`` (atomic) and renames the whole directory
to its final ``step_<N>`` name — one atomic rename.  A crash at ANY
point before the rename leaves only ``_tmp`` residue, which restore
never reads: a half-checkpoint is unrestorable by construction, and
:func:`latest_complete_step` always resolves to the newest COMPLETE
step.  Concurrent committers are safe: the merge is deterministic, the
manifest write is last-wins-identical, and the rename race resolves to
"the final directory exists" for everyone.
"""
from __future__ import annotations

import json
import os
import re
import uuid
from io import BytesIO
from typing import Dict, List, Optional, Sequence

import numpy as np

from .manifest import (Manifest, array_digest, file_digest, merge_pieces,
                       shard_entry)

__all__ = ["CheckpointError", "atomic_file_write", "write_piece",
           "try_commit", "commit_single", "complete_steps",
           "inflight_steps", "latest_complete_step", "load_manifest",
           "step_dir", "prune", "verify_step"]

STEP_RE = re.compile(r"^step_(\d{8})$")
TMP_SUBDIR = "_tmp"
MANIFEST_NAME = "MANIFEST.json"


class CheckpointError(RuntimeError):
    """A checkpoint could not be written, committed or read; the message
    always names the file/step/var at fault."""


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{int(step):08d}")


def _tmp_step_dir(root: str, step: int) -> str:
    return os.path.join(root, TMP_SUBDIR, f"step_{int(step):08d}")


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_file_write(path: str, write_fn) -> None:
    """THE atomic-write discipline: unique-tmp + fsync + os.replace —
    a reader can never observe a half-written file under the final
    name, and the tmp is reaped on a failed write (an orphan here would
    ride a commit rename into a final step directory forever).
    ``write_fn(f)`` writes to the open binary file.  Shared with io.py's
    save paths so the crash-safety invariant has one implementation.

    Carries the chaos suite's ``ckpt_write`` fault-injection site: a
    ``diskfull``/``io_err`` rule raises the corresponding ``OSError``
    here — exactly where a real ENOSPC/EIO would surface — so the
    write-path error handling (snapshotter fault accounting, the
    previous COMPLETE step staying authoritative) is exercised against
    the real failure path."""
    from ..distributed import faults as _faults
    _faults.io_fault("ckpt_write")
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _atomic_write(path: str, data: bytes) -> None:
    atomic_file_write(path, lambda f: f.write(data))


# ---------------------------------------------------------------------------
# phase 1: writers
# ---------------------------------------------------------------------------

def write_piece(root: str, step: int, writer: str,
                arrays: Dict[str, np.ndarray],
                extents: Optional[Dict[str, dict]] = None,
                topology: Optional[dict] = None,
                expected_writers: Optional[Sequence[str]] = None) -> str:
    """Write one writer's shard file + manifest piece into the in-flight
    step directory.  ``extents`` maps each array's LOCAL name to
    ``{"var": global name, "offset": int or None (replicated),
    "global_shape": [...]}``; names absent from ``extents`` are whole
    vars owned by this writer (offset 0, global shape = own shape).

    Returns the in-flight directory path.  Never commits — call
    :func:`try_commit` (any process sharing the filesystem may)."""
    tmp_dir = _tmp_step_dir(root, step)
    os.makedirs(tmp_dir, exist_ok=True)
    extents = extents or {}

    shard_file = f"shard-{writer}.npz"
    shards: List[dict] = []
    npz: Dict[str, np.ndarray] = {}
    for local_name in sorted(arrays):
        arr = np.asarray(arrays[local_name])
        ext = extents.get(local_name)
        var = ext["var"] if ext else local_name
        offset = ext.get("offset") if ext else 0
        gshape = (ext.get("global_shape") if ext else None) or arr.shape
        key = f"{var}@@{'rep' if offset is None else int(offset)}"
        if key in npz:
            if offset is None:
                # two local names replicating the same global var (e.g.
                # per-section scalar accumulators of one param on one
                # pserver): identical by construction, keep the first
                continue
            raise CheckpointError(
                f"writer {writer!r} produced two shards with identical "
                f"extent for var {var!r} (local names collide on "
                f"key {key!r})")
        if offset is not None:
            bad = (tuple(gshape) != tuple(arr.shape) or offset != 0) \
                if arr.ndim == 0 else (
                    tuple(gshape[1:]) != tuple(arr.shape[1:])
                    or offset + arr.shape[0] > int(gshape[0]))
            if bad:
                raise CheckpointError(
                    f"shard of {var!r} (local {local_name!r}) shape "
                    f"{arr.shape} at offset {offset} does not fit global "
                    f"shape {list(gshape)}")
        npz[key] = arr
        shards.append(shard_entry(
            var=var, key=key, file=shard_file, writer=writer,
            shape=arr.shape, dtype=str(arr.dtype),
            digest=array_digest(arr), offset=offset, global_shape=gshape))

    buf = BytesIO()
    np.savez(buf, **npz)
    data = buf.getvalue()
    _atomic_write(os.path.join(tmp_dir, shard_file), data)

    piece = Manifest(step, topology=topology, writers=[writer],
                     shards=shards,
                     files={shard_file: {"digest": file_digest(data),
                                         "nbytes": len(data),
                                         "writer": writer}},
                     expected_writers=(sorted(expected_writers)
                                       if expected_writers else None))
    _atomic_write(os.path.join(tmp_dir, f"manifest-{writer}.json"),
                  piece.dumps().encode("utf-8"))
    _fsync_dir(tmp_dir)
    return tmp_dir


# ---------------------------------------------------------------------------
# phase 2: commit
# ---------------------------------------------------------------------------

def _read_pieces(tmp_dir: str) -> List[Manifest]:
    pieces = []
    for fn in sorted(os.listdir(tmp_dir)):
        if fn.startswith("manifest-") and fn.endswith(".json"):
            with open(os.path.join(tmp_dir, fn), encoding="utf-8") as f:
                pieces.append(Manifest.loads(f.read()))
    return pieces


def try_commit(root: str, step: int,
               expected_writers: Optional[Sequence[str]] = None) -> bool:
    """Commit step ``step`` if every expected writer's piece is present.

    ``expected_writers=None`` uses the writer set recorded inside the
    pieces themselves (``expected_writers`` stamped by write_piece), or
    commits whatever pieces exist when nothing recorded one.  Returns
    True when the step is COMPLETE on return (committed now or already),
    False when pieces are still missing.  Safe to call from every
    writer and from pollers: idempotent, concurrent-committer safe."""
    final = step_dir(root, step)
    if os.path.isdir(final):
        return True
    tmp_dir = _tmp_step_dir(root, step)
    if not os.path.isdir(tmp_dir):
        return False
    try:
        pieces = _read_pieces(tmp_dir)
        if not pieces:
            return False
        have = {w for p in pieces for w in p.writers}
        expect = (set(expected_writers) if expected_writers is not None
                  else None)
        if expect is None:
            for p in pieces:
                if p.expected_writers:
                    expect = set(p.expected_writers)
                    break
        if expect is not None and not expect <= have:
            return False
        merged = merge_pieces(pieces)
        _atomic_write(os.path.join(tmp_dir, MANIFEST_NAME),
                      merged.dumps().encode("utf-8"))
        _fsync_dir(tmp_dir)
    except (FileNotFoundError, NotADirectoryError):
        # a racing committer renamed tmp_dir away mid-read/mid-write:
        # complete if the final directory landed, else genuinely gone
        return os.path.isdir(final)
    except ValueError as e:
        # torn/foreign piece set (step disagreement, duplicate writer,
        # cross-writer var inconsistency): normalize to the store's
        # error type so every caller handles ONE exception class
        raise CheckpointError(
            f"step {step} piece set under {root!r} cannot commit: {e}")
    try:
        os.rename(tmp_dir, final)
    except OSError:
        # a racing committer won the rename (src gone / dst exists):
        # complete either way, or genuinely failed — re-check
        if not os.path.isdir(final):
            raise
    _fsync_dir(root)
    return True


def commit_single(root: str, step: int, writer: str,
                  arrays: Dict[str, np.ndarray],
                  extents: Optional[Dict[str, dict]] = None,
                  topology: Optional[dict] = None) -> str:
    """Single-writer convenience: write + commit in one call (the plain
    one-host checkpoint).  Returns the committed step directory."""
    write_piece(root, step, writer, arrays, extents=extents,
                topology=topology, expected_writers=[writer])
    if not try_commit(root, step, expected_writers=[writer]):
        raise CheckpointError(
            f"single-writer commit of step {step} under {root!r} did not "
            "complete (piece missing after write)")
    return step_dir(root, step)


# ---------------------------------------------------------------------------
# discovery / maintenance
# ---------------------------------------------------------------------------

def complete_steps(root: str) -> List[int]:
    """COMPLETE step ids under ``root``, ascending.  Only directories
    that went through the atomic commit rename (and so contain a merged
    MANIFEST.json) qualify — in-flight ``_tmp`` residue never does."""
    if not os.path.isdir(root):
        return []
    out = []
    for fn in os.listdir(root):
        m = STEP_RE.match(fn)
        if m and os.path.isfile(os.path.join(root, fn, MANIFEST_NAME)):
            out.append(int(m.group(1)))
    return sorted(out)


def inflight_steps(root: str) -> List[int]:
    """Step ids with UNCOMMITTED residue under ``_tmp`` (crashed or
    still-writing snapshots)."""
    tmp = os.path.join(root, TMP_SUBDIR)
    if not os.path.isdir(tmp):
        return []
    out = []
    for fn in os.listdir(tmp):
        m = STEP_RE.match(fn)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def latest_complete_step(root: str) -> Optional[int]:
    steps = complete_steps(root)
    return steps[-1] if steps else None


def load_manifest(root: str, step: int) -> Manifest:
    path = os.path.join(step_dir(root, step), MANIFEST_NAME)
    try:
        with open(path, encoding="utf-8") as f:
            return Manifest.loads(f.read())
    except FileNotFoundError:
        raise CheckpointError(
            f"no COMPLETE checkpoint step {step} under {root!r} "
            f"(missing {path}); complete steps: {complete_steps(root)}")
    except (ValueError, KeyError) as e:
        raise CheckpointError(
            f"corrupt checkpoint manifest {path!r}: {e}")


def prune(root: str, keep: int, reap_inflight: bool = False) -> dict:
    """Delete the oldest COMPLETE steps beyond the newest ``keep``
    (never the newest), optionally reaping in-flight ``_tmp`` residue.
    Returns {"removed_steps": [...], "reaped_inflight": [...]}."""
    import shutil
    if keep < 1:
        raise ValueError("prune keep must be >= 1")
    steps = complete_steps(root)
    doomed = steps[:-keep] if len(steps) > keep else []
    for s in doomed:
        shutil.rmtree(step_dir(root, s), ignore_errors=True)
    reaped = []
    if reap_inflight:
        for s in inflight_steps(root):
            shutil.rmtree(_tmp_step_dir(root, s), ignore_errors=True)
            reaped.append(s)
    return {"removed_steps": doomed, "reaped_inflight": reaped}


def verify_step(root: str, step: int, deep: bool = True) -> dict:
    """Digest-verify one COMPLETE step: every shard file's bytes against
    the manifest's file digest, and (``deep``) every shard array against
    its array digest.  Returns a summary dict; raises CheckpointError
    naming the first corrupt file/var."""
    man = load_manifest(root, step)
    sdir = step_dir(root, step)
    checked_files = 0
    for fn, info in sorted(man.files.items()):
        path = os.path.join(sdir, fn)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise CheckpointError(
                f"checkpoint step {step}: shard file {path!r} named by "
                "the manifest is missing")
        got = file_digest(data)
        if info.get("digest") and got != info["digest"]:
            raise CheckpointError(
                f"checkpoint step {step}: shard file {path!r} digest "
                f"mismatch (manifest {info['digest']}, file {got})")
        checked_files += 1
    checked_arrays = 0
    if deep:
        by_file: Dict[str, List[dict]] = {}
        for s in man.shards:
            by_file.setdefault(s["file"], []).append(s)
        for fn, shards in sorted(by_file.items()):
            with np.load(os.path.join(sdir, fn)) as data:
                for s in shards:
                    if s["key"] not in data.files:
                        raise CheckpointError(
                            f"checkpoint step {step}: shard key "
                            f"{s['key']!r} of var {s['var']!r} missing "
                            f"from {fn!r}")
                    if array_digest(data[s["key"]]) != s["digest"]:
                        raise CheckpointError(
                            f"checkpoint step {step}: var {s['var']!r} "
                            f"shard {s['key']!r} in {fn!r} fails its "
                            "content digest")
                    checked_arrays += 1
    return {"step": step, "writers": man.writers,
            "files": checked_files, "arrays": checked_arrays,
            "vars": len(man.vars()), "nbytes": man.nbytes(), "ok": True}


def piece_writers(root: str, step: int) -> List[str]:
    """Writers whose pieces have landed for an IN-FLIGHT step (admin /
    commit-poll introspection)."""
    tmp_dir = _tmp_step_dir(root, step)
    if not os.path.isdir(tmp_dir):
        return []
    return sorted(w for p in _read_pieces(tmp_dir) for w in p.writers)
