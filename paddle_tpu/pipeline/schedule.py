"""Microbatch schedules for pipeline parallelism: GPipe and 1F1B.

A schedule is, per stage, an ordered list of actions ``("F", m)`` /
``("B", m)`` over ``M`` microbatches.  ``simulate_slots`` lays those
orders onto a slot-clocked grid (one action per stage per slot, earliest
slot that satisfies the data dependencies) — the grid both drives the
slot-stepped concurrent runner (pipeline/runner.py) and yields the exact
schedule-level bubble fraction, which for GPipe equals the classical
``(K-1)/(M+K-1)`` bound when forward and backward each occupy one slot.

GPipe (Huang et al.): all M forwards, then all M backwards — maximal
activation stash (M microbatches live at the fwd/bwd turn), simplest
order.  1F1B (PipeDream-flush / Narayanan et al.): stage ``s`` warms up
with ``K-1-s`` forwards then alternates one-forward-one-backward and
drains — same bubble in slot terms, but at most ``K-s`` stashed
microbatches per stage, so the activation footprint stops growing
with M.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["SCHEDULES", "gpipe_order", "one_f_one_b_order",
           "stage_orders", "validate_orders", "simulate_slots",
           "slot_bubble_fraction", "gpipe_bubble_bound"]

Action = Tuple[str, int]   # ("F"|"B", microbatch)

SCHEDULES = ("gpipe", "1f1b")


def gpipe_order(num_stages: int, num_microbatches: int,
                stage: int) -> List[Action]:
    """All forwards then all backwards for one stage."""
    del num_stages, stage
    M = num_microbatches
    return [("F", m) for m in range(M)] + [("B", m) for m in range(M)]


def one_f_one_b_order(num_stages: int, num_microbatches: int,
                      stage: int) -> List[Action]:
    """Non-interleaved 1F1B for one stage: ``K-1-s`` warmup forwards,
    steady one-forward-one-backward, backward drain."""
    K, M, s = num_stages, num_microbatches, stage
    warm = min(M, K - 1 - s)
    order: List[Action] = [("F", m) for m in range(warm)]
    for m in range(M - warm):
        order.append(("F", warm + m))
        order.append(("B", m))
    for m in range(M - warm, M):
        order.append(("B", m))
    return order


def stage_orders(schedule: str, num_stages: int,
                 num_microbatches: int) -> List[List[Action]]:
    """Per-stage action orders for a named schedule."""
    if schedule == "gpipe":
        fn = gpipe_order
    elif schedule in ("1f1b", "one_f_one_b"):
        fn = one_f_one_b_order
    else:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r}; pick one of "
            f"{SCHEDULES}")
    return [fn(num_stages, num_microbatches, s) for s in range(num_stages)]


def validate_orders(orders: List[List[Action]],
                    num_microbatches: int) -> None:
    """Every stage must run F and B of every microbatch exactly once,
    each B after its own F."""
    M = num_microbatches
    for s, order in enumerate(orders):
        want = {("F", m) for m in range(M)} | {("B", m) for m in range(M)}
        got = list(order)
        if set(got) != want or len(got) != len(want):
            raise ValueError(f"stage {s} order is not a permutation of "
                             f"F/B over {M} microbatches: {got}")
        seen_f = set()
        for kind, m in got:
            if kind == "F":
                seen_f.add(m)
            elif m not in seen_f:
                raise ValueError(
                    f"stage {s} schedules B({m}) before F({m})")


def simulate_slots(orders: List[List[Action]]
                   ) -> List[List[Optional[Action]]]:
    """Greedy slot assignment honoring pipeline dependencies.

    Dependencies: ``F(s, m)`` needs ``F(s-1, m)`` completed in an
    earlier slot; ``B(s, m)`` needs ``B(s+1, m)`` (or, on the last
    stage, its own ``F(s, m)``) completed earlier, plus its own
    ``F(s, m)``.  Each stage executes at most one action per slot, in
    its order.  Returns ``grid[slot][stage]`` of actions (None = idle).
    """
    K = len(orders)
    done: Dict[Tuple[int, str, int], int] = {}  # (stage, kind, m) -> slot
    next_i = [0] * K
    grid: List[List[Optional[Action]]] = []
    total = sum(len(o) for o in orders)
    placed = 0
    while placed < total:
        slot = len(grid)
        row: List[Optional[Action]] = [None] * K
        progressed = False
        for s in range(K):
            if next_i[s] >= len(orders[s]):
                continue
            kind, m = orders[s][next_i[s]]
            if kind == "F":
                ready = s == 0 or done.get((s - 1, "F", m), slot) < slot
            else:
                ready = done.get((s, "F", m), slot) < slot and (
                    s == K - 1 or done.get((s + 1, "B", m), slot) < slot)
            if ready:
                row[s] = (kind, m)
                done[(s, kind, m)] = slot
                next_i[s] += 1
                placed += 1
                progressed = True
        grid.append(row)
        if not progressed:
            raise RuntimeError(
                "pipeline schedule deadlocked: no stage can progress "
                f"at slot {slot} (orders violate dependencies)")
    return grid


def slot_bubble_fraction(grid: List[List[Optional[Action]]]) -> float:
    """Idle fraction of the slot grid: 1 - busy_slots / (K * slots)."""
    if not grid:
        return 0.0
    K = len(grid[0])
    busy = sum(1 for row in grid for a in row if a is not None)
    return 1.0 - busy / float(K * len(grid))


def gpipe_bubble_bound(num_stages: int, num_microbatches: int) -> float:
    """The classical GPipe bubble model ``(K-1)/(M+K-1)`` (equal-cost
    forward/backward slots)."""
    K, M = num_stages, num_microbatches
    return (K - 1) / float(M + K - 1)
