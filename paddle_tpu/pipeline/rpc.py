"""Multi-host pipeline stages over the striped zero-copy RPC transport.

Each stage runs in its own process, binds an :class:`RPCServer` with a
:class:`StageMailbox` service, and pushes boundary tensors to its peers
with batched ``SEND_VARS`` frames (one RPC per peer per action, riding
the PR-3 connection striping / scatter-gather serde).  Names carry the
microbatch tag (``<var>@mb<m>``), so a consumer blocks on exactly the
tensors its schedule action needs.  Trace contexts propagate on the
wire (PR-4), so ``tools/stitch_trace.py`` over the stage endpoints
renders the pipeline ladder as one Perfetto timeline.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..core.executor import Executor, Scope
from ..distributed import transport
from ..distributed.transport import (COMPLETE, OK, RPCServer, SEND_VARS,
                                     serde)
from . import schedule as _sched
from .transpiler import PipelineProgram

__all__ = ["StageMailbox", "PipelineStageWorker", "mb_tag"]

_TAKE_TIMEOUT_S = 180.0


def mb_tag(name: str, m: int) -> str:
    return f"{name}@mb{m}"


class StageMailbox:
    """RPC service: peers push (name@mbM, tensor) pairs; the local stage
    blocks on :meth:`take` until its action's inputs arrived."""

    def __init__(self):
        self._store: Dict[str, object] = {}
        self._cond = threading.Condition()
        self.peers_done = 0

    # -- service entry (transport._serve_io) -------------------------------
    def handle(self, msg_type, trainer_id, name, payload):
        if msg_type == SEND_VARS:
            pairs = serde.loads_batch(payload, copy=True)
            with self._cond:
                self._store.update(pairs)
                self._cond.notify_all()
            return OK, b""
        if msg_type == COMPLETE:
            with self._cond:
                self.peers_done += 1
                self._cond.notify_all()
            return OK, b""
        raise ValueError(f"stage mailbox: unexpected message {msg_type}")

    def take(self, names: List[str],
             timeout: float = _TAKE_TIMEOUT_S) -> List[object]:
        """Block until every name arrived; pop and return in order."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: all(n in self._store for n in names),
                timeout=timeout)
            if not ok:
                missing = [n for n in names if n not in self._store]
                raise TimeoutError(
                    f"pipeline stage mailbox timed out waiting for "
                    f"{missing[:4]} (of {len(names)})")
            return [self._store.pop(n) for n in names]


class PipelineStageWorker:
    """One process = one pipeline stage, exchanging boundaries over RPC.

    ``endpoints`` lists every stage's endpoint in stage order; this
    worker binds ``endpoints[stage]``.  Feeds: pass the FULL minibatch
    feed dict to :meth:`run_minibatch` on every stage — each stage
    slices out only the feeds its programs consume (so data readers can
    be replicated, the usual multi-host pattern).
    """

    def __init__(self, pipeline_program: PipelineProgram, stage: int,
                 endpoints: List[str], schedule: str = "gpipe",
                 bind_endpoint: Optional[str] = None):
        self.pp = pipeline_program
        self.K = pipeline_program.num_stages
        self.M = pipeline_program.num_microbatches
        self.stage = stage
        self.st = pipeline_program.stages[stage]
        self.endpoints = list(endpoints)
        self.schedule = schedule
        self.order = _sched.stage_orders(schedule, self.K, self.M)[stage]
        self.mailbox = StageMailbox()
        self.server = RPCServer(bind_endpoint or endpoints[stage],
                                self.mailbox)
        self.server.start()
        self.client = transport.get_client(trainer_id=stage)
        self.exe = Executor()
        self.scope = Scope()
        self._initialized = False

    def init(self, wait_peers: bool = True,
             timeout: float = 90.0) -> "PipelineStageWorker":
        self.exe.run(self.st.startup_program, scope=self.scope)
        if wait_peers:
            others = [ep for i, ep in enumerate(self.endpoints)
                      if i != self.stage]
            if others:
                transport.wait_server_ready(others, timeout=timeout)
        self._initialized = True
        return self

    def _send(self, kind: str, names_to_dsts: Dict[str, List[int]],
              vals: Dict[str, object], m: int) -> None:
        by_dst: Dict[int, list] = {}
        for n, dsts in names_to_dsts.items():
            for d in dsts:
                by_dst.setdefault(d, []).append(
                    (mb_tag(n, m), np.asarray(vals[n])))
        calls = [(self.client.send_vars, self.endpoints[d], pairs)
                 for d, pairs in sorted(by_dst.items())]
        if calls:
            self.client.parallel(calls)

    def run_minibatch(self, feed: Dict[str, object]) -> Optional[float]:
        """One full minibatch (M microbatches + one optimizer step) in
        this stage's schedule order.  Returns the mean microbatch loss
        on the last stage, None elsewhere."""
        if not self._initialized:
            raise RuntimeError("call init() first")
        st, M = self.st, self.M
        from .transpiler import split_microbatches
        _, per_mb = split_microbatches(feed, M)
        retained: Dict[tuple, object] = {}
        losses = np.zeros(M, dtype=np.float64)
        for kind, m in self.order:
            if kind == "F":
                sfeed = {n: per_mb[m][n] for n in st.fwd_feeds}
                if st.recv_acts:
                    names = sorted(st.recv_acts)
                    vals = self.mailbox.take([mb_tag(n, m) for n in names])
                    for n, v in zip(names, vals):
                        if n in st.recv_acts_fwd:
                            sfeed[n] = v
                        if n in st.recv_acts_bwd:
                            retained[(n, m)] = v
                outs = self.exe.run(st.fwd_program, feed=sfeed,
                                    fetch_list=st.fwd_fetches,
                                    scope=self.scope, sync=True)
                vals = dict(zip(st.fwd_fetches, outs))
                for n in st.stash:
                    retained[(n, m)] = vals[n]
                self._send("act", st.send_acts, vals, m)
                if self.stage == self.K - 1 and self.pp.loss_name:
                    losses[m] = float(np.asarray(vals[self.pp.loss_name]))
            else:
                bfeed = {n: per_mb[m][n] for n in st.bwd_feeds}
                for n in st.stash + st.recv_acts_bwd:
                    bfeed[n] = retained.pop((n, m))
                if st.recv_grads:
                    names = sorted(st.recv_grads)
                    vals = self.mailbox.take([mb_tag(n, m) for n in names])
                    bfeed.update(zip(names, vals))
                outs = self.exe.run(st.bwd_program, feed=bfeed,
                                    fetch_list=st.bwd_fetches,
                                    scope=self.scope, sync=True)
                vals = dict(zip(st.bwd_fetches, outs))
                self._send("grad", st.send_grads, vals, m)
        if st.opt_program is not None:
            self.exe.run(st.opt_program, scope=self.scope, sync=True)
        if self.stage == self.K - 1 and self.pp.loss_name:
            return float(losses.mean())
        return None

    def shutdown(self, notify_peers: bool = False) -> None:
        if notify_peers:
            for i, ep in enumerate(self.endpoints):
                if i != self.stage:
                    try:
                        self.client.complete(ep)
                    except Exception:
                        pass
        self.server.stop()
