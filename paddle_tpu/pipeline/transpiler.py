"""Pipeline transpiler: one trained Program → K per-stage programs.

The missing axis of the parallelism matrix (ROADMAP item 2; survey §2.7
names PP among the modern axes to design TPU-first, with the reference's
layer-placement precedent in ``legacy/gserver/.../ParallelNeuralNetwork.h``).
Takes the full program (forward + ``append_backward`` + optimizer ops)
and splits it into K **stages**, each a trio of standalone programs:

- ``fwd_program`` — the stage's forward ops; feeds are the global data
  feeds it consumes plus activations received from earlier stages;
  fetches are the boundary activations later stages consume plus the
  **stash** (forward values its own backward needs — the GPipe
  activation stash, visible as real per-microbatch bytes);
- ``bwd_program`` — the stage's backward ops plus appended
  **gradient-accumulation** ops: each optimizer-consumed gradient is
  scaled by ``1/M`` and added into a persistable ``<grad>@ACC`` var, so
  M microbatches accumulate exactly the full-batch mean gradient;
  fetches are the boundary activation-gradients sent upstream;
- ``opt_program`` — the (replicated) LR-schedule chain plus the stage's
  optimizer ops with their ``Grad`` input renamed to the accumulator,
  followed by accumulator zeroing — run ONCE per minibatch, after all
  M microbatches (gradient accumulation across microbatches before the
  optimizer block runs once).

Stage assignment: user-marked via ``program.pipeline_stage_guard`` /
explicit ``cut_points``, or cost-balanced automatically (contiguous
split of the forward ops on an analytic per-op flops estimate;
``balance="xla"`` refines the split once using real per-stage flops
from the PR-7 XLA cost attribution, ``observability/perf.cost_dict``).
Backward ops inherit the stage of their forward op (via the
``__fwd_out_slots__`` annotation ``core/backward.py`` stamps); gradient
``sum``/``assign`` combiners land on the stage that produced the summed
var; optimizer ops land on their parameter's stage.

Equal-weight caveat: microbatch-mean accumulation reproduces the
full-batch gradient exactly only when the loss is an equal-weight mean
over samples and every microbatch has the same weight (e.g. identical
token counts for a token-normalized loss) — the standard GPipe
contract.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core.program import (EMPTY_VAR, OP_ROLE_ATTR, Operator, OpRole,
                            Program, Variable, default_main_program,
                            default_startup_program)

__all__ = ["PipelineTranspiler", "PipelineProgram", "StagePrograms",
           "balanced_cut_points", "op_flops_estimate", "xla_stage_flops",
           "split_microbatches", "PIPELINE_STAGE_ATTR", "ACC_SUFFIX"]

PIPELINE_STAGE_ATTR = "pipeline_stage"
ACC_SUFFIX = "@ACC"


def _role(op) -> int:
    return int(op.attr(OP_ROLE_ATTR, OpRole.Forward))


def _is_optimize_op(op) -> bool:
    return ("Param" in op.inputs and "Grad" in op.inputs
            and _role(op) == OpRole.Optimize)


def _real(names) -> List[str]:
    return [n for n in names if n and n != EMPTY_VAR]


def split_microbatches(feed: Dict[str, object], num_microbatches: int):
    """THE microbatch split contract, shared by every driver (in-process
    runner and RPC stage workers): each feed's leading (batch) dim must
    divide M; microbatch m gets rows ``[m*mb, (m+1)*mb)``.  Returns
    ``(stacked, per_mb)`` — ``stacked[n]`` is ``[M, mb, ...]`` (the
    run_steps scan layout), ``per_mb[m][n]`` the per-microbatch slice.
    """
    import numpy as np
    M = int(num_microbatches)
    stacked: Dict[str, object] = {}
    per_mb: List[Dict[str, object]] = [dict() for _ in range(M)]
    for n, v in feed.items():
        a = np.asarray(v)
        if a.ndim < 1 or a.shape[0] % M != 0:
            raise ValueError(
                f"feed {n!r} batch {a.shape[:1]} does not divide "
                f"num_microbatches={M}")
        mb = a.shape[0] // M
        s = a.reshape((M, mb) + a.shape[1:])
        stacked[n] = s
        for m in range(M):
            per_mb[m][n] = s[m]
    return stacked, per_mb


def op_flops_estimate(block, op, batch: int = 8) -> float:
    """Analytic per-op cost for stage balancing.  Dense contractions get
    a real flops formula; everything else counts output elements (a
    bandwidth proxy).  ``-1`` (batch) dims substitute ``batch``."""

    def shape(name):
        v = block.var_or_none(name)
        if v is None or v.shape is None:
            return None
        return tuple(batch if d == -1 else int(d) for d in v.shape)

    def numel(name):
        s = shape(name)
        if not s:
            return 0.0
        n = 1.0
        for d in s:
            n *= d
        return n

    out_elems = sum(numel(n) for n in _real(op.output_arg_names()))
    if op.type in ("matmul", "mul"):
        xs = shape(op.input("X")[0]) if op.input("X") else None
        if xs:
            k = xs[-2] if op.attr("transpose_x", False) else xs[-1]
            return 2.0 * out_elems * max(k, 1)
    if op.type in ("conv2d", "depthwise_conv2d"):
        ws = shape(op.input("Filter")[0]) if op.input("Filter") else None
        if ws and len(ws) == 4:
            co, ci, kh, kw = ws
            groups = max(int(op.attr("groups", 1) or 1), 1)
            return 2.0 * out_elems * ci * kh * kw / groups
    if op.type == "fused_attention":
        qs = shape(op.input("Q")[0]) if op.input("Q") else None
        if qs and len(qs) >= 2:
            # QK^T + PV: 2 matmuls of [T, dk] x [dk, T] shape class
            return 4.0 * numel(op.input("Q")[0]) * qs[-2]
    return max(out_elems, 1.0)


def balanced_cut_points(costs: Sequence[float], num_stages: int
                        ) -> List[int]:
    """Contiguous split of ``costs`` into ``num_stages`` parts with
    near-equal sums: cut after the prefix crosses each k/K share.
    Returns K-1 cut indices (first op index of each later stage)."""
    K = num_stages
    n = len(costs)
    if K > n:
        raise ValueError(f"cannot split {n} forward ops into {K} stages")
    total = float(sum(costs)) or 1.0
    cuts: List[int] = []
    acc, k = 0.0, 1
    for i, c in enumerate(costs):
        if k >= K:
            break
        target = total * k / K
        # crossing the k/K share: cut BEFORE this op when that lands
        # closer to the target (a single huge op must start a stage,
        # not silently absorb into the previous one), and always leave
        # at least one op per remaining stage
        if acc + c >= target and i >= k - 1:
            cut_at = i if (target - acc <= acc + c - target and i > 0
                           and (not cuts or i > cuts[-1])) else i + 1
            cut_at = min(cut_at, n - (K - k))
            if not cuts or cut_at > cuts[-1]:
                cuts.append(cut_at)
                k += 1
        elif i + 1 == n - (K - k):
            cuts.append(i + 1)
            k += 1
        acc += c
    while k < K:  # degenerate tails: force remaining cuts
        cut_at = n - (K - k)
        cuts.append(cut_at)
        k += 1
    return cuts


class StagePrograms:
    """One pipeline stage's emitted programs + boundary contract."""

    def __init__(self, idx: int):
        self.idx = idx
        self.fwd_program: Optional[Program] = None
        self.bwd_program: Optional[Program] = None
        self.opt_program: Optional[Program] = None
        self.startup_program: Optional[Program] = None
        self.fwd_feeds: List[str] = []      # global data feeds (forward)
        self.bwd_feeds: List[str] = []      # global data feeds (backward)
        self.recv_acts: Dict[str, int] = {}       # name -> src stage
        self.recv_acts_fwd: List[str] = []        # consumed by fwd ops
        self.recv_acts_bwd: List[str] = []        # consumed by bwd ops
        self.send_acts: Dict[str, List[int]] = {}  # name -> dst stages
        self.stash: List[str] = []                # fwd -> own bwd
        self.recv_grads: Dict[str, int] = {}      # name -> src stage
        self.send_grads: Dict[str, List[int]] = {}  # name -> dst stages
        self.fwd_fetches: List[str] = []
        self.bwd_fetches: List[str] = []
        self.param_accs: List[Tuple[str, str, str]] = []  # (param, grad, acc)
        self.loss_name: Optional[str] = None
        self.op_indices: Dict[str, List[int]] = {"F": [], "B": [], "O": []}

    @property
    def has_optimizer(self) -> bool:
        return self.opt_program is not None

    def activation_bytes(self, microbatch: int) -> int:
        """Per-microbatch bytes this stage must hold/ship forward: the
        boundary activations it sends plus its own stash."""
        import numpy as np
        from ..core.types import np_dtype
        total = 0
        blk = self.fwd_program.global_block if self.fwd_program else None
        if blk is None:
            return 0
        for n in set(self.fwd_fetches):
            v = blk.var_or_none(n)
            if v is None or v.shape is None:
                continue
            numel = 1
            for d in v.shape:
                numel *= microbatch if d == -1 else int(d)
            total += numel * np.dtype(np_dtype(v.dtype or "float32")).itemsize
        return total


class PipelineProgram:
    """The transpiled pipeline: K StagePrograms + the microbatch/schedule
    contract (built by :class:`PipelineTranspiler`, driven by
    ``pipeline/runner.py``)."""

    def __init__(self, stages: List[StagePrograms], num_microbatches: int,
                 loss_name: Optional[str], assignment: List[Optional[int]],
                 lr_chain: List[int]):
        self.stages = stages
        self.num_stages = len(stages)
        self.num_microbatches = num_microbatches
        self.loss_name = loss_name
        # per original-op stage (None = LR-chain op, replicated into
        # every optimizing stage's opt_program)
        self.op_stage_assignment = assignment
        self.lr_chain_ops = lr_chain

    def validate(self) -> None:
        """Structural invariants: every original op assigned exactly
        once (or LR-chain-replicated), every boundary recv matched by
        the producing stage's send."""
        for i, s in enumerate(self.op_stage_assignment):
            if s is None and i not in self.lr_chain_ops:
                raise AssertionError(f"op {i} is unassigned")
        for st in self.stages:
            for n, src in st.recv_acts.items():
                if st.idx not in self.stages[src].send_acts.get(n, []):
                    raise AssertionError(
                        f"stage {st.idx} receives activation {n!r} from "
                        f"{src}, which does not send it")
            for n, src in st.recv_grads.items():
                if st.idx not in self.stages[src].send_grads.get(n, []):
                    raise AssertionError(
                        f"stage {st.idx} receives grad {n!r} from {src}, "
                        f"which does not send it")
            for n, dsts in st.send_acts.items():
                for d in dsts:
                    if st.idx != self.stages[d].recv_acts.get(n):
                        raise AssertionError(
                            f"stage {st.idx} sends {n!r} to {d}, which "
                            f"does not expect it")

    def adjacent_only(self) -> bool:
        """True when every boundary crosses exactly one stage hop (the
        collective-permute transport's requirement)."""
        for st in self.stages:
            for n, src in st.recv_acts.items():
                if st.idx - src != 1:
                    return False
            for n, src in st.recv_grads.items():
                if src - st.idx != 1:
                    return False
        return True


class PipelineTranspiler:
    """Split a trained program into pipeline stages (see module doc)."""

    def transpile(self, program: Optional[Program] = None,
                  startup_program: Optional[Program] = None,
                  num_stages: Optional[int] = None,
                  num_microbatches: int = 4,
                  loss_name: Optional[str] = None,
                  cut_points: Optional[Sequence[int]] = None,
                  balance: str = "analytic",
                  batch_hint: int = 8) -> PipelineProgram:
        program = program or default_main_program()
        startup_program = startup_program or default_startup_program()
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        self.program = program
        self.startup_program = startup_program
        self.block = program.global_block
        self.ops = list(self.block.ops)
        self.loss_name = loss_name
        self.M = int(num_microbatches)

        self._classify_ops()
        fwd_assign = self._assign_forward(num_stages, cut_points,
                                          balance, batch_hint)
        self.K = max(fwd_assign.values()) + 1
        assignment = self._assign_all(fwd_assign)
        stages = self._emit(assignment)
        pp = PipelineProgram(stages, self.M, loss_name,
                             [assignment.get(i) for i in
                              range(len(self.ops))],
                             sorted(self.lr_chain))
        pp.validate()
        if balance == "xla" and cut_points is None and \
                not self._explicit_stages():
            pp = self._xla_rebalance(pp, num_stages, batch_hint)
        return pp

    # -- classification ----------------------------------------------------
    def _classify_ops(self) -> None:
        self.fwd_idx = [i for i, op in enumerate(self.ops)
                        if _role(op) == OpRole.Forward]
        self.opt_idx = [i for i, op in enumerate(self.ops)
                        if _is_optimize_op(op)]
        # the LR closure: every var feeding an optimizer op's
        # LearningRate slot, and the LRSched/Optimize-role ops that
        # (transitively) produce them — replicated per optimizing stage
        lr_names: Set[str] = set()
        for i in self.opt_idx:
            lr_names |= set(_real(self.ops[i].input("LearningRate")))
        needed = set(lr_names)
        chain: Set[int] = set()
        for i in range(len(self.ops) - 1, -1, -1):
            op = self.ops[i]
            r = _role(op)
            if i in self.opt_idx or r not in (OpRole.Optimize,
                                              OpRole.LRSched):
                continue
            if r == OpRole.LRSched or \
                    set(_real(op.output_arg_names())) & needed:
                chain.add(i)
                needed |= set(_real(op.input_arg_names()))
        self.lr_chain = chain
        self.lr_names = lr_names

    def _phase(self, i: int) -> str:
        if i in self.lr_chain or i in self.opt_idx:
            return "O"
        return "F" if _role(self.ops[i]) == OpRole.Forward else "B"

    def _explicit_stages(self) -> bool:
        return any(self.ops[i].has_attr(PIPELINE_STAGE_ATTR)
                   for i in self.fwd_idx)

    # -- forward assignment ------------------------------------------------
    def _assign_forward(self, num_stages, cut_points, balance,
                        batch_hint) -> Dict[int, int]:
        if self._explicit_stages():
            assign, cur = {}, 0
            for i in self.fwd_idx:
                if self.ops[i].has_attr(PIPELINE_STAGE_ATTR):
                    cur = int(self.ops[i].attr(PIPELINE_STAGE_ATTR))
                assign[i] = cur
            if num_stages is not None and \
                    max(assign.values()) + 1 != num_stages:
                raise ValueError(
                    f"pipeline_stage markers name "
                    f"{max(assign.values()) + 1} stages, num_stages="
                    f"{num_stages}")
        else:
            if num_stages is None or num_stages < 1:
                raise ValueError("num_stages required without "
                                 "pipeline_stage markers or cut_points")
            if cut_points is None:
                costs = self._op_costs(batch_hint)
                cut_points = balanced_cut_points(costs, num_stages)
            if len(cut_points) != num_stages - 1:
                raise ValueError(
                    f"{num_stages} stages need {num_stages - 1} cut "
                    f"points, got {len(cut_points)}")
            assign = {}
            for pos, i in enumerate(self.fwd_idx):
                s = 0
                for c in cut_points:
                    if pos >= c:
                        s += 1
                assign[i] = s
        self._validate_forward(assign)
        return assign

    def _op_costs(self, batch_hint: int,
                  scale: Optional[Dict[int, float]] = None) -> List[float]:
        """Per-forward-op costs (``scale``: per-stage correction factors
        from the XLA rebalance pass, keyed by a prior assignment)."""
        costs = []
        for i in self.fwd_idx:
            c = op_flops_estimate(self.block, self.ops[i], batch_hint)
            if scale:
                c *= scale.get(i, 1.0)
            costs.append(c)
        return costs

    def _validate_forward(self, assign: Dict[int, int]) -> None:
        prod: Dict[str, int] = {}
        for i in self.fwd_idx:
            s = assign[i]
            for n in _real(self.ops[i].input_arg_names()):
                if n in prod and prod[n] > s:
                    raise ValueError(
                        f"forward dataflow crosses a stage boundary "
                        f"backwards: op {i} ({self.ops[i].type}) at stage "
                        f"{s} consumes {n!r} produced at stage {prod[n]}")
            for n in _real(self.ops[i].output_arg_names()):
                prod[n] = s

    # -- full assignment ---------------------------------------------------
    def _assign_all(self, fwd_assign: Dict[int, int]) -> Dict[int, int]:
        ops = self.ops
        stage_of: Dict[int, int] = dict(fwd_assign)
        var_fwd_stage: Dict[str, int] = {}
        for i in self.fwd_idx:
            for n in _real(ops[i].output_arg_names()):
                var_fwd_stage[n] = fwd_assign[i]
        # min consumer stage for feeds/params (vars with no fwd producer)
        consumer_min: Dict[str, int] = {}
        consumer_stages: Dict[str, Set[int]] = {}
        for i in self.fwd_idx:
            for n in _real(ops[i].input_arg_names()):
                if n not in var_fwd_stage:
                    consumer_min[n] = min(consumer_min.get(n, self.K),
                                          fwd_assign[i])
                    consumer_stages.setdefault(n, set()).add(fwd_assign[i])
        for n, ss in consumer_stages.items():
            v = self.block.var_or_none(n)
            if v is not None and v.is_parameter and len(ss) > 1:
                raise NotImplementedError(
                    f"parameter {n!r} is consumed by stages {sorted(ss)}: "
                    "cross-stage weight sharing is not supported — give "
                    "each stage its own parameter")

        def var_stage(n: str) -> Optional[int]:
            if n in var_fwd_stage:
                return var_fwd_stage[n]
            return consumer_min.get(n)

        producer_stage: Dict[str, int] = dict(var_fwd_stage)
        for i, op in enumerate(ops):
            if i in stage_of or i in self.lr_chain:
                continue
            s: Optional[int] = None
            if i in self.opt_idx:
                s = var_stage(op.input("Param")[0])
                if s is None:
                    raise ValueError(
                        f"optimizer op {op.type} updates "
                        f"{op.input('Param')[0]!r}, which no forward op "
                        "consumes — cannot place it on a stage")
            elif op.has_attr("__fwd_out_slots__"):
                # a grad op: inherit the stage of its forward op (whose
                # outputs ride in the __fwd_out_slots__ input slots)
                cands = [var_stage(n)
                         for slot in op.attr("__fwd_out_slots__", ())
                         for n in _real(op.inputs.get(slot, ()))]
                cands = [c for c in cands if c is not None]
                if cands:
                    s = max(cands)
            if s is None:
                # grad seed / sum / assign combiners: the stage of the
                # var whose gradient they produce
                for out in _real(op.output_arg_names()):
                    if "@GRAD" in out:
                        c = var_stage(out.split("@GRAD")[0])
                        if c is not None:
                            s = c if s is None else max(s, c)
            if s is None:
                cands = [producer_stage[n]
                         for n in _real(op.input_arg_names())
                         if n in producer_stage]
                s = max(cands) if cands else self.K - 1
            stage_of[i] = s
            for n in _real(op.output_arg_names()):
                producer_stage[n] = s
        return stage_of

    # -- emission ----------------------------------------------------------
    def _emit(self, stage_of: Dict[int, int]) -> List[StagePrograms]:
        ops, block, K = self.ops, self.block, self.K
        stages = [StagePrograms(s) for s in range(K)]
        for i in range(len(ops)):
            if i in self.lr_chain:
                continue
            stages[stage_of[i]].op_indices[self._phase(i)].append(i)

        # boundary / stash / feed analysis over F+B ops in program order
        producer: Dict[str, int] = {}  # var -> op index (last F/B writer)
        recv_fwd_use: List[Set[str]] = [set() for _ in range(K)]
        recv_bwd_use: List[Set[str]] = [set() for _ in range(K)]
        stash: List[Set[str]] = [set() for _ in range(K)]
        fwd_feeds: List[Set[str]] = [set() for _ in range(K)]
        bwd_feeds: List[Set[str]] = [set() for _ in range(K)]
        for i, op in enumerate(ops):
            if i in self.lr_chain or i in self.opt_idx:
                continue
            s, p = stage_of[i], self._phase(i)
            for n in _real(op.input_arg_names()):
                j = producer.get(n)
                if j is None:
                    v = block.var_or_none(n)
                    if v is None or v.persistable:
                        continue  # parameter / persistable state
                    (fwd_feeds if p == "F" else bwd_feeds)[s].add(n)
                    continue
                sp, pp = stage_of[j], self._phase(j)
                if sp == s:
                    if pp == "F" and p == "B":
                        stash[s].add(n)
                    continue
                if pp == "F":
                    stages[sp].send_acts.setdefault(n, [])
                    if s not in stages[sp].send_acts[n]:
                        stages[sp].send_acts[n].append(s)
                    stages[s].recv_acts[n] = sp
                    (recv_fwd_use if p == "F" else recv_bwd_use)[s].add(n)
                else:
                    stages[sp].send_grads.setdefault(n, [])
                    if s not in stages[sp].send_grads[n]:
                        stages[sp].send_grads[n].append(s)
                    stages[s].recv_grads[n] = sp
            for n in _real(op.output_arg_names()):
                producer[n] = i

        for st in stages:
            s = st.idx
            st.fwd_feeds = sorted(fwd_feeds[s])
            st.bwd_feeds = sorted(bwd_feeds[s])
            st.stash = sorted(stash[s])
            st.recv_acts_fwd = sorted(recv_fwd_use[s])
            st.recv_acts_bwd = sorted(recv_bwd_use[s])
            st.fwd_fetches = sorted(set(st.send_acts) | stash[s])
            if s == K - 1 and self.loss_name and \
                    self.loss_name not in st.fwd_fetches:
                st.fwd_fetches.append(self.loss_name)
            if s == K - 1:
                st.loss_name = self.loss_name
            st.bwd_fetches = sorted(st.send_grads)
            self._emit_stage(st)
        return stages

    def _ensure_var(self, gb, name: str, src_block=None) -> None:
        if not name or name == EMPTY_VAR or name in gb.vars:
            return
        for blk in (src_block, self.block,
                    self.startup_program.global_block):
            if blk is None:
                continue
            v = blk.var_or_none(name)
            if v is not None:
                gb.vars[name] = Variable.from_dict(gb, v.to_dict())
                return
        gb.create_var(name=name)

    def _clone_ops(self, prog: Program, indices: List[int],
                   rename: Optional[Dict[str, Dict[str, str]]] = None
                   ) -> None:
        """Clone original ops (by index) into ``prog``'s global block;
        ``rename`` optionally remaps input slots per op index:
        ``{slot: {old: new}}`` applied to every listed op."""
        gb = prog.global_block
        for i in indices:
            op = self.ops[i]
            ins = {k: list(v) for k, v in op.inputs.items()}
            if rename:
                for slot, m in rename.items():
                    if slot in ins:
                        ins[slot] = [m.get(n, n) for n in ins[slot]]
            for n in [x for vs in ins.values() for x in vs] + \
                    op.output_arg_names():
                self._ensure_var(gb, n)
            gb.ops.append(Operator(gb, op.type, ins, op.outputs,
                                   dict(op.attrs)))
        prog._version += 1

    def _emit_stage(self, st: StagePrograms) -> None:
        M, block = self.M, self.block
        # forward
        st.fwd_program = Program()
        self._clone_ops(st.fwd_program, st.op_indices["F"])
        for n in st.fwd_fetches + st.recv_acts_fwd + st.fwd_feeds:
            self._ensure_var(st.fwd_program.global_block, n)

        # backward + gradient accumulation
        st.bwd_program = Program()
        self._clone_ops(st.bwd_program, st.op_indices["B"])
        bb = st.bwd_program.global_block
        for n in (st.stash + st.recv_acts_bwd + st.bwd_feeds
                  + list(st.recv_grads) + st.bwd_fetches):
            self._ensure_var(bb, n)
        for i in st.op_indices["O"]:
            op = self.ops[i]
            p, g = op.input("Param")[0], op.input("Grad")[0]
            acc = g + ACC_SUFFIX
            pvar = block.var(p)
            st.param_accs.append((p, g, acc))
            for prog_blk in (bb,):
                prog_blk.create_var(
                    name=acc, shape=pvar.shape, dtype=pvar.dtype,
                    persistable=True)
            scaled = g + "@MBSCALE"
            self._ensure_var(bb, g)
            bb.create_var(name=scaled, shape=pvar.shape, dtype=pvar.dtype)
            bb.append_op("scale", {"X": [g]}, {"Out": [scaled]},
                         {"scale": 1.0 / M, OP_ROLE_ATTR: OpRole.Backward})
            bb.append_op("elementwise_add", {"X": [acc], "Y": [scaled]},
                         {"Out": [acc]},
                         {OP_ROLE_ATTR: OpRole.Backward})

        # optimizer: LR chain + opt ops (Grad -> ACC) + ACC zeroing
        if st.op_indices["O"]:
            st.opt_program = Program()
            ob = st.opt_program.global_block
            self._clone_ops(st.opt_program, sorted(self.lr_chain))
            grad_to_acc = {g: acc for _, g, acc in st.param_accs}
            for p, g, acc in st.param_accs:
                pvar = block.var(p)
                ob.create_var(name=acc, shape=pvar.shape, dtype=pvar.dtype,
                              persistable=True)
            self._clone_ops(st.opt_program, st.op_indices["O"],
                            rename={"Grad": grad_to_acc})
            for p, g, acc in st.param_accs:
                pvar = block.var(p)
                ob.append_op(
                    "fill_constant", {}, {"Out": [acc]},
                    {"shape": [int(d) for d in pvar.shape], "value": 0.0,
                     "dtype": pvar.dtype, OP_ROLE_ATTR: OpRole.Optimize})

        # step-stat registrations (switch_moe aux health) follow their
        # vars onto the stage programs that can fetch them — fresh
        # Program() emission must not silently drop what clone() keeps
        reg = getattr(self.program, "step_stat_vars", None) or {}
        for prog in (st.fwd_program, st.bwd_program, st.opt_program):
            if prog is None:
                continue
            produced = {n for op in prog.global_block.ops
                        for n in _real(op.output_arg_names())}
            for n, key in reg.items():
                if n in produced:
                    prog.step_stat_vars[n] = key

        st.startup_program = self._emit_startup(st)

    def _emit_startup(self, st: StagePrograms) -> Program:
        """Stage startup: the original startup ops whose outputs any of
        this stage's programs reference, plus zero-init of the gradient
        accumulators.  Initializer ops draw by var name (``seed_name``),
        so per-stage init is bit-identical to the single-process run."""
        needed: Set[str] = set()
        for prog in (st.fwd_program, st.bwd_program, st.opt_program):
            if prog is None:
                continue
            for op in prog.global_block.ops:
                needed |= set(_real(op.input_arg_names()))
                needed |= set(_real(op.output_arg_names()))
        sp = Program()
        sp.random_seed = self.startup_program.random_seed
        gb = sp.global_block
        src = self.startup_program.global_block
        for op in src.ops:
            outs = set(_real(op.output_arg_names()))
            if not outs & needed:
                continue
            for n in _real(op.input_arg_names()) + list(outs):
                self._ensure_var(gb, n, src_block=src)
            gb.ops.append(Operator(gb, op.type, op.inputs, op.outputs,
                                   dict(op.attrs)))
        for p, g, acc in st.param_accs:
            pvar = self.block.var(p)
            gb.create_var(name=acc, shape=pvar.shape, dtype=pvar.dtype,
                          persistable=True)
            gb.append_op("fill_constant", {}, {"Out": [acc]},
                         {"shape": [int(d) for d in pvar.shape],
                          "value": 0.0, "dtype": pvar.dtype})
        return sp

    # -- XLA-cost rebalance (PR-7 attribution) -----------------------------
    def _xla_rebalance(self, pp: PipelineProgram, num_stages,
                       batch_hint: int) -> PipelineProgram:
        """One refinement pass: compile each stage's forward program
        AOT, read its real flops from XLA ``cost_analysis`` (the PR-7
        harvest), scale every op's analytic cost by its stage's
        real/analytic ratio, and re-split.  Falls back to the analytic
        split when compilation or costing is unavailable."""
        try:
            measured = xla_stage_flops(pp, batch_hint)
        except Exception:
            return pp
        if not measured or all(m <= 0 for m in measured):
            return pp
        costs = self._op_costs(batch_hint)
        fwd_assign_old = {}
        for i in self.fwd_idx:
            fwd_assign_old[i] = pp.op_stage_assignment[i]
        analytic = [0.0] * pp.num_stages
        for pos, i in enumerate(self.fwd_idx):
            analytic[fwd_assign_old[i]] += costs[pos]
        scale = {}
        for pos, i in enumerate(self.fwd_idx):
            s = fwd_assign_old[i]
            if analytic[s] > 0 and measured[s] > 0:
                scale[i] = measured[s] / analytic[s]
        costs2 = self._op_costs(batch_hint, scale=scale)
        cuts = balanced_cut_points(costs2, num_stages)
        assign = {}
        for pos, i in enumerate(self.fwd_idx):
            s = 0
            for c in cuts:
                if pos >= c:
                    s += 1
            assign[i] = s
        if all(assign[i] == fwd_assign_old[i] for i in self.fwd_idx):
            return pp
        self._validate_forward(assign)
        assignment = self._assign_all(assign)
        stages = self._emit(assignment)
        pp2 = PipelineProgram(stages, self.M, self.loss_name,
                              [assignment.get(i)
                               for i in range(len(self.ops))],
                              sorted(self.lr_chain))
        pp2.validate()
        return pp2


def xla_stage_flops(pp: PipelineProgram, batch_hint: int = 8
                    ) -> List[float]:
    """Real per-stage forward flops from XLA ``cost_analysis`` (the
    PR-7 attribution chain, ``observability/perf.cost_dict``): each
    stage's forward program is AOT-lowered with abstract avals (batch
    ``-1`` dims pinned to ``batch_hint``) and compiled — compile-only,
    nothing executes."""
    import jax
    import numpy as np
    from ..core.lowering import analyze_block, build_block_fn
    from ..core.types import np_dtype
    from ..observability import perf as _perf

    out = []
    for st in pp.stages:
        prog = st.fwd_program
        blk = prog.global_block
        feeds = sorted(set(st.fwd_feeds) | set(st.recv_acts_fwd))
        plan = analyze_block(prog, 0, feeds, list(st.fwd_fetches))

        def aval(name):
            v = blk.var_or_none(name)
            if v is None or v.shape is None:
                raise ValueError(f"no static shape for {name!r}")
            shape = tuple(batch_hint if d == -1 else int(d)
                          for d in v.shape)
            return jax.ShapeDtypeStruct(
                shape, jax.dtypes.canonicalize_dtype(
                    np.dtype(np_dtype(v.dtype or "float32"))))

        feed_avals = [aval(n) for n in feeds]
        state_avals = [aval(n) for n in plan.donated_reads]
        const_avals = [aval(n) for n in plan.const_reads]
        rng = jax.ShapeDtypeStruct((2,), np.uint32)
        fn = build_block_fn(prog, plan, training=True)
        compiled = jax.jit(fn).lower(feed_avals, state_avals, const_avals,
                                     rng).compile()
        cost = _perf.cost_dict(compiled)
        out.append(float(cost.get("flops", 0.0) or 0.0))
    return out
