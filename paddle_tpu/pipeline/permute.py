"""Collective-permute boundary transport for single-host multi-device
pipelines.

Each pipeline stage lives on one device of a dedicated 1-D ``pp`` mesh
axis.  At the end of every schedule slot, all stages' outbound boundary
payloads shift one hop together — activations ``s -> s+1``, activation
gradients ``s -> s-1`` — as ONE ``lax.ppermute`` over the mesh (the
XLA collective that rides ICI on a real TPU slice), instead of K-1
host-mediated point-to-point copies.

Payloads are heterogeneous per stage (different boundary shapes), so
they ship as length-prefixed byte envelopes: a small JSON header (names,
microbatch ids, shapes, dtypes) followed by the raw tensor bytes,
padded to a common bucket size across stages (ppermute requires uniform
shard shapes; the bucket rounding bounds the jit cache).
"""
from __future__ import annotations

import json
from typing import Dict, List, Tuple

import numpy as np

from .. import platform as _platform  # noqa: F401 - shard_map alias shim
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["RingShifter", "PermuteTransport", "pack_envelope",
           "unpack_envelope"]

_PAD_BUCKET = 4096  # pad envelopes to multiples of this (jit-cache bound)


def pack_envelope(named: Dict[Tuple[str, int], np.ndarray]) -> bytes:
    """(name, microbatch) -> array, serialized as header + raw bytes."""
    header = []
    bufs = []
    for (name, m), arr in sorted(named.items()):
        arr = np.ascontiguousarray(arr)
        header.append([name, int(m), list(arr.shape), str(arr.dtype)])
        bufs.append(arr.tobytes())
    h = json.dumps(header).encode("utf-8")
    return len(h).to_bytes(4, "little") + h + b"".join(bufs)


def unpack_envelope(buf: bytes) -> Dict[Tuple[str, int], np.ndarray]:
    if len(buf) < 4:
        return {}
    hlen = int.from_bytes(buf[:4], "little")
    if hlen == 0:
        return {}
    header = json.loads(buf[4:4 + hlen].decode("utf-8"))
    out: Dict[Tuple[str, int], np.ndarray] = {}
    off = 4 + hlen
    for name, m, shape, dtype in header:
        n = int(np.prod(shape)) if shape else 1
        nbytes = n * np.dtype(dtype).itemsize
        arr = np.frombuffer(buf[off:off + nbytes],
                            dtype=dtype).reshape(shape).copy()
        out[(name, int(m))] = arr
        off += nbytes
    return out


class RingShifter:
    """One-hop byte shifter over a ``pp`` mesh axis via ``ppermute``."""

    def __init__(self, devices):
        self.K = len(devices)
        if self.K < 2:
            raise ValueError("RingShifter needs >= 2 devices")
        self.mesh = Mesh(np.array(devices), ("pp",))
        self._sharding = NamedSharding(self.mesh, P("pp", None))
        self._fns: Dict[int, object] = {}

    def _fn(self, direction: int):
        f = self._fns.get(direction)
        if f is None:
            K = self.K
            if direction > 0:
                perm = [(i, i + 1) for i in range(K - 1)]
            else:
                perm = [(i, i - 1) for i in range(1, K)]

            def shift_block(x):  # [1, P] uint8 per shard
                return jax.lax.ppermute(x, "pp", perm)

            f = jax.jit(jax.shard_map(
                shift_block, mesh=self.mesh,
                in_specs=P("pp", None), out_specs=P("pp", None)))
            self._fns[direction] = f
        return f

    def shift(self, payloads: List[bytes], direction: int = 1
              ) -> List[bytes]:
        """Move per-stage byte payloads one hop (+1 = toward later
        stages, -1 = toward earlier).  Stage ``s``'s return value is
        what stage ``s -/+ 1`` sent; ring wrap-around deliveries are
        dropped (the edge stages send/receive nothing off the end)."""
        assert len(payloads) == self.K
        width = max(4, max(len(p) for p in payloads))
        width = ((width + _PAD_BUCKET - 1) // _PAD_BUCKET) * _PAD_BUCKET
        grid = np.zeros((self.K, width), dtype=np.uint8)
        for i, p in enumerate(payloads):
            if p:
                grid[i, :len(p)] = np.frombuffer(p, dtype=np.uint8)
        x = jax.device_put(grid, self._sharding)
        out = np.asarray(self._fn(1 if direction > 0 else -1)(x))
        res: List[bytes] = []
        for i in range(self.K):
            src = i - 1 if direction > 0 else i + 1
            if src < 0 or src >= self.K:
                res.append(b"")
            else:
                res.append(out[i].tobytes())
        return res


class PermuteTransport:
    """Slot-synchronous boundary transport for the concurrent runner:
    stages stage their outbound tensors during the slot; ``end_slot``
    moves everything one hop with two collectives (activations forward,
    gradients backward) and lands results in per-stage inboxes."""

    def __init__(self, num_stages: int, devices):
        self.K = num_stages
        self.shifter = RingShifter(list(devices)[:num_stages])
        self._out_fwd: List[Dict] = [dict() for _ in range(num_stages)]
        self._out_bwd: List[Dict] = [dict() for _ in range(num_stages)]
        self._inbox: List[Dict] = [dict() for _ in range(num_stages)]

    def put(self, kind: str, name: str, m: int, value, src: int,
            dsts: List[int]) -> None:
        for d in dsts:
            if abs(d - src) != 1:
                raise ValueError(
                    f"permute transport requires adjacent stages; "
                    f"{name!r} crosses {src} -> {d}")
        box = self._out_fwd if kind == "act" else self._out_bwd
        box[src][(name, int(m))] = np.asarray(value)

    def get(self, kind: str, name: str, m: int, dst: int):
        try:
            return self._inbox[dst].pop((name, int(m)))
        except KeyError:
            raise RuntimeError(
                f"stage {dst} expected {kind} {name!r} (microbatch {m}) "
                "but the previous slot's permute did not deliver it — "
                "schedule/dependency bug") from None

    def end_slot(self) -> None:
        if any(self._out_fwd):
            moved = self.shifter.shift(
                [pack_envelope(b) if b else b"" for b in self._out_fwd],
                direction=1)
            for s, buf in enumerate(moved):
                self._inbox[s].update(unpack_envelope(buf))
            self._out_fwd = [dict() for _ in range(self.K)]
        if any(self._out_bwd):
            moved = self.shifter.shift(
                [pack_envelope(b) if b else b"" for b in self._out_bwd],
                direction=-1)
            for s, buf in enumerate(moved):
                self._inbox[s].update(unpack_envelope(buf))
            self._out_bwd = [dict() for _ in range(self.K)]
