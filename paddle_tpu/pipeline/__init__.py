"""Pipeline parallelism: program-level stage transpiler + microbatch
schedules + drivers (ROADMAP item 2; survey §2.7 — the reference's
layer-placement precedent is ``legacy/gserver/.../ParallelNeuralNetwork.h``).

Typical use::

    import paddle_tpu.pipeline as pipe

    t = pipe.PipelineTranspiler()
    pp = t.transpile(prog, startup, num_stages=4, num_microbatches=8,
                     loss_name=loss.name)
    trainer = pipe.PipelineTrainer(pp, schedule="1f1b",
                                   devices=jax.devices()[:4]).init()
    res = trainer.run(feed)        # one minibatch: M microbatches + opt
    res.loss, res.bubble_fraction, res.stage_utilization

Stage cuts: mark layers with ``fluid.pipeline_stage_guard(k)`` while
building the program, pass explicit ``cut_points``, or let the
transpiler cost-balance (``balance="xla"`` refines the split with real
XLA flops from the PR-7 cost attribution).  Multi-host stages run one
:class:`PipelineStageWorker` per process over the striped RPC
transport.
"""
from __future__ import annotations

from .schedule import (SCHEDULES, gpipe_bubble_bound, gpipe_order,
                       one_f_one_b_order, simulate_slots,
                       slot_bubble_fraction, stage_orders,
                       validate_orders)
from .transpiler import (PipelineProgram, PipelineTranspiler,
                         StagePrograms, balanced_cut_points,
                         op_flops_estimate, xla_stage_flops)
from .runner import PipelineTrainer, StepResult
from .rpc import PipelineStageWorker, StageMailbox

__all__ = [
    "PipelineTranspiler",
    "PipelineProgram",
    "StagePrograms",
    "PipelineTrainer",
    "StepResult",
    "PipelineStageWorker",
    "StageMailbox",
    "SCHEDULES",
    "stage_orders",
    "gpipe_order",
    "one_f_one_b_order",
    "simulate_slots",
    "slot_bubble_fraction",
    "validate_orders",
    "gpipe_bubble_bound",
    "balanced_cut_points",
    "op_flops_estimate",
    "xla_stage_flops",
]
