"""Pipeline drivers: scan-mode (run_steps) and slot-clocked concurrent.

Two execution modes over a transpiled :class:`PipelineProgram`:

- **scan mode** (default): GPipe semantics on the executor's existing
  ``run_steps`` scan machinery — each stage's forward runs its M
  microbatches as ONE ``lax.scan`` dispatch (microbatch = scan step),
  boundary activations travel between stages as stacked ``[M, ...]``
  arrays, backwards run in reverse stage order, and each stage's
  optimizer block runs once on the accumulated mean gradient.  This is
  the numerics-reference path (bit-comparable to the single-process
  run) and the lowest-dispatch-overhead sequential execution.

- **concurrent slot mode**: one worker thread per stage (each optionally
  pinned to its own device), stepping a GPipe or 1F1B slot grid
  (pipeline/schedule.py) with a barrier per slot.  Stages genuinely
  overlap — the measured per-stage busy time vs wall time yields the
  real bubble fraction and per-stage utilization, exported through the
  observability plane (``pipeline.*`` gauges + the ``pipeline`` debug
  page).  Boundary tensors move through an in-process store, or via
  collective permute on a dedicated ``pp`` mesh axis
  (``transport="permute"``, pipeline/permute.py).

Multi-host stages ride the striped RPC transport instead — see
pipeline/rpc.py.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..core.executor import Executor, Scope
from ..observability import debug_server as _debug_server
from ..observability import stats as _obs_stats
from . import schedule as _sched
from .transpiler import PipelineProgram

__all__ = ["PipelineTrainer", "StepResult"]

_pipe_metrics = None
_last_run_summary: Dict[str, object] = {}


def _pm():
    """Cached pipeline metric handles (see executor._em)."""
    global _pipe_metrics
    m = _pipe_metrics
    if m is None:
        import types as _t
        sc = _obs_stats.scope("pipeline")
        m = _t.SimpleNamespace(
            scope=sc,
            steps=sc.counter("steps"),
            microbatches=sc.counter("microbatches"),
            bubble=sc.gauge(
                "bubble_fraction",
                "measured idle fraction of the last concurrent pipeline "
                "step: 1 - sum(stage busy)/(K * wall)"),
            bubble_slots=sc.gauge(
                "bubble_fraction_slots",
                "schedule-level bubble of the last step's slot grid "
                "(equals (K-1)/(M+K-1) for GPipe)"),
        )
        _pipe_metrics = m
    return m


def _pipeline_statusz() -> dict:
    return dict(_last_run_summary)


_debug_server.register_provider("pipeline", _pipeline_statusz)


@dataclass
class StepResult:
    """One minibatch through the pipeline."""

    loss: Optional[float]
    microbatch_losses: Optional[np.ndarray]
    wall_ms: float
    schedule: str
    mode: str                      # "scan" | "slots"
    bubble_fraction: Optional[float] = None        # measured (slots mode)
    bubble_fraction_slots: Optional[float] = None  # schedule-level
    stage_utilization: List[float] = field(default_factory=list)
    stage_busy_ms: List[float] = field(default_factory=list)
    stage_activation_bytes: List[int] = field(default_factory=list)


class _StageExecutor(Executor):
    """Executor pinned to one device (pipeline stage placement): feeds,
    state and rng are committed to the stage's device so the jitted
    stage programs execute there, letting stages overlap."""

    def __init__(self, device=None):
        super().__init__()
        self._device = device

    def _place(self, v):
        if self._device is None:
            return v
        import jax
        return jax.device_put(v, self._device)

    def _put_feed(self, arr):
        return self._place(arr)

    def _put_rng(self, rng):
        return self._place(rng)

    def _put_state(self, name, val):
        return self._place(val)


def _make_stage_parallel_executor(build_strategy, stage_program):
    """Stage executor for the pp×dp(×ZeRO) composition (SCAN mode
    only — PipelineTrainer.run gates the rest): each stage's programs
    run as ONE sharded jit over a dp mesh — state sharded per the
    BuildStrategy (kReduce = ZeRO), microbatch feeds sharded along
    their WITHIN-microbatch batch axis.  Scan-mode feeds are stacked
    ``[M, batch, ...]``, so the batch axis is axis 1, not axis 0 (the
    plain ParallelExecutor convention); axis-0 sharding would partition
    the scan, which is wrong by construction."""
    from ..parallel.parallel_executor import ParallelExecutor

    class _StagePE(ParallelExecutor):
        def run(self, program=None, feed=None, fetch_list=None,
                scope=None, return_numpy=True, **kwargs):
            # Executor-shaped signature: the pipeline drivers call every
            # stage executor positionally as run(program, ...)
            return ParallelExecutor.run(
                self, fetch_list=fetch_list, feed=feed, program=program,
                scope=scope, return_numpy=return_numpy, **kwargs)

        def _put_feed(self, arr):
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            dp = self.mesh.shape[self._dp_axis]
            nd = getattr(arr, "ndim", 0)
            if nd >= 2 and arr.shape[1] % dp == 0 and arr.shape[1] > 0:
                spec = P(None, self._dp_axis, *([None] * (nd - 2)))
                return jax.device_put(arr, NamedSharding(self.mesh, spec))
            return jax.device_put(arr, self._replicated())

    return _StagePE(main_program=stage_program,
                    build_strategy=build_strategy)


class PipelineTrainer:
    """Drive a transpiled pipeline for training steps.

    ``devices``: one jax device per stage enables the concurrent slot
    mode (stages genuinely overlap); without devices the scan mode runs
    everything sequentially on the default device.  ``transport``:
    ``"local"`` (in-process store / device-to-device put) or
    ``"permute"`` (collective permute over a ``pp`` mesh axis — requires
    ``devices`` and adjacent-only boundaries).  ``schedule`` may be
    reassigned between steps (``tr.schedule = "1f1b"``): it only orders
    the slot grid, the numerics and compiled executables are identical.
    """

    def __init__(self, pipeline_program: PipelineProgram,
                 schedule: str = "gpipe",
                 devices: Optional[List] = None,
                 concurrent: Optional[bool] = None,
                 transport: str = "local",
                 parallel=None):
        self.pp = pipeline_program
        self.K = pipeline_program.num_stages
        self.M = pipeline_program.num_microbatches
        # pp×dp(×ZeRO) composition: a BuildStrategy turns every stage
        # executor into a dp-mesh ParallelExecutor (state sharded per
        # reduce_strategy — kReduce is the ZeRO cell of the reshard
        # matrix); scan/sequential modes only, the slot runner pins
        # stages to single devices instead
        self.parallel = parallel
        if parallel is not None and devices is not None:
            raise ValueError(
                "parallel= (dp mesh per stage) and devices= (one device "
                "per stage) are mutually exclusive stage placements")
        if schedule not in ("gpipe", "1f1b", "one_f_one_b"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.schedule = "1f1b" if schedule == "one_f_one_b" else schedule
        if devices is not None and len(devices) < self.K:
            raise ValueError(
                f"{self.K} stages need {self.K} devices, got "
                f"{len(devices)}")
        self.devices = list(devices)[:self.K] if devices else None
        self.concurrent = (bool(concurrent) if concurrent is not None
                           else self.devices is not None)
        if transport not in ("local", "permute"):
            raise ValueError(f"unknown transport {transport!r}")
        if transport == "permute":
            if not self.devices:
                raise ValueError("transport='permute' needs per-stage "
                                 "devices (the pp mesh axis)")
            if not pipeline_program.adjacent_only():
                raise ValueError(
                    "transport='permute' requires adjacent-only stage "
                    "boundaries (every send crosses one hop); this "
                    "pipeline has skip boundaries — use the local or "
                    "RPC transport")
        self.transport = transport
        if self.parallel is not None:
            self.executors = [
                _make_stage_parallel_executor(self.parallel,
                                              st.fwd_program)
                for st in self.pp.stages]
        else:
            self.executors = [
                _StageExecutor(self.devices[s] if self.devices else None)
                for s in range(self.K)]
        self.scopes = [Scope() for _ in range(self.K)]
        self._initialized = False

    # -- lifecycle ---------------------------------------------------------
    def init(self) -> "PipelineTrainer":
        """Run every stage's startup program (named initializer draws
        make the union of stage scopes bit-identical to the
        single-process init)."""
        for st, exe, scope in zip(self.pp.stages, self.executors,
                                  self.scopes):
            exe.run(st.startup_program, scope=scope)
        self._initialized = True
        return self

    def state_dict(self) -> Dict[str, np.ndarray]:
        """All persistable stage state (params, moments, accumulators,
        LR counters) as host arrays, stage scopes merged."""
        out: Dict[str, np.ndarray] = {}
        for scope in self.scopes:
            for name in scope.local_names():
                out[name] = np.asarray(scope.find_var(name))
        return out

    # -- sharded checkpoints (paddle_tpu/checkpoint/) ----------------------
    def _stage_persist_names(self, k: int) -> List[str]:
        """Persistable vars a stage owns: declared persistable in any of
        its programs AND present in its scope (grad @ACC accumulators
        only exist after the first backward)."""
        from ..core.executor import RNG_STATE_VAR
        st = self.pp.stages[k]
        progs = [st.startup_program, st.fwd_program, st.bwd_program,
                 st.opt_program]
        names = set()
        for p in progs:
            if p is None:
                continue
            for v in p.global_block.vars.values():
                if v.persistable and v.name != RNG_STATE_VAR:
                    names.add(v.name)
        scope = self.scopes[k]
        return sorted(n for n in names if scope.find_var(n) is not None)

    def save_checkpoint(self, root: str, step: int,
                        commit: bool = True) -> bool:
        """Write one checkpoint piece per stage (writer ``stage<k>``)
        and two-phase commit the step.  Pipeline sharding partitions the
        VAR SET, not rows — each stage's vars are whole shards, and vars
        replicated across stages (the LR closure every optimizing stage
        carries) are marked replicated so any stage's copy restores
        them.  The manifest is topology-independent: restore onto a
        different stage count or a plain single host re-shards from the
        same files (``checkpoint.restore_scope`` / ``load_vars``)."""
        from .. import checkpoint as _ckpt
        per_stage = [self._stage_persist_names(k) for k in range(self.K)]
        count: Dict[str, int] = {}
        for names in per_stage:
            for n in names:
                count[n] = count.get(n, 0) + 1
        writers = [f"stage{k}" for k in range(self.K)]
        topo = {"kind": "pipeline", "pp": self.K,
                "schedule": self.schedule}
        if self.parallel is not None:
            topo["dp_mesh"] = dict(self.parallel.mesh_shape or {})
            from ..parallel.strategy import ReduceStrategy
            topo["zero"] = (self.parallel.reduce_strategy
                            == ReduceStrategy.kReduce)
        for k, names in enumerate(per_stage):
            scope = self.scopes[k]
            arrays, extents = {}, {}
            for n in names:
                arr = np.asarray(scope.find_var(n))
                arrays[n] = arr
                if count[n] > 1:
                    # stage-replicated (LR closure): identical
                    # deterministic evolution on every stage
                    extents[n] = {"var": n, "offset": None, "rows": None,
                                  "global_shape": list(arr.shape)}
            _ckpt.write_piece(root, step, f"stage{k}", arrays,
                              extents=extents, topology=topo,
                              expected_writers=writers)
        if commit:
            return _ckpt.try_commit(root, step, writers)
        return False

    def restore_checkpoint(self, root: str, step: Optional[int] = None,
                           verify: bool = True) -> int:
        """Hydrate every stage scope from the newest (or given) COMPLETE
        step — written by ANY topology (a different stage count, a
        plain single-host save, a pserver fleet).  Restored values are
        re-placed by each stage executor on its next dispatch."""
        from .. import checkpoint as _ckpt
        if step is None:
            step = _ckpt.latest_complete_step(root)
            if step is None:
                raise _ckpt.CheckpointError(
                    f"no COMPLETE checkpoint step under {root!r}")
        from .transpiler import ACC_SUFFIX
        man = _ckpt.load_manifest(root, step)
        have = man.vars()
        for k in range(self.K):
            names = self._stage_persist_names(k)
            # <grad>@ACC microbatch accumulators are pipeline-transpiler
            # transients, zeroed between minibatches: a checkpoint from a
            # NON-pipeline topology legitimately lacks them — keep the
            # startup zeros.  Anything else missing is a real hole.
            missing = [n for n in names if n not in have
                       and not n.endswith(ACC_SUFFIX)]
            if missing:
                raise _ckpt.CheckpointError(
                    f"checkpoint step {step} is missing stage {k} "
                    f"persistable vars {missing[:8]}")
            names = [n for n in names if n in have]
            vals = _ckpt.load_vars(root, step,
                                   {n: (None, None) for n in names},
                                   verify=verify)
            scope = self.scopes[k]
            for n, v in vals.items():
                scope.set_var(n, v)
            placed = getattr(self.executors[k], "_placed", None)
            if placed is not None:
                placed.clear()
        return step

    # -- feed plumbing -----------------------------------------------------
    def _split_feed(self, feed: Dict[str, object]):
        from .transpiler import split_microbatches
        return split_microbatches(feed, self.M)

    # -- public API --------------------------------------------------------
    def run(self, feed: Dict[str, object],
            mode: Optional[str] = None) -> StepResult:
        """One minibatch.  ``mode``: None = auto (slots when concurrent,
        else scan), or force ``"scan"`` / ``"slots"`` /
        ``"sequential"`` (the naive per-microbatch stage-by-stage
        baseline the bench compares against)."""
        if not self._initialized:
            raise RuntimeError("call PipelineTrainer.init() first")
        if mode is None:
            mode = "slots" if self.concurrent else "scan"
        if self.parallel is not None and mode != "scan":
            # sequential mode feeds per-microbatch [batch, ...] arrays
            # whose axis 1 is a FEATURE axis — the stage PE's scan-
            # stacked feed sharding would partition the wrong axis; and
            # the slot runner wants one device per stage, not a mesh
            raise ValueError(
                "parallel= stage composition supports scan mode only "
                f"(got mode={mode!r})")
        t0 = time.perf_counter()
        if mode == "slots":
            res = self._run_slots(feed)
        elif mode == "scan":
            res = self._run_scan(feed)
        elif mode == "sequential":
            res = self._run_sequential(feed)
        else:
            raise ValueError(f"unknown run mode {mode!r}")
        res.wall_ms = (time.perf_counter() - t0) * 1e3
        self._record(res, feed)
        return res

    def _record(self, res: StepResult, feed) -> None:
        m = _pm()
        m.steps.inc()
        m.microbatches.inc(self.M)
        if res.bubble_fraction is not None:
            m.bubble.set(res.bubble_fraction)
        if res.bubble_fraction_slots is not None:
            m.bubble_slots.set(res.bubble_fraction_slots)
        mb = next((np.asarray(v).shape[0] // self.M
                   for v in feed.values()
                   if np.asarray(v).ndim >= 1), 1)
        res.stage_activation_bytes = [
            st.activation_bytes(mb) for st in self.pp.stages]
        for s in range(self.K):
            m.scope.gauge(f"stage_activation_bytes.s{s}").set(
                res.stage_activation_bytes[s])
            if res.stage_utilization:
                m.scope.gauge(f"stage_utilization.s{s}").set(
                    res.stage_utilization[s])
        _last_run_summary.update({
            "schedule": res.schedule, "mode": res.mode,
            "num_stages": self.K, "num_microbatches": self.M,
            "transport": self.transport,
            "wall_ms": round(res.wall_ms, 3),
            "bubble_fraction": res.bubble_fraction,
            "bubble_fraction_slots": res.bubble_fraction_slots,
            "gpipe_bubble_bound": _sched.gpipe_bubble_bound(self.K,
                                                            self.M),
            "stage_utilization": [round(u, 4)
                                  for u in res.stage_utilization],
            "stage_activation_bytes": res.stage_activation_bytes,
        })

    # -- scan mode (sequential GPipe on run_steps) -------------------------
    def _run_scan(self, feed) -> StepResult:
        pp = self.pp
        stacked, _ = self._split_feed(feed)
        acts: Dict[str, np.ndarray] = {}
        for st, exe, scope in zip(pp.stages, self.executors, self.scopes):
            sfeed = {n: stacked[n] for n in st.fwd_feeds}
            sfeed.update({n: acts[n] for n in st.recv_acts_fwd})
            outs = exe.run_steps(st.fwd_program, feed=sfeed,
                                 fetch_list=st.fwd_fetches, scope=scope)
            acts.update(zip(st.fwd_fetches, outs))
        grads: Dict[str, np.ndarray] = {}
        for st, exe, scope in zip(reversed(pp.stages),
                                  reversed(self.executors),
                                  reversed(self.scopes)):
            bfeed = {n: acts[n] for n in st.stash}
            bfeed.update({n: acts[n] for n in st.recv_acts_bwd})
            bfeed.update({n: stacked[n] for n in st.bwd_feeds})
            bfeed.update({n: grads[n] for n in st.recv_grads})
            outs = exe.run_steps(st.bwd_program, feed=bfeed,
                                 fetch_list=st.bwd_fetches, scope=scope)
            grads.update(zip(st.bwd_fetches, outs))
        for st, exe, scope in zip(pp.stages, self.executors, self.scopes):
            if st.opt_program is not None:
                exe.run(st.opt_program, scope=scope)
        mb_losses = None
        loss = None
        if pp.loss_name and pp.loss_name in acts:
            mb_losses = np.asarray(acts[pp.loss_name]).reshape(self.M)
            loss = float(mb_losses.mean())
        return StepResult(loss=loss, microbatch_losses=mb_losses,
                          wall_ms=0.0, schedule=self.schedule,
                          mode="scan")

    # -- naive sequential baseline -----------------------------------------
    def _run_sequential(self, feed) -> StepResult:
        """Naive sequential stage execution: every microbatch's forward
        and backward dispatched stage by stage on ONE thread, no
        overlap, no scan amortization — the baseline the pipeline
        schedules are measured against (bench.py ``pipeline``)."""
        pp, M = self.pp, self.M
        _, per_mb = self._split_feed(feed)
        acts: Dict[tuple, np.ndarray] = {}
        mb_losses = np.zeros(M, dtype=np.float64)
        for m in range(M):
            for st, exe, scope in zip(pp.stages, self.executors,
                                      self.scopes):
                sfeed = {n: per_mb[m][n] for n in st.fwd_feeds}
                sfeed.update({n: acts[(n, m)] for n in st.recv_acts_fwd})
                outs = exe.run(st.fwd_program, feed=sfeed,
                               fetch_list=st.fwd_fetches, scope=scope,
                               sync=True)
                for n, v in zip(st.fwd_fetches, outs):
                    acts[(n, m)] = v
                if st.idx == self.K - 1 and pp.loss_name:
                    mb_losses[m] = float(np.asarray(
                        outs[st.fwd_fetches.index(pp.loss_name)]))
        grads: Dict[tuple, np.ndarray] = {}
        for m in range(M):
            for st, exe, scope in zip(reversed(pp.stages),
                                      reversed(self.executors),
                                      reversed(self.scopes)):
                bfeed = {n: per_mb[m][n] for n in st.bwd_feeds}
                for n in st.stash + st.recv_acts_bwd:
                    bfeed[n] = acts[(n, m)]
                for n in st.recv_grads:
                    bfeed[n] = grads[(n, m)]
                outs = exe.run(st.bwd_program, feed=bfeed,
                               fetch_list=st.bwd_fetches, scope=scope,
                               sync=True)
                for n, v in zip(st.bwd_fetches, outs):
                    grads[(n, m)] = v
        for st, exe, scope in zip(pp.stages, self.executors, self.scopes):
            if st.opt_program is not None:
                exe.run(st.opt_program, scope=scope, sync=True)
        loss = float(mb_losses.mean()) if pp.loss_name else None
        return StepResult(loss=loss, microbatch_losses=mb_losses.copy(),
                          wall_ms=0.0, schedule=self.schedule,
                          mode="sequential")

    # -- concurrent slot mode ----------------------------------------------
    def _run_slots(self, feed) -> StepResult:
        pp, K, M = self.pp, self.K, self.M
        orders = _sched.stage_orders(self.schedule, K, M)
        _sched.validate_orders(orders, M)
        grid = _sched.simulate_slots(orders)
        _, per_mb = self._split_feed(feed)

        if self.transport == "permute":
            from .permute import PermuteTransport
            store = PermuteTransport(K, self.devices)
        else:
            store = _LocalTransport()
        barrier = threading.Barrier(K, action=store.end_slot)
        busy = [0.0] * K
        mb_losses = np.zeros(M, dtype=np.float64)
        errors: List[BaseException] = []

        def worker(s: int) -> None:
            st = pp.stages[s]
            exe, scope = self.executors[s], self.scopes[s]
            retained: Dict[tuple, np.ndarray] = {}
            try:
                for row in grid:
                    action = row[s]
                    if action is not None and not errors:
                        kind, m = action
                        t0 = time.perf_counter()
                        if kind == "F":
                            sfeed = {n: per_mb[m][n] for n in st.fwd_feeds}
                            for n in st.recv_acts:
                                v = store.get("act", n, m, s)
                                if n in st.recv_acts_fwd:
                                    sfeed[n] = v
                                if n in st.recv_acts_bwd:
                                    retained[(n, m)] = v
                            outs = exe.run(st.fwd_program, feed=sfeed,
                                           fetch_list=st.fwd_fetches,
                                           scope=scope, sync=True)
                            vals = dict(zip(st.fwd_fetches, outs))
                            for n in st.stash:
                                retained[(n, m)] = vals[n]
                            for n, dsts in st.send_acts.items():
                                store.put("act", n, m, vals[n], s, dsts)
                            if s == K - 1 and pp.loss_name:
                                mb_losses[m] = float(
                                    np.asarray(vals[pp.loss_name]))
                        else:
                            bfeed = {n: per_mb[m][n] for n in st.bwd_feeds}
                            for n in st.stash + st.recv_acts_bwd:
                                bfeed[n] = retained.pop((n, m))
                            for n in st.recv_grads:
                                bfeed[n] = store.get("grad", n, m, s)
                            outs = exe.run(st.bwd_program, feed=bfeed,
                                           fetch_list=st.bwd_fetches,
                                           scope=scope, sync=True)
                            vals = dict(zip(st.bwd_fetches, outs))
                            for n, dsts in st.send_grads.items():
                                store.put("grad", n, m, vals[n], s, dsts)
                        busy[s] += time.perf_counter() - t0
                    barrier.wait()
                if st.opt_program is not None and not errors:
                    exe.run(st.opt_program, scope=scope, sync=True)
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)
                barrier.abort()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(s,), daemon=True)
                   for s in range(K)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            # a worker failure aborts the barrier; peers then raise
            # BrokenBarrierError — surface the root cause, not the echo
            real = [e for e in errors
                    if not isinstance(e, threading.BrokenBarrierError)]
            raise (real or errors)[0]
        wall = time.perf_counter() - t0
        util = [b / wall if wall > 0 else 0.0 for b in busy]
        loss = (float(mb_losses.mean())
                if pp.loss_name and K >= 1 else None)
        return StepResult(
            loss=loss, microbatch_losses=mb_losses.copy(), wall_ms=0.0,
            schedule=self.schedule, mode="slots",
            bubble_fraction=max(0.0, 1.0 - sum(busy) / (K * wall))
            if wall > 0 else None,
            bubble_fraction_slots=_sched.slot_bubble_fraction(grid),
            stage_utilization=util,
            stage_busy_ms=[b * 1e3 for b in busy])


class _LocalTransport:
    """In-process boundary store for the slot runner: producers write
    during their slot, consumers read in a later slot (the per-slot
    barrier is the happens-before edge)."""

    def __init__(self):
        self._store: Dict[tuple, object] = {}

    def put(self, kind, name, m, value, src, dsts) -> None:
        self._store[(kind, name, int(m))] = value

    def get(self, kind, name, m, dst):
        try:
            return self._store[(kind, name, int(m))]
        except KeyError:
            raise RuntimeError(
                f"stage {dst} expected {kind} {name!r} (microbatch {m}) "
                "before its producer ran — schedule dependency bug"
            ) from None

    def end_slot(self) -> None:
        pass
