"""Graph-level autodiff: append gradient ops to the program.

Reference: ``python/paddle/fluid/backward.py:469`` (``append_backward``) —
find the op path to the loss, emit one grad op per forward op in reverse
order, sum duplicate gradient contributions, prune no-grad branches.

TPU-native difference: grad ops here carry no hand-written kernels.  A grad
op of type ``<op>_grad`` lowers through ``jax.vjp`` of the forward lowering
rule by default (``registry.vjp_grad``), so every registered op is
differentiable for free; ops whose forward consumes randomness register an
explicit grad rule (e.g. dropout uses its saved Mask).  Because the whole
block is jitted as one XLA computation, the vjp's re-traced forward is
merged with the original forward by XLA CSE.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from . import registry
from .program import (
    EMPTY_VAR,
    OP_ROLE_ATTR,
    OP_ROLE_VAR_ATTR,
    Block,
    Operator,
    OpRole,
    Variable,
    grad_var_name,
)
from .registry import GRAD_OP_SUFFIX
from .types import VarType, is_float


def _find_relevant_ops(block: Block, target: str) -> Set[int]:
    """Backward reachability: indices of ops whose outputs (transitively)
    feed the target var (reference ``_find_op_path_``, backward.py:645)."""
    needed = {target}
    relevant: Set[int] = set()
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if needed & set(op.output_arg_names()):
            relevant.add(idx)
            needed |= {n for n in op.input_arg_names() if n}
    return relevant


def _grad_allowed(block: Block, name: str, no_grad_set: Set[str]) -> bool:
    if not name or name == EMPTY_VAR or name in no_grad_set:
        return False
    v = block.var_or_none(name)
    if v is None:
        return True  # temp without desc: allow, dtype unknown
    if v.stop_gradient:
        return False
    return v.dtype is None or is_float(v.dtype)


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence[str]] = None,
    no_grad_set: Optional[Set[str]] = None,
) -> List[tuple]:
    """Append grad ops for ``loss``; return [(param_var, grad_var)] pairs.

    Only block-0 programs for now; grad-of-control-flow (reference
    while_op.cc:101 reverse sub-block machinery) arrives with the sequence
    stack, where RNN recurrence is a scan op whose vjp is the reverse scan.
    """
    assert loss.shape in ((1,), ()), (
        f"loss must be a scalar, got shape {loss.shape}"
    )
    return _append_backward_impl([loss], None, parameter_list, no_grad_set)


def calc_gradient(targets, inputs, target_gradients=None,
                  no_grad_set=None):
    """Gradients of ``targets`` w.r.t. ``inputs`` (reference
    backward.py:685 calc_gradient): seeds are ``target_gradients`` (or
    ones over each target); returns the grad Variable per input (None
    when the input does not influence any target — see reference semantics)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(
            target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    if target_gradients is not None and \
            len(target_gradients) != len(targets):
        raise ValueError(
            f"calc_gradient: {len(targets)} targets but "
            f"{len(target_gradients)} target_gradients")
    pairs = _append_backward_impl(list(targets), target_gradients,
                                  [v.name if isinstance(v, Variable) else v
                                   for v in inputs],
                                  no_grad_set, inputs_need_params=False)
    by_name = {p.name: g for p, g in pairs}
    return [by_name.get(v.name if isinstance(v, Variable) else v)
            for v in inputs]


def _append_backward_impl(
    targets: List[Variable],
    target_gradients: Optional[List[Variable]],
    parameter_list: Optional[Sequence[str]],
    no_grad_set: Optional[Set[str]],
    inputs_need_params: bool = True,
) -> List[tuple]:
    block = targets[0].block
    program = block.program
    no_grad = set(no_grad_set or ())
    for v in block.vars.values():
        if v.stop_gradient:
            no_grad.add(v.name)

    relevant = set()
    for t in targets:
        relevant |= _find_relevant_ops(block, t.name)

    # contributions: var name -> list of grad var names feeding it
    contribs: Dict[str, List[str]] = {}
    # monotone per-var counter for @RENAME@k grad names (stays unique even
    # when an in-place rewrite resets the contribution list below)
    grad_counts: Dict[str, int] = {}

    def add_contrib(var_name: str, grad_name: str):
        contribs.setdefault(var_name, []).append(grad_name)

    def resolve_out_grad(var_name: str) -> Optional[str]:
        """Gradient var for ``var_name``, emitting a sum op when several
        partials exist (reference ``_addup_repetitive_outputs_``)."""
        lst = contribs.get(var_name)
        if not lst:
            return None
        if len(lst) == 1:
            return lst[0]
        g = grad_var_name(var_name)
        _make_grad_var(block, g, var_name)
        block.append_op(
            "sum", {"X": list(lst)}, {"Out": [g]},
            {OP_ROLE_ATTR: OpRole.Backward},
        )
        contribs[var_name] = [g]
        return g

    # seeds: d target / d target = 1 (reference scale_loss_grad
    # boundary), or the caller-supplied target_gradients (calc_gradient)
    n_fwd_ops = len(block.ops)  # before any seed ops are appended
    for ti, t in enumerate(targets):
        tg = target_gradients[ti] if target_gradients else None
        if tg is not None:
            if tuple(tg.shape) != tuple(t.shape):
                raise ValueError(
                    f"target_gradient {tg.name!r} shape {tg.shape} != "
                    f"target {t.name!r} shape {t.shape}")
            add_contrib(t.name, tg.name)
            continue
        t_grad = grad_var_name(t.name)
        block.create_var(name=t_grad, shape=t.shape, dtype=t.dtype)
        block.append_op(
            "fill_constant",
            {},
            {"Out": [t_grad]},
            {
                "shape": list(t.shape),
                "value": 1.0,
                "dtype": t.dtype,
                OP_ROLE_ATTR: OpRole.Backward | OpRole.Loss,
            },
        )
        add_contrib(t.name, t_grad)
    for idx in range(n_fwd_ops - 1, -1, -1):
        if idx not in relevant:
            continue
        op = block.ops[idx]
        if op.attr(OP_ROLE_ATTR, OpRole.Forward) != OpRole.Forward:
            continue
        if op.type == "while" and not op.attr("max_iters"):
            raise NotImplementedError(
                "gradient of While needs a static trip-count bound: "
                "While(cond, max_iters=N) — the backward pass re-runs the "
                "loop as an N-step masked scan (the functional form of "
                "while_grad's step-scope replay, while_op.cc:101)")
        if op.type in ("static_rnn", "dynamic_rnn", "while",
                       "conditional_block"):
            # grad re-traces the sub-block; rng-consuming ops inside would
            # draw fresh keys and silently corrupt gradients — reject them
            sub = program.blocks[op.attr("sub_block")]
            for sop in sub.ops:
                if registry.has(sop.type) and registry.get(sop.type).stateful:
                    raise NotImplementedError(
                        f"op {sop.type!r} inside a {op.type} sub-block is "
                        f"not differentiable (rng re-traced in the reverse "
                        f"pass); hoist it outside or use is_test")
        if not registry.has(op.type):
            raise KeyError(f"cannot differentiate unregistered op {op.type!r}")
        opdef = registry.get(op.type)

        # gather grads of this op's outputs
        out_grad_inputs: Dict[str, List[str]] = {}
        any_grad = False
        for slot, names in op.outputs.items():
            gs = []
            for n in names:
                g = resolve_out_grad(n) if n else None
                gs.append(g if g is not None else EMPTY_VAR)
                any_grad = any_grad or g is not None
            out_grad_inputs[slot + "@GRAD"] = gs
        if not any_grad:
            continue

        if opdef.stateful and opdef.grad is None:
            raise RuntimeError(
                f"op {op.type!r} consumes randomness/state and must register "
                f"an explicit grad rule"
            )

        # grad op inputs: fwd ins + fwd outs + out grads
        g_inputs: Dict[str, List[str]] = {}
        for slot, names in op.inputs.items():
            g_inputs[slot] = list(names)
        for slot, names in op.outputs.items():
            g_inputs[slot] = list(names)
        g_inputs.update(out_grad_inputs)

        # in-place rewrites (op input name == output name, e.g. a
        # conditional_block/while carry): the downstream cotangent was just
        # consumed via Out@GRAD; earlier writers of the var must see ONLY
        # the grad wrt the pre-op value this grad op emits (the reference's
        # _rename_arg_ SSA discipline, backward.py:135)
        for n in set(op.input_arg_names()) & set(op.output_arg_names()):
            if n and n != EMPTY_VAR and contribs.get(n):
                grad_counts[n] = grad_counts.get(n, 0) + len(contribs[n])
                contribs[n] = []

        # grad op outputs: grads of differentiable inputs (renamed when a
        # var already has a partial, summed lazily at consumption)
        g_outputs: Dict[str, List[str]] = {}
        pairs_for_role: List[str] = []
        produced = False
        for slot, names in op.inputs.items():
            if slot in opdef.no_grad_slots:
                continue
            outs = []
            for n in names:
                if not _grad_allowed(block, n, no_grad):
                    outs.append(EMPTY_VAR)
                    continue
                k = grad_counts.get(n, 0) + len(contribs.get(n, []))
                gname = grad_var_name(n) if k == 0 else f"{grad_var_name(n)}@RENAME@{k}"
                _make_grad_var(block, gname, n)
                add_contrib(n, gname)
                outs.append(gname)
                produced = True
            if any(o != EMPTY_VAR for o in outs):
                g_outputs[slot + "@GRAD"] = outs
        if not produced:
            continue

        block.append_op(
            op.type + GRAD_OP_SUFFIX,
            g_inputs,
            g_outputs,
            {
                **{k: v for k, v in op.attrs.items() if k != OP_ROLE_ATTR},
                "__fwd_out_slots__": list(op.outputs.keys()),
                OP_ROLE_ATTR: OpRole.Backward,
            },
        )
        # sparse lookup gradients are SelectedRows (selected_rows.h:32);
        # mark the grad var so regularizers/transpilers can branch on it
        if op.type == "lookup_table" and op.attrs.get("is_sparse"):
            for gn in g_outputs.get("W@GRAD", ()):
                if gn != EMPTY_VAR:
                    block.var(gn).type = VarType.SELECTED_ROWS

    # canonicalize: any var left with several partials gets its summed
    # ``<var>@GRAD`` materialized, so fetching a leaf gradient by name sees
    # the total, not one partial (reference _addup_repetitive_outputs_
    # sums eagerly; we sum lazily, so flush here).  A single surviving
    # @RENAME partial (in-place carry reset) is assigned onto the
    # canonical name too — else the fetch would see the stale pre-reset
    # partial.
    for n, lst in list(contribs.items()):
        canonical = grad_var_name(n)
        if len(lst) > 1:
            resolve_out_grad(n)
        elif lst and lst[0] != canonical and grad_counts.get(n, 0):
            _make_grad_var(block, canonical, n)
            block.append_op("assign", {"X": [lst[0]]}, {"Out": [canonical]},
                            {OP_ROLE_ATTR: OpRole.Backward})

    # collect (param, grad) pairs
    params = (
        [block.var(p) if isinstance(p, str) else p for p in parameter_list]
        if parameter_list
        else block.all_parameters()
    )
    pairs = []
    for p in params:
        if inputs_need_params and not p.trainable:
            continue
        g = resolve_out_grad(p.name)
        if g is None:
            continue
        gv = block.var(g)
        pairs.append((p, gv))
    # annotate backward ops with their (param, grad) pairs for parallel
    # lowering (reference op_role_var, multi_devices_graph_pass.cc:520)
    role_vars = [n for p, g in pairs for n in (p.name, g.name)]
    for op in block.ops:
        if op.attr(OP_ROLE_ATTR) == OpRole.Backward and not op.has_attr(OP_ROLE_VAR_ATTR):
            op.set_attr(OP_ROLE_VAR_ATTR, role_vars)
    return pairs


def _make_grad_var(block: Block, grad_name: str, fwd_name: str) -> Variable:
    fv = block.var_or_none(fwd_name)
    return block.create_var(
        name=grad_name,
        shape=fv.shape if fv is not None else None,
        dtype=fv.dtype if fv is not None else "float32",
    )
