"""Whole-block lowering: Program block → one pure JAX function.

This replaces the reference's op-by-op interpreters (``Executor``
``executor.cc:357-392`` hot loop and the ParallelExecutor SSA machinery in
``framework/details/``) with ahead-of-time lowering: a static analysis pass
finds the block's external reads (scope state) and persistable writes, then
every op is traced through its registered lowering rule into a single
``(feeds, state, rng) -> (fetches, new_state, rng')`` function that XLA
JIT-compiles and fuses end-to-end.  Data-dependence ordering, memory reuse,
kernel fusion, and stream scheduling — everything ``details/`` did by hand —
is delegated to the XLA compiler.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import registry
from .program import Program, Block, EMPTY_VAR
from .registry import GRAD_OP_SUFFIX, LowerContext
from ..observability import stats as _obs_stats
from ..observability import trace as _obs_trace

# ops handled by the executor itself, not lowered
SKIP_OPS = ("feed", "fetch")

_telemetry_on = _obs_trace.flags_on


@dataclass
class BlockPlan:
    """Static dataflow summary of one block (+ its sub-blocks)."""

    block_idx: int
    feed_names: tuple
    fetch_names: tuple
    state_reads: List[str] = field(default_factory=list)     # scope vars read
    persist_writes: List[str] = field(default_factory=list)  # scope vars written
    has_stateful: bool = False

    @property
    def donated_reads(self) -> List[str]:
        w = set(self.persist_writes)
        return [n for n in self.state_reads if n in w]

    @property
    def const_reads(self) -> List[str]:
        w = set(self.persist_writes)
        return [n for n in self.state_reads if n not in w]

    @property
    def donated_write_indices(self) -> List[int]:
        """For step-loop drivers: indices into the returned ``new_state``
        (persist_writes order) that refeed the donated inputs
        (donated_reads order) on the next call."""
        pos = {n: i for i, n in enumerate(self.persist_writes)}
        return [pos[n] for n in self.donated_reads]


def analyze_block(program: Program, block_idx: int, feed_names: Sequence[str],
                  fetch_names: Sequence[str]) -> BlockPlan:
    t0 = time.perf_counter_ns() if _telemetry_on() else None
    plan = BlockPlan(block_idx, tuple(feed_names), tuple(fetch_names))
    seen_reads = set()
    persist_written = set()

    def is_persistable(block: Block, name: str) -> bool:
        v = block.var_or_none(name)
        return bool(v and v.persistable)

    def walk(block: Block, defined: set):
        for op in block.ops:
            if op.type in SKIP_OPS:
                continue
            base = op.type[: -len(GRAD_OP_SUFFIX)] if op.type.endswith(GRAD_OP_SUFFIX) else op.type
            if registry.has(base) and registry.get(base).stateful:
                plan.has_stateful = True
            for n in op.input_arg_names():
                if n and n != EMPTY_VAR and n not in defined and n not in seen_reads:
                    seen_reads.add(n)
                    plan.state_reads.append(n)
            # names bound inside sub-blocks by the control-flow lowering
            # (scan step inputs, memories, loop carries) are not scope reads
            inner = set(op.attr("carry_vars", ()) or ())
            inner |= set(op.attr("step_input_vars", ()) or ())
            inner |= {m[0] for m in (op.attr("memories", ()) or ())}
            for sub in op.sub_block_ids:
                walk(program.blocks[sub], set(defined) | inner)
            for n in op.output_arg_names():
                if not n or n == EMPTY_VAR:
                    continue
                defined.add(n)
                if is_persistable(block, n) and n not in persist_written:
                    persist_written.add(n)
                    plan.persist_writes.append(n)

    walk(program.blocks[block_idx], set(feed_names))

    # fetches of vars never touched by ops must still come from scope
    defined_or_read = seen_reads | set(feed_names)
    for b in [program.blocks[block_idx]]:
        for op in b.ops:
            defined_or_read |= set(op.output_arg_names())
    for n in fetch_names:
        if n not in defined_or_read and n not in seen_reads:
            seen_reads.add(n)
            plan.state_reads.append(n)
    if t0 is not None:
        t1 = time.perf_counter_ns()
        _obs_stats.scope("lowering").histogram("analyze_ms").observe(
            (t1 - t0) / 1e6)
        if _obs_trace.enabled():
            _obs_trace.emit("lowering::analyze", t0, t1)
    return plan


def lower_ops(ctx: LowerContext, program: Program, block: Block, env: Dict) -> Dict:
    """Trace every op in ``block`` through its lowering rule, mutating env."""
    from ..ops.control_flow_ops import CONTROL_FLOW_OPS

    # FLAGS_sparse_fused_kernel peephole: lookup_table ops sharing one Ids
    # input lower through a single fused Pallas gather launch
    # (kernels/sparse.py).  Mesh-lowered blocks keep the plain XLA gathers
    # — GSPMD shards those natively but cannot partition a custom call —
    # and fault-recovery re-lowers (ctx.disable_sparse_fused) skip it.
    from ..kernels import sparse as _sparse_kernels
    fusion = (_sparse_kernels.plan_lookup_fusion(block)
              if _sparse_kernels.enabled_for(ctx) else None)

    # int8 inference peephole: mul/fused_fc ops the quantize_int8
    # calibration pass stamped (quant_int8 attr + WInt8/WScale sidecar
    # inputs) lower through the fused-dequant int8 Pallas matmul
    # (kernels/quant.py).  Activation is attr-driven — an uncalibrated
    # program builds no plan and lowers byte-identically.
    from ..kernels import quant as _quant_kernels
    int8_plan = (_quant_kernels.plan_int8(block)
                 if _quant_kernels.enabled_for(ctx) else None)

    for pos, op in enumerate(block.ops):
        if op.type in SKIP_OPS:
            continue
        if fusion is not None and fusion.covers(pos) and fusion.lower(pos, env):
            ctx.sparse_fused_used = True
            continue
        if int8_plan is not None and int8_plan.covers(pos) \
                and int8_plan.lower(pos, env):
            ctx.int8_fused_used = True
            continue
        if op.type in CONTROL_FLOW_OPS:
            try:
                CONTROL_FLOW_OPS[op.type](ctx, program, op, env, lower_ops)
            except Exception as e:
                raise type(e)(
                    f"while lowering control-flow op {op!r} in block "
                    f"{block.idx}: {e}") from e
            continue
        ins = {}
        for slot, names in op.inputs.items():
            if slot.endswith("@GRAD"):
                # grad slots keep positional alignment; missing grads → None
                vals = [env.get(n) if n and n != EMPTY_VAR else None for n in names]
                if any(v is not None for v in vals):
                    ins[slot] = vals
            else:
                vals = [env[n] for n in names if n and n != EMPTY_VAR]
                if vals:
                    ins[slot] = vals
        try:
            if op.type.endswith(GRAD_OP_SUFFIX) and not registry.has(op.type):
                base = registry.get(op.type[: -len(GRAD_OP_SUFFIX)])
                if base.grad is not None:
                    outs = base.grad(ctx, ins, op.attrs)
                else:
                    outs = registry.vjp_grad(base, ctx, ins, op.attrs)
            else:
                outs = registry.get(op.type).lower(ctx, ins, op.attrs)
        except Exception as e:
            raise type(e)(f"while lowering op {op!r} in block {block.idx}: {e}") from e
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            for name, val in zip(names, vals):
                if name and name != EMPTY_VAR and val is not None:
                    env[name] = val
    return env


def build_block_fn(program: Program, plan: BlockPlan, training: bool = True,
                   mesh=None, disable_sparse_fused: bool = False):
    """Return fn(feed_vals, donated_state, const_state, rng) ->
    (fetch_vals, new_persist_vals, rng_out).

    ``disable_sparse_fused``: lower WITHOUT the fused Pallas paths (the
    sparse-embedding kernels AND the int8 inference peephole) even when
    enabled — the executor's dispatch-fault recovery re-lowers a step
    this way when its compile died with fused kernels in it
    (kernels/sparse.py / kernels/quant.py counted-fallback contract)."""
    block = program.blocks[plan.block_idx]
    donated, const = plan.donated_reads, plan.const_reads
    # trace-time latch: did THIS lowering actually emit fused sparse /
    # int8 kernels?  The executor's dispatch-fault recovery gates on it
    # (the flag alone lies in both directions: it may have changed since
    # the entry traced, and a flag-on program may contain no sparse
    # lookups)
    used = {"sparse_fused": False, "int8_fused": False}

    def fn(feed_vals, donated_state, const_state, rng):
        # host-side timing of the op-by-op jax trace: runs once per XLA
        # compile (and per scan/eval_shape re-trace), never on cached
        # executions — the "build" half of the lowering cost
        t0 = time.perf_counter_ns() if _telemetry_on() else None

        def lower_sub(block_idx, env):
            return lower_ops(ctx, program, program.blocks[block_idx], env)

        ctx = LowerContext(block=block, mesh=mesh, lower_block_fn=lower_sub,
                           training=training)
        ctx.disable_sparse_fused = disable_sparse_fused
        ctx.disable_int8_fused = disable_sparse_fused
        ctx.set_rng(rng)
        env: Dict = {}
        env.update(zip(plan.feed_names, feed_vals))
        env.update(zip(donated, donated_state))
        env.update(zip(const, const_state))
        lower_ops(ctx, program, block, env)
        if getattr(ctx, "sparse_fused_used", False):
            used["sparse_fused"] = True
        if getattr(ctx, "int8_fused_used", False):
            used["int8_fused"] = True
        fetches = [env[n] for n in plan.fetch_names]
        new_state = [env[n] for n in plan.persist_writes]
        if t0 is not None:
            t1 = time.perf_counter_ns()
            _obs_stats.scope("lowering").histogram("trace_ms").observe(
                (t1 - t0) / 1e6)
            if _obs_trace.enabled():
                _obs_trace.emit("lowering::trace", t0, t1)
        return fetches, new_state, ctx.rng_key

    fn._sparse_fused_used = used
    return fn
