"""Persistent cross-process compilation cache + AOT warm start.

The executor's in-memory executable cache (``executor.py`` — the
reference's program cache, ``python/paddle/fluid/executor.py:207``
``_get_program_cache_key``) dies with its process, so every fresh
trainer — first launch, elastic kill-restart, bench worker respawn —
re-pays full lowering + XLA compilation (PERF.md: 8.6 s for one first
call at seq-64k).  On TPU the compile IS the cold-start bound, which is
why JAX grew its own persistent compilation cache; this module is the
framework-level equivalent, keyed by our own ProgramDesc fingerprint:

- **Tier A** — whole-executable reuse: ``jax.jit(fn).lower(...)
  .compile()`` AOT executables serialized via
  ``jax.experimental.serialize_executable`` into content-addressed
  entry files.  A warm process skips lowering-trace AND XLA compile;
  first step costs one deserialize (~ms).
- **Tier B** — XLA-level reuse: ``jax_compilation_cache_dir`` is
  pointed at ``<dir>/xla`` so paths tier A cannot serialize (platform
  limitations) still skip the XLA compile on re-trace.

Store discipline is robustness-grade: entries are written atomically
(unique tmp + ``os.replace``); loads of corrupted / truncated /
version-skewed entries degrade to a *counted miss* (never an
exception out of :func:`load`) and evict the bad file; an LRU size cap
(``FLAGS_compile_cache_max_bytes``, mtime = last use) bounds the dir.
Every fault leaves a flight-recorder note (``observability/flight.py``)
so a post-mortem explains a recompile storm.

Keying: :func:`fingerprint` hashes the canonical ProgramDesc (block
ops/attrs + var dtypes/shapes via ``Program.to_dict``), the feed
signature, fetch list, lowering mode (train/infer, run/run_steps), the
mesh spec, and an environment digest (jax/jaxlib versions, backend
platform, device count, x64 mode, lowering-relevant FLAGS).  Entries
from a different environment are skipped with a counted
``version_skew`` — a jax upgrade invalidates the cache instead of
crashing it.

Everything is gated on ``FLAGS_compile_cache_dir``: unset (default)
⇒ no disk I/O, no threads, byte-for-byte the previous behavior.

SECURITY: entry payloads deserialize through pickle (the transport
``jax.experimental.serialize_executable`` uses), so loading an entry
executes code from the file.  The cache directory must be PRIVATE to
the training user — it is created 0700 — and must never point at a
world-writable shared location; anyone who can write the directory can
run code in every process that reads it.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import flags as _flags
from ..observability import debug_server as _debug_server
from ..observability import stats as _obs_stats
from ..observability import trace as _obs_trace

MAGIC = b"PTCC1\0"
FORMAT_VERSION = 1
ENTRY_SUFFIX = ".ptcc"
_HEADER_LEN = struct.Struct("<I")

_metrics = None
_lock = threading.Lock()
_tmp_counter = 0
_env_digest_cache: Optional[str] = None
_jax_cache_wired = False


def _cm():
    """Metric handles (module-wide, survive observability.reset()).

    The persistent hit/miss/serialize/deserialize series live in the
    ``executor`` scope next to the in-memory cache counters (one
    dashboard row answers "did the restart hydrate?"); store-level
    faults/evictions live under ``compile_cache``.
    """
    global _metrics
    m = _metrics
    if m is None:
        ex = _obs_stats.scope("executor")
        cc = _obs_stats.scope("compile_cache")
        import types as _t
        m = _t.SimpleNamespace(
            hits=ex.counter(
                "persistent_hits",
                "executable cache misses served from the persistent "
                "disk cache (no lowering trace, no XLA compile)"),
            misses=ex.counter(
                "persistent_misses",
                "executable cache misses that also missed the "
                "persistent disk cache (full compile paid)"),
            serialize_ms=ex.histogram("persistent_serialize_ms"),
            deserialize_ms=ex.histogram("persistent_deserialize_ms"),
            store_errors=cc.counter(
                "store_errors",
                "failed entry serializations/writes (cache stays "
                "consistent; the run continues uncached)"),
            faults=cc.counter(
                "faults",
                "corrupted/truncated/unloadable entries hit at read "
                "time — each one degraded to a miss and was evicted"),
            version_skews=cc.counter(
                "version_skews",
                "entries skipped because they were written by a "
                "different jax/jaxlib/platform environment"),
            evictions=cc.counter("evictions",
                                 "entry files pruned by the LRU size cap"),
            stored_bytes=cc.counter("stored_bytes"),
        )
        _metrics = m
    return m


def _flight_note(msg: str, **fields) -> None:
    try:
        from ..observability import flight as _flight
        _flight.note(msg, **fields)
    except Exception:  # the recorder must never take a run down
        pass


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def cache_dir() -> str:
    try:
        return str(_flags.get_flags("compile_cache_dir") or "")
    except KeyError:  # pragma: no cover - flag always defined
        return ""


def enabled() -> bool:
    return bool(cache_dir())


def max_bytes() -> int:
    try:
        return int(_flags.get_flags("compile_cache_max_bytes") or 0)
    except KeyError:  # pragma: no cover
        return 0


def wire_jax_cache() -> bool:
    """Tier B: point jax's own persistent compilation cache at
    ``<dir>/xla`` so even executables tier A cannot serialize get
    XLA-level reuse across processes.  One flag read when disabled;
    idempotent; config names are probed so a jax without them degrades
    to tier A only."""
    global _jax_cache_wired
    d = cache_dir()
    if not d or _jax_cache_wired:
        return _jax_cache_wired
    try:
        # we create the dir (0700 — entries are pickle on load, see the
        # module docstring) BEFORE jax can, whose cache writes would
        # otherwise create it with default permissions
        os.makedirs(d, mode=0o700, exist_ok=True)
    except OSError:
        pass
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(d, "xla"))
        _jax_cache_wired = True
    except Exception:
        return False
    # cache every executable: the restart win is the point, and the
    # LRU cap (not a compile-time floor) bounds the footprint.  These
    # knobs are tuning only — a jax without them still has tier B on
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass
    return _jax_cache_wired


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _env_digest() -> str:
    """Environment part of every key: an executable only loads into the
    jax/jaxlib/platform world that built it."""
    global _env_digest_cache
    if _env_digest_cache is None:
        import jax
        import jaxlib
        env = {
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
            "device_count": jax.device_count(),
            "x64": bool(jax.config.jax_enable_x64),
        }
        _env_digest_cache = hashlib.sha256(
            json.dumps(env, sort_keys=True).encode()).hexdigest()
    return _env_digest_cache


def _lowering_flags() -> dict:
    """Trace-time flags that change the lowered program — read LIVE
    (not cached with the env digest) so a mid-process ``set_flags``
    can't alias two different lowerings under one fingerprint."""
    return {"sparse_dense_update_max_elems":
            _flags.get_flags("sparse_dense_update_max_elems")}


def env_info() -> dict:
    """The human-readable environment stamp written into entry headers
    (and checked, field by field, at load time)."""
    import jax
    import jaxlib
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
            "device_count": jax.device_count()}


def program_digest(program) -> str:
    """Stable content hash of a ProgramDesc (blocks: ops, attrs, var
    dtypes+shapes).  Memoized per (program, version): mutation bumps
    ``_version`` which invalidates the memo along with the executor
    caches."""
    cached = getattr(program, "_fp_digest", None)
    if cached is not None and cached[0] == program._version:
        return cached[1]
    doc = json.dumps(program.to_dict(), sort_keys=True, default=repr)
    digest = hashlib.sha256(doc.encode()).hexdigest()
    program._fp_digest = (program._version, digest)
    return digest


def mesh_spec(mesh) -> Optional[list]:
    if mesh is None:
        return None
    try:
        kinds = sorted({d.device_kind for d in mesh.devices.flat})
    except Exception:
        kinds = []
    return [list(mesh.axis_names), list(mesh.devices.shape), kinds]


def fingerprint(program, sig, fetch_names, training: bool, mode: str,
                mesh=None, extra=None) -> str:
    """The canonical cache key: hex digest of everything that determines
    the compiled executable."""
    doc = {
        "program": program_digest(program),
        "sig": [[n, list(s), str(d)] for n, s, d in sig],
        "fetch": list(fetch_names),
        "training": bool(training),
        "mode": mode,
        "mesh": mesh_spec(mesh),
        "env": _env_digest(),
        "flags": _lowering_flags(),
    }
    if extra:
        doc["extra"] = extra
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


# ---------------------------------------------------------------------------
# entry file format
# ---------------------------------------------------------------------------

def entry_path(key: str, d: Optional[str] = None) -> str:
    return os.path.join(d or cache_dir(), key + ENTRY_SUFFIX)


def read_header(path: str) -> dict:
    """Parse one entry file's framed JSON header (stdlib-only — the
    operator CLI uses this without importing jax).  Raises ValueError
    on any framing problem."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise ValueError("bad magic")
        (hlen,) = _HEADER_LEN.unpack(f.read(_HEADER_LEN.size))
        if hlen <= 0 or hlen > 1 << 20:
            raise ValueError(f"implausible header length {hlen}")
        hdr = json.loads(f.read(hlen).decode("utf-8"))
        if not isinstance(hdr, dict):
            raise ValueError("header is not an object")
    payload = size - len(MAGIC) - _HEADER_LEN.size - hlen
    if payload < 0 or payload != int(hdr.get("payload_bytes", payload)):
        raise ValueError("truncated entry (payload size mismatch)")
    return hdr


def _read_entry(path: str) -> Tuple[dict, bytes]:
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(MAGIC):
        raise ValueError("bad magic")
    off = len(MAGIC)
    (hlen,) = _HEADER_LEN.unpack(data[off:off + _HEADER_LEN.size])
    off += _HEADER_LEN.size
    if hlen <= 0 or off + hlen > len(data):
        raise ValueError("truncated header")
    hdr = json.loads(data[off:off + hlen].decode("utf-8"))
    payload = data[off + hlen:]
    if len(payload) != int(hdr.get("payload_bytes", -1)):
        raise ValueError("truncated entry (payload size mismatch)")
    return hdr, payload


def _atomic_write(d: str, name: str, blob: bytes) -> str:
    """Unique-tmp + rename: concurrent writers of the same key race
    benignly (last rename wins, both files are complete)."""
    global _tmp_counter
    with _lock:
        _tmp_counter += 1
        n = _tmp_counter
    tmp = os.path.join(d, f".tmp-{os.getpid()}-{n}-{name}")
    path = os.path.join(d, name)
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _evict_file(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# store / load
# ---------------------------------------------------------------------------

def store(key: str, compiled, meta: Optional[dict] = None) -> Optional[str]:
    """Serialize one AOT-compiled executable (``jax.stages.Compiled``)
    under ``key``.  Never raises: serialization failures (platforms
    without executable serialization) and I/O errors are counted in
    ``compile_cache.store_errors`` and the run continues uncached
    (tier B still applies).  Returns the entry path or None."""
    d = cache_dir()
    if not d:
        return None
    m = _cm()
    try:
        from jax.experimental import serialize_executable as _se
        t0 = time.perf_counter_ns()
        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree),
                            protocol=pickle.HIGHEST_PROTOCOL)
        hdr = {"format": FORMAT_VERSION, "key": key,
               "created": time.time(), "payload_bytes": len(blob)}
        hdr.update(env_info())
        if meta:
            hdr["meta"] = meta
        hdr_bytes = json.dumps(hdr, sort_keys=True).encode("utf-8")
        framed = (MAGIC + _HEADER_LEN.pack(len(hdr_bytes)) + hdr_bytes
                  + blob)
        # 0700: entries execute as pickle on load — the dir must stay
        # private to the training user (see the module docstring)
        os.makedirs(d, mode=0o700, exist_ok=True)
        path = _atomic_write(d, key + ENTRY_SUFFIX, framed)
        m.serialize_ms.observe((time.perf_counter_ns() - t0) / 1e6)
        m.stored_bytes.inc(len(framed))
        prune_lru(d)
        return path
    except Exception as e:
        m.store_errors.inc()
        _flight_note("compile_cache_store_error", key=key[:16],
                     error=repr(e)[:200])
        return None


def _env_matches(hdr: dict) -> bool:
    info = env_info()
    return (int(hdr.get("format", -1)) == FORMAT_VERSION
            and all(hdr.get(k) == v for k, v in info.items()))


def load(key: str, count_miss: bool = True):
    """Load + deserialize the executable stored under ``key``.

    ``count_miss=False`` keeps a clean not-found out of the
    ``persistent_misses`` series (hydrate-only probes, whose miss is
    counted by the real compile that follows); faults and skews are
    always counted.

    Returns a callable ``jax.stages.Compiled`` or None.  NEVER raises:
    a missing file is a plain miss; a corrupted/truncated/unloadable
    entry is a *counted* miss (``compile_cache.faults``) that evicts
    the bad file; an entry from a different jax/jaxlib/platform world
    is a counted ``version_skew`` (also evicted — it can never load
    here).  Hits touch the file's mtime (the LRU clock).

    All counters here increment unconditionally (unlike the per-run
    hot-path telemetry, which FLAGS_runtime_stats gates): loads happen
    only on compile-path misses, and the hit/miss/fault series must
    stay consistent with each other for the restart-win accounting.
    """
    d = cache_dir()
    if not d:
        return None
    path = entry_path(key, d)
    m = _cm()
    try:
        hdr, blob = _read_entry(path)
    except FileNotFoundError:
        if count_miss:
            m.misses.inc()
        return None
    except Exception as e:
        m.faults.inc()
        m.misses.inc()
        _flight_note("compile_cache_corrupt_entry", key=key[:16],
                     error=repr(e)[:200])
        _evict_file(path)
        return None
    if not _env_matches(hdr):
        m.version_skews.inc()
        m.misses.inc()
        _flight_note("compile_cache_version_skew", key=key[:16],
                     entry_env={k: hdr.get(k) for k in
                                ("format", "jax", "jaxlib", "platform")})
        _evict_file(path)
        return None
    try:
        t0 = time.perf_counter_ns()
        payload, in_tree, out_tree = pickle.loads(blob)
        from jax.experimental import serialize_executable as _se
        compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
        ms = (time.perf_counter_ns() - t0) / 1e6
    except Exception as e:
        # payload unpickles garbage / XLA refuses the executable: same
        # contract as corruption — counted miss, evict, carry on
        m.faults.inc()
        m.misses.inc()
        _flight_note("compile_cache_deserialize_fault", key=key[:16],
                     error=repr(e)[:200])
        _evict_file(path)
        return None
    m.hits.inc()
    m.deserialize_ms.observe(ms)
    try:
        os.utime(path, None)  # LRU touch
    except OSError:
        pass
    return compiled


def dispatch_fault(key: Optional[str], exc) -> None:
    """A disk-hydrated executable failed its first dispatch (the
    executor falls back to a fresh compile): count the fault, evict
    the entry it came from, leave a flight note."""
    _cm().faults.inc()
    _flight_note("compile_cache_dispatch_fault",
                 key=(key or "")[:16], error=repr(exc)[:200])
    if key:
        d = cache_dir()
        if d:
            _evict_file(entry_path(key, d))


# ---------------------------------------------------------------------------
# occupancy / LRU prune
# ---------------------------------------------------------------------------

def list_entries(d: Optional[str] = None) -> List[dict]:
    """[{key, path, bytes, mtime}] for every tier-A entry file (sorted
    oldest-used first — prune order)."""
    d = d or cache_dir()
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for n in names:
        if not n.endswith(ENTRY_SUFFIX) or n.startswith(".tmp-"):
            continue
        p = os.path.join(d, n)
        try:
            st = os.stat(p)
        except OSError:
            continue  # racing another process's prune
        out.append({"key": n[:-len(ENTRY_SUFFIX)], "path": p,
                    "bytes": st.st_size, "mtime": st.st_mtime})
    out.sort(key=lambda e: e["mtime"])
    return out


def store_stats(d: Optional[str] = None) -> dict:
    entries = list_entries(d)
    return {"entries": len(entries),
            "bytes": sum(e["bytes"] for e in entries)}


def register_memory_pool() -> None:
    """Register the on-disk store on the MemoryLedger (kind ``disk``)
    so /allocz answers how many bytes the persistent cache holds
    against its ``FLAGS_compile_cache_max_bytes`` cap.  No-op unless
    both the cache and ``FLAGS_memory_attribution`` are on."""
    from ..observability import memory as _memory
    if not _memory.enabled() or not enabled():
        return

    def _snap() -> dict:
        st = store_stats()
        return {"used": st["bytes"], "entries": st["entries"],
                "cap_bytes": max_bytes()}

    _memory.pool("compile_cache_disk", "disk", _snap)


def prune_lru(d: Optional[str] = None,
              cap: Optional[int] = None) -> List[str]:
    """Evict oldest-used entries until the tier-A files fit under the
    byte cap.  Concurrent-process safe: a file deleted under us is
    someone else's eviction."""
    d = d or cache_dir()
    cap = max_bytes() if cap is None else cap
    if not d:
        return []
    # reap tmp files a crashed writer left behind (old enough that no
    # live writer can still be between write and rename) — even when
    # the byte cap is 0/unbounded, these must not accumulate
    try:
        now = time.time()
        for n in os.listdir(d):
            if n.startswith(".tmp-"):
                p = os.path.join(d, n)
                try:
                    if now - os.stat(p).st_mtime > 3600:
                        os.remove(p)
                except OSError:
                    pass
    except OSError:
        pass
    if not cap:
        return []
    entries = list_entries(d)
    total = sum(e["bytes"] for e in entries)
    evicted = []
    for e in entries:
        if total <= cap:
            break
        _evict_file(e["path"])
        total -= e["bytes"]
        evicted.append(e["key"])
        _cm().evictions.inc()
    if evicted:
        _flight_note("compile_cache_lru_prune", evicted=len(evicted),
                     cap=cap)
    return evicted


def _statusz() -> dict:
    d = cache_dir()
    if not d:
        return {"enabled": False}
    out = {"enabled": True, "dir": d, "max_bytes": max_bytes(),
           "jax_cache_wired": _jax_cache_wired}
    out.update(store_stats(d))
    return out


_debug_server.register_provider("compile_cache", _statusz)
