"""Type system for the program IR.

TPU-native re-design of the reference's variable/type model
(``paddle/fluid/framework/framework.proto:104-181`` — VarType with
LOD_TENSOR / SELECTED_ROWS / LOD_TENSOR_ARRAY / READER / STEP_SCOPES, and
typed attributes on OpDesc).  Dtypes map directly onto JAX/XLA dtypes;
``bfloat16`` is first-class because it is the native MXU input type.
"""
from __future__ import annotations

import enum

import numpy as np

try:  # ml_dtypes ships with jax
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BFLOAT16 = np.dtype("float32")


class VarType(enum.IntEnum):
    """Variable container kinds (framework.proto:104 equivalents)."""

    DENSE_TENSOR = 0       # reference LOD_TENSOR; here: dense array (+ optional lengths)
    SELECTED_ROWS = 1      # sparse {rows, values} gradient for embeddings
    TENSOR_ARRAY = 2       # reference LOD_TENSOR_ARRAY: stacked per-step tensors
    STEP_SCOPES = 3        # control-flow carry bookkeeping
    READER = 4             # data pipeline endpoint
    RAW = 5                # opaque host object
    FEED_MINIBATCH = 6
    FETCH_LIST = 7


# Canonical dtype names (attribute values store these strings).
_DTYPES = {
    "bool": np.dtype("bool"),
    "int8": np.dtype("int8"),
    "uint8": np.dtype("uint8"),
    "int16": np.dtype("int16"),
    "int32": np.dtype("int32"),
    "int64": np.dtype("int64"),
    "float16": np.dtype("float16"),
    "bfloat16": _BFLOAT16,
    "float32": np.dtype("float32"),
    "float64": np.dtype("float64"),
}

_CANON = {v: k for k, v in _DTYPES.items()}


def normalize_dtype(dtype) -> str:
    """Return the canonical string name for any dtype spelling."""
    if isinstance(dtype, str):
        if dtype in _DTYPES:
            return dtype
        return _CANON[np.dtype(dtype)]
    d = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    if d in _CANON:
        return _CANON[d]
    raise ValueError(f"unsupported dtype: {dtype!r}")


def np_dtype(name) -> np.dtype:
    return _DTYPES[normalize_dtype(name)]


def is_float(name) -> bool:
    return normalize_dtype(name) in ("float16", "bfloat16", "float32", "float64")


def is_int(name) -> bool:
    return normalize_dtype(name) in ("int8", "uint8", "int16", "int32", "int64")
