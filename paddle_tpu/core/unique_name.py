"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import collections
import contextlib

_counters: dict = collections.defaultdict(int)


def generate(key: str) -> str:
    _counters[key] += 1
    return f"{key}_{_counters[key] - 1}"


def reset() -> None:
    _counters.clear()


@contextlib.contextmanager
def guard(prefix: str = ""):
    """Isolate the counter namespace (used by Program.clone and tests)."""
    global _counters
    saved = _counters
    _counters = collections.defaultdict(int)
    try:
        yield
    finally:
        _counters = saved
