"""Op registry: op type → XLA lowering rule (+ optional custom grad rule).

TPU-native replacement for the reference's OperatorWithKernel registry
(``paddle/fluid/framework/op_registry.h:43,124`` and the OpKernelType
dispatch in ``operator.cc:686-723``).  There is no runtime kernel dispatch:
each op type registers a *lowering rule* — a pure function from JAX values
to JAX values — and whole blocks are traced through these rules into one
XLA computation (see ``core/lowering.py``).  Hot ops may register a Pallas
implementation; the rule decides internally (the reference's
library_type={Plain,cuDNN,MKLDNN} analogue).

Gradients: the default grad rule applies ``jax.vjp`` to the forward rule —
XLA CSE merges the re-traced forward with the original, so this costs no
extra FLOPs inside a jitted block.  Ops whose lowering consumes randomness
or host state must register an explicit ``grad`` rule (reference analogue:
GradOpDescMaker, ``grad_op_desc_maker.h``).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

GRAD_OP_SUFFIX = "_grad"

# in/out values passed to lowering rules: dict slot -> list[jax.Array]
SlotVals = Dict[str, List[Any]]


class LowerContext:
    """Per-block lowering context handed to every rule.

    Provides split PRNG keys (rng is threaded through the block as hidden
    state — the functional translation of the reference's per-op ``seed``
    attrs), access to the block being lowered (for sub-block control flow),
    and mesh info for parallel lowering.
    """

    def __init__(self, block=None, mesh=None, lower_block_fn=None, training=True):
        self.block = block
        self.mesh = mesh
        self.training = training
        self._rng_key = None
        self._rng_key0 = None
        self._rng_used = False
        self._lower_block_fn = lower_block_fn  # (block_idx, env) -> env

    def set_rng(self, key):
        self._rng_key = key
        self._rng_key0 = key
        self._rng_used = False

    def named_prng(self, name: str, seed: int = 0):
        """Order-independent PRNG key derived from (base key, name).

        Used by initializer ops (attr ``seed_name``) so that initialization
        is a pure function of (program.random_seed, var name) regardless of
        op order or program partitioning — program rewrites (transpilers,
        pserver splits) then initialize identical values to the local run.
        The reference gets the equivalent property from per-op ``seed``
        attrs (uniform_random_op.cc) set at build time.
        """
        import zlib

        base = jax.random.PRNGKey(seed) if seed else self._rng_key0
        if base is None:
            raise RuntimeError("op requires randomness but no rng state was provided")
        self._rng_used = True
        return jax.random.fold_in(base, zlib.crc32(name.encode("utf-8")))

    def prng(self):
        """Split off a fresh PRNG key (marks rng as consumed)."""
        if self._rng_key is None:
            raise RuntimeError("op requires randomness but no rng state was provided")
        self._rng_key, sub = jax.random.split(self._rng_key)
        self._rng_used = True
        return sub

    @property
    def rng_key(self):
        return self._rng_key

    def lower_sub_block(self, block_idx: int, env: dict) -> dict:
        if self._lower_block_fn is None:
            raise RuntimeError("sub-block lowering not available in this context")
        return self._lower_block_fn(block_idx, env)


class OpDef:
    def __init__(
        self,
        type: str,
        lower: Callable[[LowerContext, SlotVals, dict], SlotVals],
        grad: Optional[Callable] = None,
        stateful: bool = False,
        input_slots: Optional[Sequence[str]] = None,
        output_slots: Optional[Sequence[str]] = None,
        no_grad_slots: Sequence[str] = (),
        infer_shape: Optional[Callable] = None,
    ):
        self.type = type
        self.lower = lower
        self.grad = grad            # custom grad lowering, else vjp default
        self.stateful = stateful    # consumes rng / host state → needs custom grad
        self.input_slots = list(input_slots) if input_slots else None
        self.output_slots = list(output_slots) if output_slots else None
        self.no_grad_slots = set(no_grad_slots)  # input slots never differentiated
        self.infer_shape = infer_shape


_REGISTRY: Dict[str, OpDef] = {}


def register(
    type: str,
    *,
    grad=None,
    stateful: bool = False,
    input_slots=None,
    output_slots=None,
    no_grad_slots=(),
    infer_shape=None,
):
    """Decorator: register a lowering rule for ``type``."""

    def deco(fn):
        _REGISTRY[type] = OpDef(
            type,
            fn,
            grad=grad,
            stateful=stateful,
            input_slots=input_slots,
            output_slots=output_slots,
            no_grad_slots=no_grad_slots,
            infer_shape=infer_shape,
        )
        return fn

    return deco


def register_grad(type: str):
    """Decorator: attach a custom grad rule to an already-registered op.

    Signature: ``grad(ctx, ins, attrs) -> {in_slot + '@GRAD': [vals]}`` where
    ``ins`` contains the forward ins, forward outs, and ``slot@GRAD`` entries.
    """

    def deco(fn):
        _REGISTRY[type].grad = fn
        return fn

    return deco


def get(type: str) -> OpDef:
    if type not in _REGISTRY:
        raise KeyError(f"no lowering registered for op type {type!r}")
    return _REGISTRY[type]


def has(type: str) -> bool:
    return type in _REGISTRY


def all_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Default (vjp-based) grad lowering
# ---------------------------------------------------------------------------

def vjp_grad(opdef: OpDef, ctx: LowerContext, ins: SlotVals, attrs: dict) -> SlotVals:
    """Differentiate the forward lowering rule with jax.vjp.

    ``ins`` holds the forward input slots, forward output slots, and
    ``slot@GRAD`` cotangents for outputs that received gradients.  Returns
    ``slot@GRAD`` for each differentiable forward input slot.  Integer and
    ``no_grad_slots`` inputs are held constant.  The forward is re-traced
    inside vjp; within one jitted block XLA CSE merges it with the original
    forward, so there is no duplicated compute at run time.
    """
    fwd_out_slots = set(attrs.get("__fwd_out_slots__", ()))
    if opdef.output_slots:
        fwd_out_slots |= set(opdef.output_slots)
    in_slots = [
        s for s in ins
        if not s.endswith("@GRAD")
        and (opdef.input_slots is None or s in opdef.input_slots)
        and s not in fwd_out_slots
    ]
    diff_slots = [
        s for s in in_slots
        if s not in opdef.no_grad_slots
        and all(jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact) for v in ins[s])
    ]
    const_vals = {s: ins[s] for s in in_slots if s not in diff_slots}
    if not diff_slots:
        return {}

    def fwd(d: dict):
        full = {k: list(v) for k, v in d.items()}
        full.update(const_vals)
        fwd_attrs = {k: v for k, v in attrs.items() if not k.startswith("__")}
        return opdef.lower(ctx, full, fwd_attrs)

    primals_out, vjp_fn = jax.vjp(fwd, {s: ins[s] for s in diff_slots})

    def make_cot(path_slot, j, primal):
        g_list = ins.get(path_slot + "@GRAD")
        if g_list is not None and j < len(g_list) and g_list[j] is not None:
            g = g_list[j]
            pdt = jnp.asarray(primal).dtype
            # declared grad-var dtype can differ from the promoted primal
            # dtype under mixed precision (bf16 activations, f32 stats)
            return g.astype(pdt) if g.dtype != pdt else g
        if jnp.issubdtype(jnp.asarray(primal).dtype, jnp.inexact):
            return jnp.zeros_like(primal)
        import numpy as _np
        return _np.zeros(jnp.shape(primal), dtype=jax.dtypes.float0)

    cot = {
        s: [make_cot(s, j, p) for j, p in enumerate(vals)]
        for s, vals in primals_out.items()
    }
    (grads,) = vjp_fn(cot)
    return {s + "@GRAD": list(v) for s, v in grads.items()}
