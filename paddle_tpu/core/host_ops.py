"""Host-op registry: side-effectful ops run by the Executor on the host.

The reference's op loop treats RPC/IO ops like any other op — their kernels
just happen to do gRPC or file IO instead of math (``send_op.cc:29``,
``recv_op.cc:28``, ``listen_and_serv_op.cc:102``, ``print_op.cc``).  The
TPU runtime whole-block-JITs device compute, so side-effectful ops cannot
live inside the XLA program.  Instead they register here; the Executor
partitions a block containing host ops into maximal *device segments*
(each lowered + jitted exactly as before) interleaved with host-op calls
that read/write the Scope.  Device compute keeps end-to-end XLA fusion;
host ops keep reference op-loop ordering semantics.

Handler signature: ``fn(executor, program, op, scope)``; inputs are read
from the scope (device segments fetch any value a later host op consumes
into the scope first), outputs are written back to the scope.
"""
from __future__ import annotations

from typing import Callable, Dict

HOST_OPS: Dict[str, Callable] = {}


def register_host_op(op_type: str):
    def deco(fn: Callable) -> Callable:
        HOST_OPS[op_type] = fn
        return fn
    return deco


def is_host_op(op_type: str) -> bool:
    return op_type in HOST_OPS


def run_host_op(executor, program, op, scope):
    return HOST_OPS[op.type](executor, program, op, scope)
