"""SelectedRows: static-shape sparse row-slice gradients.

TPU-native redesign of the reference's ``SelectedRows``
(``paddle/fluid/framework/selected_rows.h:32``): a {row-index vector,
value rows} pair used as the gradient type of ``lookup_table(is_sparse)``.
The reference stores a dynamically-sized row list on the host; XLA needs
static shapes, so here ``rows`` is the *flattened id tensor* of the lookup
(fixed length N = number of lookups per step, duplicates allowed) and
``values`` the matching cotangent rows.  Dense materialisation of the
[height, D] gradient never happens: optimizers scatter straight into the
parameter rows (``sgd_op.h:47-52`` sparse-path analogue).

Duplicate handling: scatter-add is exact for SGD; accumulator-based
optimizers (momentum/adam/adagrad/...) must see each row once, so
``merge_rows`` segment-sums duplicates into unique rows — the analogue of
the reference's ``scatter::MergeAdd`` (``operators/math/selected_rows_functor.h``).
Merged slots beyond the number of unique rows carry the sentinel row id
``height``; gathers use fill-with-zero and scatters use drop mode, so the
sentinel rows are no-ops on device — no dynamic shapes anywhere.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import tree_util


@tree_util.register_pytree_node_class
class SelectedRows:
    """Sparse row-slice tensor: ``values[i]`` is a (sub)gradient for row
    ``rows[i]`` of a dense [height, ...] tensor.  Rows may repeat."""

    def __init__(self, rows, values, height: int, merged: bool = False):
        self.rows = rows
        self.values = values
        self.height = int(height)
        self.merged = bool(merged)  # rows already unique (merge_rows output)

    def tree_flatten(self):
        return (self.rows, self.values), (self.height, self.merged)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype), self.height)

    def to_dense(self):
        """Materialise the dense gradient (duplicates accumulate)."""
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values, mode="drop")

    def __repr__(self):
        return (f"SelectedRows(n={self.rows.shape[0]}, height={self.height}, "
                f"row_shape={self.values.shape[1:]}, dtype={self.dtype})")


def merge_rows(sr: SelectedRows) -> SelectedRows:
    """Sum duplicate rows (MergeAdd).  Result has the same static length N;
    slot i holds the i-th unique row's sum, unused slots carry the sentinel
    row id ``height`` (dropped by scatters, zero-filled by gathers)."""
    rows, vals = sr.rows, sr.values
    n = rows.shape[0]
    if n == 0 or sr.merged:
        return sr
    order = jnp.argsort(rows)
    r = rows[order]
    v = vals[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), r[1:] != r[:-1]])
    seg = jnp.cumsum(first) - 1           # sorted position → unique-group id
    merged = jax.ops.segment_sum(v, seg, num_segments=n)
    group_rows = jax.ops.segment_max(r, seg, num_segments=n)
    valid = jnp.arange(n) < seg[-1] + 1   # first n_unique slots are real
    out_rows = jnp.where(valid, group_rows, jnp.asarray(sr.height, r.dtype))
    return SelectedRows(out_rows, merged, sr.height, merged=True)


def dense_grad_and_mask(sr: SelectedRows, dtype=None):
    """Two-scatter alternative to ``merge_rows`` for lazy optimizers:
    scatter-add the (possibly duplicated) rows into a dense [height, D]
    gradient and scatter-count a touched-row mask.  The optimizer then
    updates the WHOLE table with elementwise math masked by ``touched`` —
    exact lazy semantics (untouched rows unchanged, duplicates summed)
    with only 2 scatter ops instead of the sort + segment ops + 3 gathers
    + 3 scatters of the sorted path.  On this chip scatter-class ops cost
    ~1 ms each regardless of width, so for small/medium tables the fused
    full-table elementwise pass is 4× faster (measured: DeepFM 82k →
    362k samples/s); ``prefer_dense_update`` gates it by table size."""
    vals = sr.values if dtype is None else sr.values.astype(dtype)
    shape = (sr.height,) + (1,) * (vals.ndim - 1)
    if vals.ndim >= 2:
        # ONE scatter for both grad and mask (r5, VERDICT r4 #4): the
        # scatter-class op COUNT is the binding term on this chip (~1 ms
        # flat each, PERF.md §5), so ride the touched-count along as an
        # extra trailing column of the same scatter-add instead of a
        # second scatter.  For DeepFM's two tables this halves the
        # per-step scatter count of the update path (4 -> 2).
        flat = vals.reshape(vals.shape[0], -1)
        ones = jnp.ones((flat.shape[0], 1), flat.dtype)
        aug = jnp.concatenate([flat, ones], axis=1)
        buf = jnp.zeros((sr.height, aug.shape[1]), aug.dtype)
        buf = buf.at[sr.rows].add(aug, mode="drop")
        gd = buf[:, :-1].reshape((sr.height,) + vals.shape[1:])
        return gd, (buf[:, -1:] > 0).reshape(shape)
    src = SelectedRows(sr.rows, vals, sr.height, sr.merged)
    gd = src.to_dense()
    touched = jnp.zeros((sr.height, 1), jnp.float32)
    touched = touched.at[sr.rows].add(
        jnp.ones((sr.rows.shape[0], 1), jnp.float32), mode="drop")
    return gd, (touched > 0).reshape(shape)


def prefer_dense_update(sr: SelectedRows) -> bool:
    """Size heuristic for the masked-dense lazy-update path: the dense
    pass costs ~7 full-table HBM sweeps, the sorted path ~12 serialized
    scatter-class ops (~flat cost).  Below the element threshold dense
    wins; override with FLAGS_sparse_dense_update_max_elems."""
    from . import flags
    row_elems = 1
    for d in sr.values.shape[1:]:
        row_elems *= int(d)
    return (sr.height * row_elems
            <= flags.get_flags("sparse_dense_update_max_elems"))


def gather_rows(dense, rows):
    """Gather dense[rows]; sentinel (out-of-range) rows read as zero."""
    return dense.at[rows].get(mode="fill", fill_value=0)


def scatter_set_rows(dense, rows, values):
    """dense[rows] = values; sentinel rows are dropped."""
    return dense.at[rows].set(values.astype(dense.dtype), mode="drop")
