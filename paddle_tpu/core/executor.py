"""Scope + Executor: the single-device runtime.

Reference: ``paddle/fluid/framework/scope.h:41`` (hierarchical name→Variable
map) and ``executor.cc`` / ``python/paddle/fluid/executor.py:256-474``.

TPU-native redesign: ``Executor.run`` does NOT interpret ops.  It analyzes
the requested (program, feed-signature, fetch-list) once, lowers the whole
block to a pure JAX function (core/lowering.py), ``jax.jit``s it with the
updated persistable state *donated* (so parameters update in-place in HBM),
and caches the compiled executable — the analogue of the reference's
program cache (``executor.py:207`` ``_get_program_cache_key``) plus kernel
dispatch, replaced by one XLA compile.  Feed batches with new shapes
trigger a recompile (cached per shape bucket), which is the
static-shape/recompile-cache policy SURVEY.md §7 calls out.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import compile_cache as _compile_cache
from . import flags as _flags
from . import host_ops as _host_ops
from .lowering import analyze_block, build_block_fn
from .program import EMPTY_VAR, Program, Variable, default_main_program
from .selected_rows import SelectedRows
from .types import np_dtype
from ..observability import debug_server as _debug_server
from ..observability import perf as _obs_perf
from ..observability import runlog as _obs_runlog
from ..observability import stats as _obs_stats
from ..observability import step_stats as _obs_step
from ..observability import trace as _obs_trace

RNG_STATE_VAR = "@RNG_STATE@"

# depth > 0 while _run_segmented drives per-segment inner runs on this
# thread: those runs suppress their own runlog records (the segmented
# step logs ONE aggregate record) — thread-local, executors are shared
_SEGMENT_TLS = threading.local()

_exec_metrics = None

# live executors for the debug server's /statusz (weak: the provider
# must never keep a notebook's discarded executor — and its compiled
# executables — alive)
_live_executors: "weakref.WeakSet" = weakref.WeakSet()


def _executor_statusz() -> dict:
    cap = _flags.get_flags("executor_cache_capacity")
    return {
        "cache_capacity": cap,
        "executors": [
            {"training": e._training,
             "cache_entries": len(e._cache),
             "seen_shape_buckets": len(e._seen_shapes)}
            for e in list(_live_executors)],
    }


_debug_server.register_provider("executors", _executor_statusz)


def _executor_pool_snapshot() -> dict:
    """MemoryLedger callback: the persistent-state scope's device
    bytes (shape × itemsize — no LazyFetch materialization, no sync)
    plus the live executors' executable-cache entry count."""
    scope_bytes = 0
    nvars = 0
    for v in list(global_scope().vars.values()):
        if isinstance(v, SelectedRows):
            v = v.values
        shape = getattr(v, "shape", None)
        dt = getattr(v, "dtype", None)
        if shape is None or dt is None:
            continue
        try:
            scope_bytes += int(np.prod(shape)) * np.dtype(dt).itemsize
            nvars += 1
        except (TypeError, ValueError):  # pragma: no cover - odd var
            continue
    entries = sum(len(e._cache) for e in list(_live_executors))
    return {"used": scope_bytes, "scope_vars": nvars,
            "cache_entries": entries}


def _register_memory_pools() -> None:
    """Register the executor's byte holders on the MemoryLedger —
    called from ``Executor.__init__`` so a flag-off process pays one
    flag read and never creates a pool."""
    from ..observability import memory as _memory
    if not _memory.enabled():
        return
    _memory.pool("executor_scope", "device", _executor_pool_snapshot)
    _compile_cache.register_memory_pool()


def _em():
    """Cached executor metric handles: registering through the registry
    on every run costs a lock + dict round trip per metric; the handles
    are process-wide and survive ``observability.reset()``, so create
    them once (hot-path budget: the whole telemetry cost per cached run
    must stay under 5% of a dispatch)."""
    global _exec_metrics
    m = _exec_metrics
    if m is None:
        sc = _obs_stats.scope("executor")
        import types as _t
        m = _t.SimpleNamespace(
            steps=sc.counter("steps"),
            hits=sc.counter("cache_hits"),
            misses=sc.counter("cache_misses"),
            shape_recompiles=sc.counter(
                "shape_recompiles",
                "compile-cache misses caused by a new feed-shape bucket "
                "for an already-compiled program"),
            evictions=sc.counter("cache_evictions"),
            feed_bytes=sc.counter("feed_bytes"),
            fetch_bytes=sc.counter("fetch_bytes"),
            wall=sc.histogram("run_wall_ms"),
        )
        _exec_metrics = m
    return m


_numerics_metrics = None


def _nm():
    """Cached numerics-sentinel metric handles (see ``_em``)."""
    global _numerics_metrics
    m = _numerics_metrics
    if m is None:
        sc = _obs_stats.scope("numerics")
        import types as _t
        m = _t.SimpleNamespace(
            nan=sc.counter("nan", "variables with NaN values caught by "
                           "the FLAGS_numerics_check post-step sentinel"),
            inf=sc.counter("inf", "variables with Inf values caught by "
                           "the FLAGS_numerics_check post-step sentinel"),
            checked=sc.counter("checked_steps"),
        )
        _numerics_metrics = m
    return m


def _numerics_mode() -> str:
    """'' (off) / 'warn' / 'fatal' from ``FLAGS_numerics_check``."""
    try:
        v = str(_flags.get_flags("numerics_check") or "").strip().lower()
    except KeyError:  # pragma: no cover - flag always defined
        return ""
    if v in ("", "0", "false", "off", "no", "none"):
        return ""
    return "fatal" if v == "fatal" else "warn"


class _CacheEntry:
    """One compiled-executable cache slot.  ``meta`` memoizes the
    telemetry constants of the executable (program_key string, feed and
    fetch byte totals) so the cached-run record path never re-hashes the
    big nested cache key or walks array metadata.

    Persistent-cache bookkeeping: a dispatch failure of an AOT
    executable (``from_disk`` set, or ``aot_ms`` not None — avals
    pinned at build time by disk hydration, inline AOT compile, or
    warm_start specs) falls back to a fresh lazy jit instead of
    failing the run (``Executor._recover_disk_entry``);
    ``fingerprint`` is the disk key; ``aot_ms`` the measured AOT
    compile cost (0.0 for disk hits — no compile was paid)."""

    __slots__ = ("plan", "jitted", "meta", "from_disk", "fingerprint",
                 "aot_ms", "perf", "fused_disabled", "fused_used")

    def __init__(self, plan, jitted):
        self.plan = plan
        self.jitted = jitted
        self.meta = None
        self.from_disk = False
        self.fingerprint = None
        self.aot_ms = None
        # set by _recover_fused_fault: this entry was re-lowered without
        # the fused sparse kernels after a dispatch-level compile fault
        # (recovery is once-per-entry — a second fault re-raises)
        self.fused_disabled = False
        # the lowering's trace-time latch dict ({"sparse_fused": bool},
        # build_block_fn._sparse_fused_used): did THIS entry's lowering
        # actually emit fused sparse kernels?  None for executables with
        # no reachable trace (disk hydrates).  Recovery gates on it —
        # the live flag value can lie in both directions
        self.fused_used = None
        # cost/memory attribution record (observability/perf.py) when
        # FLAGS_perf_attribution harvested this executable; else None
        self.perf = None

    def __iter__(self):
        # (plan, jitted) unpacking compatibility for cache introspection
        return iter((self.plan, self.jitted))


class Scope:
    """Name → device-value map with parent fallback (scope.h:41)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.vars: Dict[str, object] = {}
        self.parent = parent
        self.kids: List[Scope] = []

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def drop_kids(self) -> None:
        self.kids.clear()

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def set_var(self, name: str, value) -> None:
        self.vars[name] = value

    def erase(self, name: str) -> None:
        self.vars.pop(name, None)

    def local_names(self) -> List[str]:
        return list(self.vars)


class LazyFetch(np.lib.mixins.NDArrayOperatorsMixin):
    """Deferred ``Executor.run`` fetch: holds the device value and
    materializes to numpy on first host access, so back-to-back ``run``
    calls pipeline their dispatches instead of paying the host<->device
    round trip per step (the reference's async stream-execution role,
    ``details/threaded_ssa_graph_executor.cc:36``; on the tunneled chip
    one readback costs ~1.4 s, so an N-step user loop was N x RTT).

    Reading ANY pending fetch flushes ALL pending fetches in one batched
    ``jax.device_get`` — a whole training run's losses cost one round
    trip at the first read.  Shape/dtype/ndim are served without a sync.
    Acts as an ndarray for ufuncs/indexing/float()/format; anything else
    delegates to the materialized array."""

    _PENDING: List = []          # weakrefs: a dropped fetch frees its buffer
    _LOCK = threading.Lock()     # Executor.run is called from many threads
    _MAX_PENDING = 512  # flush backstop so unread fetches can't pile up

    def __init__(self, dev):
        self._dev = dev
        self._np = None
        self._err = None
        self._done = threading.Event()
        backstop = None
        with LazyFetch._LOCK:
            if len(LazyFetch._PENDING) >= LazyFetch._MAX_PENDING:
                backstop = LazyFetch._snapshot_locked()
            LazyFetch._PENDING.append(weakref.ref(self))
        if backstop:  # materialize OUTSIDE the lock (see _flush)
            LazyFetch._materialize(backstop)

    @classmethod
    def _snapshot_locked(cls):
        batch = [f for ref in cls._PENDING
                 if (f := ref()) is not None
                 and f._np is None and f._err is None]
        cls._PENDING.clear()
        return batch

    @classmethod
    def _flush(cls):
        # snapshot under the lock, read back OUTSIDE it: holding the lock
        # across the ~1.4 s tunneled device_get would serialize every
        # concurrent Executor.run on LazyFetch construction
        with cls._LOCK:
            batch = cls._snapshot_locked()
        cls._materialize(batch)

    @classmethod
    def _materialize(cls, batch):
        if not batch:
            return
        try:
            vals = jax.device_get([f._dev for f in batch])
        except Exception:
            # isolate the poisoned buffer: fetch one by one so a single
            # failed read cannot lose every other pending value
            for f in batch:
                try:
                    cls._assign(f, jax.device_get(f._dev))
                except Exception as e:
                    f._err = e
                    f._dev = None
                f._done.set()
            return
        for f, v in zip(batch, vals):
            cls._assign(f, v)
            f._done.set()

    @staticmethod
    def _assign(f, v):
        arr = np.asarray(v)
        if not arr.flags.writeable:
            arr = arr.copy()
        # ONE mutable array per fetch, like the sync path's returned
        # ndarray: user mutation through __setitem__/__array__ is visible
        # to later reads of the same fetch, never to other fetches
        f._np = arr
        f._dev = None

    def _val(self):
        if self._np is None and self._err is None:
            LazyFetch._flush()
            # raced another thread's in-flight snapshot: its device_get
            # will assign and signal; wait instead of double-fetching
            if self._np is None and self._err is None:
                if not self._done.wait(timeout=600.0):
                    raise RuntimeError(
                        "deferred fetch timed out waiting for another "
                        "thread's in-flight device readback")
        if self._err is not None:
            raise RuntimeError(
                f"deferred fetch failed: {self._err!r}") from self._err
        return self._np

    # metadata without sync (snapshot fields first: a concurrent flush
    # may assign _np and null _dev between attribute reads)
    @property
    def shape(self):
        a, dev = self._np, self._dev
        if a is not None:
            return a.shape
        if dev is not None:
            return tuple(dev.shape)
        return self._val().shape

    @property
    def dtype(self):
        a, dev = self._np, self._dev
        if a is not None:
            return a.dtype
        if dev is not None:
            return np.dtype(dev.dtype)
        return self._val().dtype

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= d
        return n

    def __array__(self, dtype=None, *args, **kwargs):
        # identity semantics like the sync path (np.asarray of the one
        # returned ndarray is that ndarray): hand out the fetch's own
        # mutable array; dtype conversion or an explicit numpy-2
        # copy=True request returns a private copy
        a = self._val()
        if kwargs.get("copy") or (args and args[0]):
            return np.array(a, dtype=dtype, copy=True)
        return np.asarray(a, dtype=dtype) if dtype is not None else a

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        inputs = tuple(np.asarray(i) if isinstance(i, LazyFetch) else i
                       for i in inputs)
        return getattr(ufunc, method)(*inputs, **kwargs)

    def __getitem__(self, idx):
        return self._val()[idx]

    def __setitem__(self, idx, value):
        self._val()[idx] = value

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        return iter(self._val())

    def __float__(self):
        return float(self._val())

    def __int__(self):
        return int(self._val())

    def __bool__(self):
        return bool(self._val())

    def __format__(self, spec):
        return format(self._val(), spec)

    def __repr__(self):
        return repr(self._val())

    def __str__(self):
        return str(self._val())

    def item(self, *args):
        return self._val().item(*args)

    def __getattr__(self, name):
        # anything beyond the fast-path surface: materialize and delegate.
        # Dunder protocols must NOT leak through (numpy would find the
        # ml_dtypes array's __array_interface__ and reinterpret bf16
        # buffers as void bytes; __array__ above is the one true door).
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return getattr(self._val(), name)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope: Scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        saved = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = saved

    return guard()


def _expand_lod_feeds(feed):
    """A fed LoDTensor splits into its padded array + the ``@LEN``
    companion (the reference's LoD travels inside the tensor; the padded
    contract carries lengths as a separate feed).  Nested (level-2)
    tensors additionally carry the inner [B, S] lengths as ``@LEN2``."""
    from ..lod_tensor import LoDTensor

    out = {}
    for name, val in feed.items():
        if isinstance(val, LoDTensor):
            out[name] = val.data
            out.setdefault(name + "@LEN", val.seq_lens)
            if val.inner_lens is not None:
                out.setdefault(name + "@LEN2", val.inner_lens)
        else:
            out[name] = val
    return out


def _as_device_array(value, var: Optional[Variable]):
    if isinstance(value, (jax.Array,)):
        return value
    if isinstance(value, SelectedRows):
        return SelectedRows(jnp.asarray(np.asarray(value.rows)),
                            jnp.asarray(np.asarray(value.values)),
                            value.height)
    arr = np.asarray(value)
    if var is not None and var.dtype is not None:
        arr = arr.astype(np_dtype(var.dtype), copy=False)
    return jnp.asarray(arr)


class Executor:
    """Single-device program runner (executor.py:256 equivalent).

    ``place`` is advisory — JAX owns device placement; pass
    ``paddle_tpu.TPUPlace()`` / ``CPUPlace()`` for API parity.
    """

    def __init__(self, place=None, training: bool = True):
        self.place = place
        self._cache: Dict = {}
        # telemetry: feed signatures seen per (program, fetch, mode) base
        # key, to distinguish shape-bucket recompiles from first compiles
        self._seen_shapes: Dict = {}
        # lowering mode: inference executors (the Predictor) pass
        # training=False so ctx.training-gated lowerings (dropout off
        # without an is_test attr, Pallas RNN cells inside the fusion ops
        # whose training path needs the vjp-friendly scan) pick the test
        # branch; part of the executable cache key
        self._training = training
        _live_executors.add(self)
        # fleet observability opt-in: FLAGS_debug_server_port=0 (default)
        # makes this a flag read — no socket, no thread; same deal for
        # the crash flight recorder (FLAGS_flight_record_dir empty)
        _debug_server.maybe_start_from_flags()
        from ..observability import flight as _flight
        _flight.arm_from_flags()
        # persistent compile cache tier B: point jax's own compilation
        # cache at FLAGS_compile_cache_dir/xla.  Flag unset (default):
        # one flag read, nothing else
        _compile_cache.wire_jax_cache()
        # memory anatomy: register the executable-cache + persistent-
        # scope pool (and the compile cache's disk pool) on the
        # MemoryLedger — one flag read when FLAGS_memory_attribution
        # is off, idempotent when on
        _register_memory_pools()
        # HA promotion awareness: last fleet-topology epoch this executor
        # acted on (see _refresh_promoted_endpoints)
        self._promo_epoch = 0

    def _refresh_promoted_endpoints(self) -> None:
        """Promotion-aware endpoint refresh: when any RPC client failed
        over to a NEW physical address since our last host-op dispatch
        (a backup was promoted / a replacement re-registered — the
        transport bumps a process-wide epoch), drop every cached
        logical→physical resolution before running this program's RPC
        host ops.  Endpoints that did not fail a request yet re-resolve
        through the registry instead of timing out into serial failovers
        mid-step.  One int compare when nothing moved."""
        from ..distributed import transport as _transport
        epoch = _transport.promotion_epoch()
        if epoch != self._promo_epoch:
            self._promo_epoch = epoch
            _transport.refresh_resolutions()

    # -- public API --------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, object]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        sync: bool = False,
    ):
        # one step-root span per top-level run (head-sampled by
        # FLAGS_trace_sample_rate): everything below — lowering, the
        # jitted dispatch, and every RPC the host ops issue — stitches
        # under this trace id, across processes (distributed/transport
        # carries the context on the wire).  Nested runs (device
        # segments, pserver optimize blocks) become child spans.
        with _obs_trace.start_span("executor::step", cat="executor"):
            return self._run_traced(program, feed, fetch_list, scope,
                                    return_numpy, use_program_cache, sync)

    def _run_traced(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, object]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        sync: bool = False,
    ):
        program = program if program is not None else default_main_program()
        feed = _expand_lod_feeds(feed or {})
        fetch_names = [v.name if isinstance(v, Variable) else str(v) for v in (fetch_list or [])]
        scope = scope or global_scope()
        program = self._prepare_program(program, feed)

        if any(_host_ops.is_host_op(op.type) for op in program.global_block.ops):
            return self._run_segmented(program, feed, fetch_names, scope, return_numpy)

        tel = _obs_trace.flags_on()
        rl = _obs_runlog.enabled() and \
            not getattr(_SEGMENT_TLS, "depth", 0)
        if rl:
            # before this dispatch donates buffers: queued records
            # whose fetches alias persistable state land while those
            # buffers are still alive (previous dispatch has
            # typically completed by now, so no blocking)
            _obs_runlog.drain_pending()
        pf = _obs_perf.enabled()
        t_run0 = time.perf_counter_ns() if (tel or rl or pf) else None

        feed_names = sorted(feed)
        block = program.global_block
        feed_vals = []
        for n in feed_names:
            var = block.var_or_none(n)
            feed_vals.append(self._put_feed(_as_device_array(feed[n], var)))

        sig = self._feed_sig(feed_names, feed_vals)
        base = (program._uid, program._version, tuple(fetch_names),
                self._training)
        key = self._mem_key(program, sig, fetch_names)
        entry = self._cache.get(key) if use_program_cache else None
        cache_hit = entry is not None
        lowering_ms = 0.0
        if entry is None:
            # analysis first: the state-read sets below are the plan's,
            # and (persistent cache) the state values double as the AOT
            # lowering's avals
            t_an0 = time.perf_counter_ns()
            plan = analyze_block(program, 0, feed_names, fetch_names)
            lowering_ms = (time.perf_counter_ns() - t_an0) / 1e6
        else:
            plan = entry.plan

        donated_state = [self._state_val(scope, block, n) for n in plan.donated_reads]
        const_state = [self._state_val(scope, block, n) for n in plan.const_reads]
        rng = scope.find_var(RNG_STATE_VAR)
        if rng is None:
            rng = jax.random.PRNGKey(program.random_seed or 0)
        rng = self._put_rng(rng)

        if entry is None:
            t_low0 = time.perf_counter_ns()
            with _obs_trace.start_span("executor::lower", cat="executor",
                                       root=False):
                entry = self._build_entry(
                    program, plan, sig, tuple(fetch_names), "run",
                    (feed_vals, donated_state, const_state, rng))
            t_low1 = time.perf_counter_ns()
            # the AOT compile (entry.aot_ms) reports as compile_ms below;
            # keep it out of lowering_ms or a cold first step counts it twice
            lowering_ms += max(
                0.0, (t_low1 - t_low0) / 1e6 - (entry.aot_ms or 0.0))
            if use_program_cache:
                self._cache[key] = entry
                self._evict_cache_overflow()
            if tel:
                self._note_cache_miss(base, sig)
                if _obs_trace.enabled():
                    _obs_trace.emit("executor::lower", t_low0, t_low1)
        elif tel:
            _em().hits.inc()
        plan, jitted = entry.plan, entry.jitted

        t0 = time.perf_counter() if _flags.get_flags("benchmark") else None

        nc = _numerics_mode()
        state_backup = None
        if nc == "fatal":
            # the dispatch DONATES the state buffers, so "raise before
            # the poisoned step applies" needs a pre-step copy to
            # restore into the scope (fatal is an opt-in debugging
            # mode; one state copy per step is its price)
            state_backup = [self._copy_state_val(v) for v in donated_state]

        compile_ms = 0.0
        t_disp0 = time.perf_counter_ns() if tel else None
        with _obs_trace.start_span("executor::dispatch", cat="executor",
                                   root=False):
            try:
                fetches, new_state, rng_out = jitted(feed_vals, donated_state,
                                                     const_state, rng)
            except Exception as e:
                jitted = self._recover_disk_entry(entry, program, e,
                                                  donated_state)
                try:
                    fetches, new_state, rng_out = jitted(
                        feed_vals, donated_state, const_state, rng)
                except Exception as e2:
                    # an AOT/disk entry recovered to a lazy re-lower that
                    # STILL faults: last chance is a fused-kernel compile
                    # fault — drop the kernels once, counted
                    jitted = self._recover_fused_fault(entry, program, e2,
                                                       donated_state)
                    fetches, new_state, rng_out = jitted(
                        feed_vals, donated_state, const_state, rng)
        if tel:
            t_disp1 = time.perf_counter_ns()
            if not cache_hit:
                # first call of a fresh executable: the synchronous part
                # is jax trace + XLA compile (execution is async), so this
                # wall time is the compile cost to within dispatch noise.
                # AOT-compiled entries (persistent cache) measured their
                # compile in the lower phase instead; disk hits paid none.
                compile_ms = (entry.aot_ms if entry.aot_ms is not None
                              else (t_disp1 - t_disp0) / 1e6)
            if _obs_trace.enabled():
                _obs_trace.emit("executor::dispatch", t_disp0, t_disp1)

        self._numerics_guard(nc, state_backup, fetch_names, fetches,
                             plan, new_state, scope)

        for name, val in zip(plan.persist_writes, new_state):
            self._note_state_write(name)
            scope.set_var(name, val)
        if plan.has_stateful:
            scope.set_var(RNG_STATE_VAR, rng_out)

        if _flags.get_flags("check_nan_inf"):
            # post-block NaN/Inf scan (FLAGS_check_nan_inf, operator.cc:31
            # post-kernel check at whole-block granularity)
            for name, val in list(zip(fetch_names, fetches)) + \
                    list(zip(plan.persist_writes, new_state)):
                arr = np.asarray(val.values if isinstance(val, SelectedRows)
                                 else val)
                # jnp.issubdtype: ml_dtypes floats (bfloat16, float8_*)
                # are invisible to np.issubdtype — the flagship bf16
                # workloads must not bypass the guard
                if jnp.issubdtype(arr.dtype, jnp.floating) and \
                        not np.all(np.isfinite(arr)):
                    raise FloatingPointError(
                        f"NaN/Inf detected in {name!r} "
                        f"(FLAGS_check_nan_inf)")
        if t0 is not None:
            sync_ref = next((v for v in list(fetches) + list(new_state)
                             if v is not None), None)
            if sync_ref is not None:
                np.asarray(sync_ref.values
                           if isinstance(sync_ref, SelectedRows)
                           else sync_ref)
            print(f"[benchmark] executor run: "
                  f"{(time.perf_counter() - t0) * 1e3:.3f} ms")

        if return_numpy:
            if sync:
                out = [self._fetch_to_numpy(v) for v in fetches]
            else:
                # async dispatch: wrap plain-array fetches lazily so user
                # step loops pipeline (one batched readback at first
                # access).  Fetches that alias persistable state
                # materialize NOW — the next run() donates that state's
                # buffer, and a deferred read of a donated buffer would
                # raise.
                persist = set(plan.persist_writes) | set(plan.donated_reads)
                out = []
                for name, v in zip(fetch_names, fetches):
                    if (isinstance(v, jax.Array) and name not in persist):
                        out.append(LazyFetch(v))
                    else:
                        out.append(self._fetch_to_numpy(v))
        else:
            out = list(fetches)
        if tel:
            self._record_step(entry, key, cache_hit, lowering_ms,
                              compile_ms, feed_vals, fetches, t_run0, plan,
                              donated_state, program=program)
        if pf and entry.perf is not None and t_run0 is not None:
            # feed the measured wall back into the cost/memory record
            # (roofline position) and sample the live device-memory
            # gauges — both ride the FLAGS_perf_attribution opt-in.
            # A cold step's wall subtracts the one-time lowering/compile
            # cost so the roofline rates reflect execution, not build
            _obs_perf.observe_step(
                entry.perf, self._program_key(key),
                self._perf_wall_ms(t_run0, cache_hit, lowering_ms,
                                   compile_ms, entry))
            _obs_perf.sample_device_memory()
        if rl:
            _obs_runlog.log_run(
                fetch_names, out,
                wall_ms=(time.perf_counter_ns() - t_run0) / 1e6,
                batch=_obs_runlog.batch_of(feed_vals))
        return out

    def run_callable(self, key: str, build_fn, feed: Sequence,
                     state: Sequence = (), const: Sequence = ()):
        """Dispatch a pure JAX callable through THIS executor's
        executable cache — the decode plane's entry point, and the
        general mechanism for cache-resident device state across
        dispatches.

        ``build_fn()`` returns ``fn(feed, state, const) -> (outs,
        new_state)`` (lists in, lists out).  The compiled executable is
        cached per ``(key, feed/state/const shape-dtype signature)`` in
        the SAME cache as program runs, and counts against the same
        ``executor.*`` telemetry (cache_hits / cache_misses /
        shape_recompiles / steps / run_wall_ms) — so a serving plane
        can pin "zero recompiles under mixed traffic" for callable
        dispatches exactly as it does for program dispatches.

        ``state`` buffers are DONATED: they stay device-resident and
        update in place in HBM across dispatches (a paged KV cache
        never round-trips to host); the caller must carry the returned
        ``new_state`` handles forward — the old ones are consumed.
        ``const`` values (model params) are neither donated nor copied.
        No persistent-cache tier: a callable has no canonical program
        fingerprint to key a disk entry by.

        Returns ``(outs, new_state)`` as device arrays (wrap in
        ``np.asarray`` to materialize)."""
        feed = [v if isinstance(v, jax.Array) else jnp.asarray(v)
                for v in feed]
        state = list(state)
        const = list(const)
        tel = _obs_trace.flags_on()
        t_run0 = time.perf_counter_ns() if tel else None
        sig = (self._feed_sig([str(i) for i in range(len(feed))], feed)
               + self._feed_sig([f"s{i}" for i in range(len(state))], state)
               + self._feed_sig([f"c{i}" for i in range(len(const))], const))
        base = ("callable", key, self._training)
        mem_key = ("callable", key, sig, self._training)
        entry = self._cache.get(mem_key)
        cache_hit = entry is not None
        lowering_ms = 0.0
        if entry is None:
            t_low0 = time.perf_counter_ns()
            jitted = jax.jit(build_fn(), donate_argnums=(1,))
            lowering_ms = (time.perf_counter_ns() - t_low0) / 1e6
            entry = _CacheEntry(None, jitted)
            self._cache[mem_key] = entry
            self._evict_cache_overflow()
            if tel:
                self._note_cache_miss(base, sig)
        elif tel:
            _em().hits.inc()
        compile_ms = 0.0
        t_disp0 = time.perf_counter_ns() if tel else None
        with _obs_trace.start_span("executor::dispatch", cat="executor",
                                   root=False):
            outs, new_state = entry.jitted(feed, state, const)
        if tel:
            t_disp1 = time.perf_counter_ns()
            if not cache_hit:
                # first call of a fresh executable: the synchronous part
                # is jax trace + XLA compile (execution is async)
                compile_ms = (t_disp1 - t_disp0) / 1e6
            m = _em()
            m.steps.inc()
            wall_ms = (time.perf_counter_ns() - t_run0) / 1e6
            m.wall.observe(wall_ms)
            _obs_step.record(_obs_step.StepStats(
                program_key=f"callable:{key}",
                cache_hit=cache_hit,
                lowering_ms=round(lowering_ms, 3),
                compile_ms=round(compile_ms, 3),
                feed_bytes=sum(_obs_step.approx_nbytes(v) for v in feed),
                fetch_bytes=sum(_obs_step.approx_nbytes(v) for v in outs),
                wall_ms=round(wall_ms, 3)))
        return outs, new_state

    def run_steps(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, object]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
    ):
        """Run K training steps in ONE device dispatch via ``lax.scan``.

        ``feed`` maps each feed name to a *stacked* array with a leading
        step dimension ``[K, ...]``; step i consumes slice i (fresh data
        per step, unlike repeating ``run`` which pays per-step dispatch).
        Fetches come back stacked ``[K, ...]``.  Persistable state
        (params, optimizer moments, BN stats, RNG) advances exactly as K
        ``run`` calls would.  The TPU-native replacement for the
        reference's C++ executor loop over a pre-fed data queue — and the
        steady-state loop bench.py measures.
        """
        program = program if program is not None else default_main_program()
        feed = feed or {}
        from ..lod_tensor import LoDTensor
        for n, v in feed.items():
            if isinstance(v, LoDTensor):
                raise TypeError(
                    f"run_steps feed {n!r} is a LoDTensor — the leading "
                    "dim of a run_steps feed is the STEP count, not the "
                    "batch; stack padded arrays + '@LEN' vectors per "
                    "step instead")
        if not feed:
            raise ValueError("run_steps needs at least one stacked feed "
                             "to define the step count")
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]
        scope = scope or global_scope()
        program = self._prepare_program(program, feed)
        if any(_host_ops.is_host_op(op.type)
               for op in program.global_block.ops):
            raise NotImplementedError(
                "run_steps cannot scan programs with host ops (RPC/IO); "
                "use run() per step")

        tel = _obs_trace.flags_on()
        rl = _obs_runlog.enabled() and \
            not getattr(_SEGMENT_TLS, "depth", 0)
        if rl:
            # before this dispatch donates buffers: queued records
            # whose fetches alias persistable state land while those
            # buffers are still alive (previous dispatch has
            # typically completed by now, so no blocking)
            _obs_runlog.drain_pending()
        pf = _obs_perf.enabled()
        t_run0 = time.perf_counter_ns() if (tel or rl or pf) else None

        feed_names = sorted(feed)
        block = program.global_block
        ks = {np.asarray(feed[n]).shape[0] for n in feed_names}
        if len(ks) != 1:
            raise ValueError(
                f"stacked feeds disagree on the step count: { {n: np.asarray(feed[n]).shape[0] for n in feed_names} }")
        (K,) = ks
        stacked = []
        for n in feed_names:
            var = block.var_or_none(n)
            arr = np.asarray(feed[n])
            steps = [_as_device_array(a, var) for a in arr]
            stacked.append(jax.device_put(np.stack(steps)))

        sig = self._feed_sig(feed_names, stacked)
        base = (program._uid, program._version, tuple(fetch_names),
                "run_steps", self._training)
        key = self._mem_key(program, sig, fetch_names, mode="run_steps")
        entry = self._cache.get(key)
        cache_hit = entry is not None
        lowering_ms = 0.0
        if entry is None:
            # analysis timed apart from the state gathering below: the
            # H2D transfer of params must not inflate lowering_ms
            t_an0 = time.perf_counter_ns()
            plan = analyze_block(program, 0, feed_names, fetch_names)
            lowering_ms = (time.perf_counter_ns() - t_an0) / 1e6
        else:
            plan = entry.plan

        donated_state = [self._state_val(scope, block, n)
                         for n in plan.donated_reads]
        const_state = [self._state_val(scope, block, n)
                       for n in plan.const_reads]
        rng = scope.find_var(RNG_STATE_VAR)
        if rng is None:
            rng = jax.random.PRNGKey(program.random_seed or 0)
        rng = self._put_rng(rng)

        if entry is None:
            t_low0 = time.perf_counter_ns()
            build = self._make_scan_builder(program, plan)
            entry = self._build_entry(
                program, plan, sig, tuple(fetch_names), "run_steps",
                (stacked, donated_state, const_state, rng), build_fn=build)
            self._cache[key] = entry
            self._evict_cache_overflow()
            t_low1 = time.perf_counter_ns()
            # AOT compile time reports as compile_ms, not lowering.
            # Unconditional like run()'s: _perf_wall_ms subtracts
            # lowering_ms from cold perf-record walls even when tel off
            lowering_ms += max(
                0.0, (t_low1 - t_low0) / 1e6 - (entry.aot_ms or 0.0))
            if tel:
                self._note_cache_miss(base, sig)
                if _obs_trace.enabled():
                    _obs_trace.emit("executor::lower", t_low0, t_low1)
        elif tel:
            _em().hits.inc()
        plan, jitted = entry.plan, entry.jitted

        nc = _numerics_mode()
        state_backup = None
        if nc == "fatal":
            # donation consumes the pre-step buffers; see run()
            state_backup = [self._copy_state_val(v) for v in donated_state]

        compile_ms = 0.0
        t_disp0 = time.perf_counter_ns() if tel else None
        # run_steps admits no host ops, so the K-step dispatch IS the
        # step: one root span (head-sampled like run()'s)
        with _obs_trace.start_span("executor::step", cat="executor",
                                   tags={"k_steps": K}):
            try:
                fetches, new_state, rng_out = jitted(stacked, donated_state,
                                                     const_state, rng)
            except Exception as e:
                jitted = self._recover_disk_entry(
                    entry, program, e, donated_state,
                    build_fn=self._make_scan_builder(program, entry.plan))
                try:
                    fetches, new_state, rng_out = jitted(
                        stacked, donated_state, const_state, rng)
                except Exception as e2:
                    # see run(): AOT/disk recovery faulting again can
                    # only be saved by dropping the fused kernels once
                    jitted = self._recover_fused_fault(
                        entry, program, e2, donated_state,
                        build_fn=self._make_scan_builder(program,
                                                         entry.plan))
                    fetches, new_state, rng_out = jitted(
                        stacked, donated_state, const_state, rng)
        if tel:
            t_disp1 = time.perf_counter_ns()
            if not cache_hit:
                compile_ms = (entry.aot_ms if entry.aot_ms is not None
                              else (t_disp1 - t_disp0) / 1e6)
            if _obs_trace.enabled():
                _obs_trace.emit("executor::dispatch", t_disp0, t_disp1)
        self._numerics_guard(nc, state_backup, fetch_names, fetches,
                             plan, new_state, scope)
        for name, val in zip(plan.persist_writes, new_state):
            self._note_state_write(name)
            scope.set_var(name, val)
        if plan.has_stateful:
            scope.set_var(RNG_STATE_VAR, rng_out)
        if return_numpy:
            out = [np.asarray(v) for v in fetches]
        else:
            out = list(fetches)
        if tel:
            self._record_step(entry, key, cache_hit, lowering_ms,
                              compile_ms, stacked, fetches, t_run0, plan,
                              donated_state, program=program)
        if pf and entry.perf is not None and t_run0 is not None:
            # dispatch wall covers K steps, and so does the record's
            # flops/bytes — the roofline rates normalize consistently
            _obs_perf.observe_step(
                entry.perf, self._program_key(key),
                self._perf_wall_ms(t_run0, cache_hit, lowering_ms,
                                   compile_ms, entry))
            _obs_perf.sample_device_memory()
        if rl:
            _obs_runlog.log_run_steps(
                fetch_names, out if return_numpy else fetches, K,
                wall_ms=(time.perf_counter_ns() - t_run0) / 1e6,
                batch=_obs_runlog.batch_of(stacked, axis=1))
        return out

    def _fetch_to_numpy(self, v):
        return np.asarray(v)

    # -- persistent compile cache (core/compile_cache.py) ------------------
    def _make_scan_builder(self, program: Program, plan):
        """Builder for run_steps' K-step ``lax.scan`` wrapper (the
        executable the cache stores for mode="run_steps")."""
        def build(disable_sparse_fused=False):
            fn = build_block_fn(program, plan, training=self._training,
                                mesh=self._mesh(),
                                disable_sparse_fused=disable_sparse_fused)
            refeed = plan.donated_write_indices
            n_writes = len(plan.persist_writes)
            extra_idx = [i for i in range(n_writes)
                         if i not in set(refeed)]

            def multi(stacked, donated, const, rng):
                # All persistable writes ride the scan CARRY; only
                # fetches are stacked as ys.  Stacking state would
                # allocate O(K x full model state) HBM per dispatch.
                # Write-only slots (not refed) are seeded with zeros —
                # the block never reads them, each step overwrites.
                if extra_idx:
                    _, ns, _ = jax.eval_shape(
                        fn, [s[0] for s in stacked], donated, const, rng)
                    extra0 = [jnp.zeros(ns[i].shape, ns[i].dtype)
                              for i in extra_idx]
                else:
                    extra0 = []

                def one(carry, xs):
                    donated, _, rng = carry
                    fetches, new_state, rng = fn(list(xs), donated, const,
                                                 rng)
                    return ([new_state[i] for i in refeed],
                            [new_state[i] for i in extra_idx],
                            rng), fetches
                (donated, extra, rng), fetches = jax.lax.scan(
                    one, (donated, extra0, rng), tuple(stacked))
                final_state = [None] * n_writes
                for slot, i in enumerate(refeed):
                    final_state[i] = donated[slot]
                for slot, i in enumerate(extra_idx):
                    final_state[i] = extra[slot]
                return fetches, final_state, rng

            multi._sparse_fused_used = fn._sparse_fused_used
            return multi
        return build

    @staticmethod
    def _feed_sig(feed_names, vals) -> tuple:
        """Feed-signature component of the executable cache key; ``vals``
        are device arrays or ShapeDtypeStructs (warm_start) — both carry
        the shape/dtype the compiled executable is pinned to."""
        return tuple((n, tuple(v.shape), str(v.dtype))
                     for n, v in zip(feed_names, vals))

    def _mem_key(self, program: Program, sig, fetch_names,
                 mode: str = "run") -> tuple:
        """THE in-memory executable-cache key.  warm_start precompiles
        install entries under this same key, so every component lives
        here — run()/run_steps()/_warm_one must never reassemble it by
        hand (a drifted copy silently defeats warm starts)."""
        if mode == "run":
            return (program._uid, program._version, sig,
                    tuple(fetch_names), self._training)
        return (program._uid, program._version, sig, tuple(fetch_names),
                mode, self._training)

    def _build_entry(self, program: Program, plan, sig, fetch_names: tuple,
                     mode: str, args, build_fn=None,
                     force_aot: bool = False,
                     hydrate_only: bool = False) -> _CacheEntry:
        """Resolve the executable for a fresh cache slot.

        Persistent cache enabled: disk load (tier A hit — no trace, no
        compile) → AOT ``lower(...).compile()`` + serialize to disk.
        Disabled (default): lazy ``jax.jit``, byte-for-byte the
        pre-cache behavior, unless ``force_aot`` (warm_start) asks for
        an eager compile anyway.  ``hydrate_only`` returns None on a
        disk miss instead of compiling (a restarting worker that wants
        the restart win but must not block its startup on cold-cache
        compiles).  ``args`` are the concrete call args or
        ShapeDtypeStructs — the AOT lowering's avals; any aval guessed
        wrong is recovered at dispatch (``_recover_disk_entry``).
        """
        raw_make = build_fn or (lambda: build_block_fn(
            program, plan, training=self._training, mesh=self._mesh()))
        used_cell = []  # the raw fn's _sparse_fused_used dict, once built

        def make(**kw):
            fn = raw_make(**kw)
            cell = getattr(fn, "_sparse_fused_used", None)
            if cell is not None:
                used_cell[:] = [cell]
            return fn

        if _compile_cache.enabled():
            fp = _compile_cache.fingerprint(program, sig, fetch_names,
                                            self._training, mode,
                                            self._mesh())
            compiled = _compile_cache.load(fp, count_miss=not hydrate_only)
            if compiled is not None:
                entry = _CacheEntry(plan, compiled)
                entry.from_disk = True
                entry.fingerprint = fp
                entry.aot_ms = 0.0
                entry.perf = _obs_perf.harvest(compiled, "disk", mode,
                                               compile_ms=0.0)
                return entry
            if hydrate_only:
                return None
            jitted = jax.jit(make(), donate_argnums=(1,))
            t0 = time.perf_counter_ns()
            compiled = jitted.lower(*args).compile()
            aot_ms = (time.perf_counter_ns() - t0) / 1e6
            _compile_cache.store(fp, compiled,
                                 meta={"mode": mode,
                                       "fetches": list(fetch_names)})
            entry = _CacheEntry(plan, compiled)
            entry.fused_used = used_cell[0] if used_cell else None
            entry.fingerprint = fp
            entry.aot_ms = aot_ms
            entry.perf = _obs_perf.harvest(compiled, "compile", mode,
                                           compile_ms=aot_ms)
            return entry
        if hydrate_only:
            return None
        jitted = jax.jit(make(), donate_argnums=(1,))
        if force_aot or _obs_perf.enabled():
            # perf attribution needs the compiled handle (cost/memory
            # analysis lives on jax.stages.Compiled): compile the SAME
            # executable eagerly instead of at first dispatch.  A
            # dispatch fault of this AOT entry recovers to a lazy jit
            # like every other AOT entry (_recover_disk_entry)
            t0 = time.perf_counter_ns()
            jitted = jitted.lower(*args).compile()
            entry = _CacheEntry(plan, jitted)
            entry.fused_used = used_cell[0] if used_cell else None
            entry.aot_ms = (time.perf_counter_ns() - t0) / 1e6
            entry.perf = _obs_perf.harvest(jitted, "compile", mode,
                                           compile_ms=entry.aot_ms)
            return entry
        entry = _CacheEntry(plan, jitted)
        entry.fused_used = used_cell[0] if used_cell else None
        return entry

    def _recover_disk_entry(self, entry: _CacheEntry, program: Program,
                            exc, donated_state, build_fn=None):
        """An AOT executable whose dispatch fails is replaced in-place
        by a fresh lazy jit and the call retried: disk-hydrated entries
        and warm_start precompiles can mismatch the live scope
        (fingerprint blind spot, stale device assignment, wrong spec),
        and even a long-validated AOT ``Compiled`` is pinned to state
        avals the lazy jit would simply have retraced for (a user
        resizing a persistable var in the scope).  The fault is
        counted, the entry file evicted (stale for this key either
        way), and the run proceeds as a plain compile.

        Failures of lazy-jit entries — which already retrace per call —
        re-raise untouched UNLESS their lowering emitted fused sparse
        kernels (entry.fused_used latch): a
        fused-kernel Mosaic/XLA compile fault only surfaces at this
        layer (the per-op try/except in kernels/sparse.py covers trace
        time only), so the counted-fallback contract is completed here
        by ONE re-lower with the fused kernels disabled.  A fault AFTER
        execution started (donated buffers already consumed: a retry
        would read deleted arrays) always re-raises; aval/sharding and
        compile faults raise before any donation."""
        if any(isinstance(v, jax.Array) and v.is_deleted()
               for v in donated_state):
            raise exc
        if entry.aot_ms is None and not entry.from_disk:
            return self._recover_fused_fault(entry, program, exc,
                                             donated_state, build_fn)
        if entry.fingerprint is not None:
            # a cache-keyed executable (disk-hydrated or stored): count
            # the fault against the cache and evict the stale entry.
            # warm_start force-AOT entries with the cache OFF recompile
            # silently — there is no cache to blame
            _compile_cache.dispatch_fault(entry.fingerprint, exc)
        jitted = jax.jit(self._entry_builder(entry, program, build_fn)(),
                         donate_argnums=(1,))
        entry.jitted = jitted
        entry.from_disk = False
        entry.aot_ms = None
        return jitted

    def _entry_builder(self, entry, program, build_fn=None):
        """Block-fn builder for fault-recovery re-lowers; accepts
        ``disable_sparse_fused`` (both producers — the default
        build_block_fn closure and _make_scan_builder's build — do).
        The rebuilt fn's trace-time used-latch replaces the entry's (a
        disk-hydrated entry has none until its lazy rebuild traces)."""
        def mk(disable_sparse_fused=False):
            if build_fn is not None:
                fn = build_fn(disable_sparse_fused=disable_sparse_fused)
            else:
                fn = build_block_fn(
                    program, entry.plan, training=self._training,
                    mesh=self._mesh(),
                    disable_sparse_fused=disable_sparse_fused)
            cell = getattr(fn, "_sparse_fused_used", None)
            if cell is not None:
                entry.fused_used = cell
            return fn
        return mk

    def _recover_fused_fault(self, entry, program, exc, donated_state,
                             build_fn=None):
        """Last line of the FLAGS_sparse_fused_kernel counted-fallback
        contract: a compile fault that only surfaces at dispatch (Mosaic
        on a real TPU — invisible to the trace-time try/except in
        kernels/sparse.py) re-lowers the step ONCE with the fused
        kernels disabled, counted in sparse_fused.runtime_disables.
        Reached for lazy-jit entries directly from _recover_disk_entry,
        and from the run()/run_steps() second-level retry when an
        AOT/disk entry's fused re-lower faults again.  Gated on the
        ENTRY's trace-time latch (entry.fused_used — the flag's live
        value can lie in both directions: flipped since the trace, or
        on for a program with no sparse lookups); anything whose
        lowering emitted no fused kernels re-raises untouched."""
        from ..kernels import quant as _quant_kernels
        from ..kernels import sparse as _sparse_kernels
        cell = entry.fused_used
        if entry.fused_disabled or not (
                cell and (cell.get("sparse_fused")
                          or cell.get("int8_fused"))):
            raise exc
        if any(isinstance(v, jax.Array) and v.is_deleted()
               for v in donated_state):
            raise exc
        if cell.get("sparse_fused"):
            _sparse_kernels.count_runtime_disable()
        if cell.get("int8_fused"):
            _quant_kernels.count_runtime_disable()
        mk = self._entry_builder(entry, program, build_fn)
        jitted = jax.jit(mk(disable_sparse_fused=True), donate_argnums=(1,))
        entry.jitted = jitted
        entry.from_disk = False
        entry.aot_ms = None
        entry.fused_disabled = True
        return jitted

    def warm_start(self, program: Optional[Program] = None,
                   feed_specs: Optional[Dict[str, object]] = None,
                   fetch_list: Optional[Sequence] = None,
                   scope: Optional[Scope] = None,
                   hydrate_only: bool = False) -> dict:
        """AOT-precompile ``(program, feed_specs, fetch_list)`` and
        hydrate this executor's executable cache *before the first
        batch* — from the persistent disk cache when
        ``FLAGS_compile_cache_dir`` is set (an elastic-restarted worker
        skips the whole compile), else by compiling now (and, with the
        cache enabled, storing for the next process).

        ``feed_specs`` maps feed names to shape tuples, ``(shape,
        dtype)`` pairs (shape itself a tuple/list), numpy/jax arrays,
        or ``jax.ShapeDtypeStruct``s — only shape/dtype are read, no
        feed data is needed.  A LIST of such dicts warms one executable
        per entry (the serving plane precompiles a whole batch-size
        bucket ladder this way); the returned counts aggregate over
        all of them.  Shapes must be concrete.  Names are the
        post-expansion feed names (a LoD feed contributes its padded
        array plus the ``<name>@LEN`` length vector).  The scope must
        already hold the program's persistable state (run the startup
        program / restore the checkpoint first): state shapes are part
        of the executable.

        Programs containing host ops (the transpiled trainer program)
        warm every device segment whose inputs are covered by
        ``feed_specs`` + scope; segments fed by an earlier host op's
        runtime output are skipped (reported in ``skipped``).

        ``hydrate_only=True`` takes disk hits but never compiles on a
        miss — for restart paths that want the warm-cache win without
        blocking startup on cold-cache compiles (the pserver hydrates
        before binding its port; a cold cache keeps the old lazy
        compile-at-first-round behavior).

        Returns {"segments", "warmed", "persistent_hits", "compiled",
        "skipped": [...], "ms"}.
        """
        program = program if program is not None else default_main_program()
        scope = scope or global_scope()
        if isinstance(feed_specs, (list, tuple)):
            # one warm per spec-set (a serving bucket ladder): aggregate
            # the counts, keep every skip reason
            agg = {"segments": 0, "warmed": 0, "persistent_hits": 0,
                   "compiled": 0, "skipped": [], "ms": 0.0}
            for fs in feed_specs:
                one = self.warm_start(program, fs, fetch_list, scope,
                                      hydrate_only=hydrate_only)
                for k in ("segments", "warmed", "persistent_hits",
                          "compiled"):
                    agg[k] += one[k]
                agg["skipped"].extend(one["skipped"])
                agg["ms"] = round(agg["ms"] + one["ms"], 3)
            return agg
        feed_specs = dict(feed_specs or {})
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]
        t0 = time.perf_counter()
        _compile_cache.wire_jax_cache()
        program = self._prepare_program(program, feed_specs)
        out = {"segments": 0, "warmed": 0, "persistent_hits": 0,
               "compiled": 0, "skipped": [], "ms": 0.0}

        if any(_host_ops.is_host_op(op.type)
               for op in program.global_block.ops):
            segs = self._segment_plan(program, tuple(sorted(feed_specs)),
                                      tuple(fetch_names))
            for i, seg in enumerate(segs):
                if seg[0] != "device":
                    continue
                _, sub, seg_fetches, reads = seg
                sub_specs = {n: v for n, v in feed_specs.items()
                             if n in reads}
                self._warm_one(sub, sub_specs, seg_fetches, scope, out,
                               label=f"segment[{i}]",
                               hydrate_only=hydrate_only)
        else:
            self._warm_one(program, feed_specs, fetch_names, scope, out,
                           label="program", hydrate_only=hydrate_only)
        out["ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        return out

    def _warm_one(self, program: Program, feed_specs: Dict, fetch_names,
                  scope: Scope, out: dict, label: str,
                  hydrate_only: bool = False) -> None:
        out["segments"] += 1
        feed_names = sorted(feed_specs)
        block = program.global_block
        feed_avals = [self._spec_aval(feed_specs[n], block.var_or_none(n))
                      for n in feed_names]
        sig = self._feed_sig(feed_names, feed_avals)
        key = self._mem_key(program, sig, fetch_names)
        if key in self._cache:
            out["warmed"] += 1
            return
        plan = analyze_block(program, 0, feed_names, fetch_names)
        try:
            donated_state = [self._warm_state_aval(scope, block, n)
                             for n in plan.donated_reads]
            const_state = [self._warm_state_aval(scope, block, n)
                           for n in plan.const_reads]
        except RuntimeError as e:
            # state produced at runtime by an earlier host op with no
            # static declaration (and the scope doesn't hold it yet):
            # nothing to precompile
            out["skipped"].append(f"{label}: {e}")
            return
        rng = scope.find_var(RNG_STATE_VAR)
        if rng is None:
            rng = jax.random.PRNGKey(program.random_seed or 0)
        rng = self._put_rng(rng)
        entry = self._build_entry(
            program, plan, sig, tuple(fetch_names), "run",
            (feed_avals, donated_state, const_state, rng), force_aot=True,
            hydrate_only=hydrate_only)
        if entry is None:  # hydrate_only + disk miss: leave it lazy
            out["skipped"].append(f"{label}: persistent-cache miss "
                                  "(hydrate_only)")
            return
        self._cache[key] = entry
        self._evict_cache_overflow()
        out["warmed"] += 1
        if entry.from_disk:
            out["persistent_hits"] += 1
        else:
            out["compiled"] += 1

    def _warm_state_aval(self, scope: Scope, block, name: str):
        """State input for a warm_start lowering: the live scope value
        when present (exact avals), else an abstract aval from the
        program's static var declaration (a pserver's grad inputs exist
        only at runtime but are fully declared).  Raises RuntimeError
        when neither is available."""
        if scope.find_var(name) is not None:
            return self._state_val(scope, block, name)
        var = block.var_or_none(name)
        from .types import VarType
        if var is None or var.shape is None or var.dtype is None or \
                any(s < 0 for s in var.shape) or \
                var.type != VarType.DENSE_TENSOR:
            raise RuntimeError(
                f"variable {name!r} is neither in the scope nor "
                f"statically declared (shape/dtype) in the program")
        return jax.ShapeDtypeStruct(
            tuple(int(s) for s in var.shape),
            jax.dtypes.canonicalize_dtype(np_dtype(var.dtype)))

    @staticmethod
    def _spec_aval(spec, var: Optional[Variable]) -> "jax.ShapeDtypeStruct":
        """Normalize one warm_start feed spec to the aval the real run
        will produce: the executor casts host arrays to the program
        var's dtype (``_as_device_array``), so a declared var dtype
        wins over a host spec's — but a ``jax.Array`` spec is fed
        through UNCAST by the real path, so its dtype stands."""
        dtype = None
        if isinstance(spec, jax.Array):
            return jax.ShapeDtypeStruct(tuple(spec.shape),
                                        np.dtype(spec.dtype))
        if isinstance(spec, jax.ShapeDtypeStruct):
            shape, dtype = tuple(spec.shape), np.dtype(spec.dtype)
        elif hasattr(spec, "shape") and hasattr(spec, "dtype"):
            shape, dtype = tuple(spec.shape), np.dtype(spec.dtype)
        elif isinstance(spec, (tuple, list)) and len(spec) == 2 and \
                isinstance(spec[0], (tuple, list)):
            shape, dtype = tuple(spec[0]), np.dtype(spec[1])
        elif isinstance(spec, (tuple, list)):
            shape = tuple(spec)
        else:
            raise TypeError(
                f"warm_start feed spec must be a shape tuple, "
                f"(shape, dtype) pair, array, or ShapeDtypeStruct; "
                f"got {spec!r}")
        shape = tuple(int(s) for s in shape)
        if any(s < 0 for s in shape):
            raise ValueError(
                f"warm_start feed shape {shape} has a dynamic (-1) dim; "
                "precompilation needs concrete shapes")
        if var is not None and var.dtype is not None:
            dtype = np.dtype(np_dtype(var.dtype))
        elif dtype is None:
            dtype = np.dtype("float32")
        # the device array the real run feeds is jnp.asarray's view of
        # the cast value: canonicalized (x64 off ⇒ int64→int32 etc.)
        return jax.ShapeDtypeStruct(shape,
                                    jax.dtypes.canonicalize_dtype(dtype))

    # -- host-op segmented execution ---------------------------------------
    # Blocks containing host ops (core/host_ops.py: RPC, pserver loop, IO)
    # are partitioned into maximal device segments — each lowered + jitted
    # exactly like a plain block — interleaved with host-op calls against
    # the scope.  This is the TPU translation of the reference op loop
    # running send/recv/listen_and_serv kernels in program order
    # (executor.cc:390, operators/send_op.cc:29, listen_and_serv_op.cc:102).

    def _segment_plan(self, program: Program, feed_names: tuple, fetch_names: tuple):
        key = ("seg", program._uid, program._version, feed_names, fetch_names)
        segs = self._cache.get(key)
        if segs is not None:
            return segs
        block = program.global_block
        runs: List = []  # (kind, start, end) over block.ops
        for i, op in enumerate(block.ops):
            kind = "host" if _host_ops.is_host_op(op.type) else "device"
            if runs and runs[-1][0] == kind:
                runs[-1][2] = i + 1
            else:
                runs.append([kind, i, i + 1])
        segs = []
        for idx, (kind, a, b) in enumerate(runs):
            if kind == "host":
                segs.append(("host", block.ops[a:b]))
                continue
            needed_later = set(fetch_names)
            for _, a2, b2 in runs[idx + 1:]:
                for op in block.ops[a2:b2]:
                    needed_later.update(op.input_arg_names())
            produced = set()
            for op in block.ops[a:b]:
                produced.update(op.output_arg_names())
            seg_fetches = sorted((produced & needed_later) - {EMPTY_VAR, ""})
            sub = program.clone()
            sub.global_block.ops = sub.global_block.ops[a:b]
            reads, defined = set(), set()
            for op in sub.global_block.ops:
                reads.update(n for n in op.input_arg_names() if n not in defined)
                defined.update(op.output_arg_names())
            segs.append(("device", sub, seg_fetches, reads))
        self._cache[key] = segs
        return segs

    def _run_segmented(self, program, feed, fetch_names, scope, return_numpy):
        self._refresh_promoted_endpoints()
        rl = _obs_runlog.enabled()
        t_seg0 = time.perf_counter_ns() if rl else None
        backup = None
        if _numerics_mode() == "fatal":
            # the per-segment sentinel restore only covers ONE segment's
            # donated state; 'scope restored intact' needs every
            # persistable snapshotted before the FIRST segment runs
            backup = [
                (v.name, self._copy_state_val(scope.find_var(v.name)))
                for v in program.global_block.vars.values()
                if getattr(v, "persistable", False)
                and scope.find_var(v.name) is not None]
        _SEGMENT_TLS.depth = getattr(_SEGMENT_TLS, "depth", 0) + 1
        try:
            out = self._run_segments(program, feed, fetch_names, scope,
                                     return_numpy)
        except FloatingPointError:
            if backup is not None:
                for name, val in backup:
                    scope.set_var(name, val)
            raise
        finally:
            _SEGMENT_TLS.depth -= 1
        if rl:
            # ONE record per step: the inner per-segment runs suppressed
            # theirs (per-segment step_ms/boundary fetches would corrupt
            # the series), this one carries the user's fetches and the
            # whole-step wall including host ops
            _obs_runlog.log_run(
                fetch_names, out,
                wall_ms=(time.perf_counter_ns() - t_seg0) / 1e6,
                batch=_obs_runlog.batch_of(list(feed.values())))
        return out

    def _run_segments(self, program, feed, fetch_names, scope,
                      return_numpy):
        segs = self._segment_plan(program, tuple(sorted(feed)), tuple(fetch_names))
        fetched: Dict[str, object] = {}
        # host ops read their inputs from the scope; make fed values visible
        for seg in segs:
            if seg[0] == "host":
                for op in seg[1]:
                    for n in op.input_arg_names():
                        if n in feed:
                            scope.set_var(n, feed[n])
        for seg in segs:
            if seg[0] == "host":
                for op in seg[1]:
                    # one child span per host op: in a stitched trace
                    # the send/recv/barrier rows sit between the device
                    # segments, with the pserver's server spans hanging
                    # under them via the wire context
                    with _obs_trace.start_span("host_op::" + op.type,
                                               cat="executor", root=False):
                        _host_ops.run_host_op(self, program, op, scope)
                continue
            _, sub, seg_fetches, reads = seg
            sub_feed = {n: v for n, v in feed.items() if n in reads}
            # keyword form: ParallelExecutor.run's positional signature
            # differs (reference parity), but both accept program=/scope=
            vals = self.run(program=sub, feed=sub_feed,
                            fetch_list=seg_fetches,
                            scope=scope, return_numpy=False)
            for n, v in zip(seg_fetches, vals):
                fetched[n] = v
                scope.set_var(n, v)
        out = []
        for n in fetch_names:
            v = fetched.get(n)
            if v is None:
                v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"fetch target {n!r} was not produced by any program "
                    f"segment and is not in the scope")
            if return_numpy and not isinstance(v, SelectedRows):
                v = self._fetch_to_numpy(v)  # PE: process_allgather of
                # non-addressable multi-host shards; plain Executor: asarray
            out.append(v)
        return out

    # -- telemetry (paddle_tpu/observability) ------------------------------
    def _note_cache_miss(self, base, sig) -> None:
        m = _em()
        m.misses.inc()
        if len(self._seen_shapes) > 1024:
            # bound the side-table (telemetry only: a clear just makes
            # the next miss per base count as a first compile, not a
            # shape recompile) — shape churn must not leak memory here
            # while the executable cache itself is capped
            self._seen_shapes.clear()
        seen = self._seen_shapes.setdefault(base, set())
        if seen and sig not in seen:
            # same program+fetches, new feed signature: a shape-bucket
            # recompile (the static-shape policy's cost made visible —
            # a storm of these means feed shapes are churning)
            m.shape_recompiles.inc()
        if len(seen) > 1024:  # same leak bound, per-base
            seen.clear()
        seen.add(sig)

    def _evict_cache_overflow(self) -> None:
        cap = _flags.get_flags("executor_cache_capacity")
        while cap and len(self._cache) > cap:
            oldest = next(iter(self._cache))  # insertion order = FIFO
            del self._cache[oldest]
            if _obs_trace.flags_on():
                _em().evictions.inc()

    def _record_step(self, entry, key, cache_hit: bool, lowering_ms: float,
                     compile_ms: float, feed_vals, fetches,
                     t_run0_ns: int, plan, donated_state,
                     program: Optional[Program] = None) -> None:
        t_now = time.perf_counter_ns()
        wall_ms = (t_now - t_run0_ns) / 1e6
        meta = entry.meta
        if meta is None:
            # once per executable: the cache key pins every feed/fetch
            # shape, so program_key and the transfer byte totals are
            # constants — re-deriving them per step (nested-tuple hash +
            # jax metadata property chains) dominated the cached-run
            # telemetry cost
            nbytes = _obs_step.approx_nbytes
            meta = (self._program_key(key),
                    sum(nbytes(v) for v in feed_vals),
                    sum(nbytes(v) for v in fetches))
            entry.meta = meta
        pk, feed_bytes, fetch_bytes = meta
        ss = _obs_step.StepStats(
            program_key=pk,
            cache_hit=cache_hit,
            lowering_ms=round(lowering_ms, 3),
            compile_ms=round(compile_ms, 3),
            feed_bytes=feed_bytes,
            fetch_bytes=fetch_bytes,
            wall_ms=round(wall_ms, 3),
            extras=self._step_stat_extras(program, plan, fetches))
        _obs_step.record(ss)
        m = _em()
        m.steps.inc()
        m.wall.observe(wall_ms)
        m.feed_bytes.inc(ss.feed_bytes)
        m.fetch_bytes.inc(ss.fetch_bytes)
        if _obs_trace.enabled():
            _obs_trace.emit("executor::run", t_run0_ns, t_now)
        self._post_step_telemetry(ss, plan, donated_state)

    def _post_step_telemetry(self, ss, plan, donated_state) -> None:
        """Hook for subclasses (ParallelExecutor adds mesh-level stats)."""

    @staticmethod
    def _step_stat_extras(program, plan, fetches):
        """Model-health scalars for the StepStats record: any fetch
        registered in ``Program.step_stat_vars`` (switch_moe wires its
        aux loss / dropped-token fraction there) lands in the record's
        ``extras`` and a same-named gauge — so EP health shows per step
        on ``/stepz`` and ``/metrics``.  Scalar-only, and only when the
        var is actually fetched; the float() forces a (tiny) device
        readback, paid solely under FLAGS_runtime_stats.  For
        ``run_steps`` the stacked [K] fetch reports the LAST step."""
        reg = getattr(program, "step_stat_vars", None)
        if not reg:
            return None
        out = {}
        for name, val in zip(plan.fetch_names, fetches):
            key = reg.get(name)
            if key is None:
                continue
            try:
                arr = np.asarray(val)
                if arr.size < 1:
                    continue
                v = float(arr.reshape(-1)[-1])
            except Exception:
                continue
            out[key] = v
            _obs_stats.gauge(key).set(v)
        return out or None

    @staticmethod
    def _perf_wall_ms(t_run0, cache_hit, lowering_ms, compile_ms,
                      entry) -> float:
        """Wall time for the perf-record roofline: the full run wall
        minus the one-time build costs a COLD step paid (lowering,
        in-dispatch first-call XLA compile, AOT compile) — otherwise a
        1–2-step run's achieved FLOP/s is dominated by the compile,
        understating the roofline by orders of magnitude."""
        wall = (time.perf_counter_ns() - t_run0) / 1e6
        if not cache_hit:
            # compile_ms REPORTS entry.aot_ms for AOT entries (see the
            # dispatch block) — max(), not sum, or it subtracts twice
            wall -= (lowering_ms or 0.0) + max(compile_ms or 0.0,
                                               entry.aot_ms or 0.0)
        return max(wall, 0.0)

    @staticmethod
    def _program_key(key) -> str:
        """Short telemetry id of an executable-cache key (the StepStats
        ``program_key`` and the /profilez record key share it)."""
        return f"{key[0]:x}v{key[1]}:{abs(hash(key)) % (16 ** 8):08x}"

    # -- numerics sentinel (FLAGS_numerics_check) --------------------------
    @staticmethod
    def _copy_state_val(v):
        """Device copy of one donated-state value (fatal-mode pre-step
        snapshot — the original buffer is consumed by donation)."""
        if isinstance(v, SelectedRows):
            return SelectedRows(jnp.asarray(v.rows).copy(),
                                jnp.asarray(v.values).copy(), v.height)
        cp = getattr(v, "copy", None)
        return cp() if callable(cp) else v

    def _numerics_guard(self, mode: str, state_backup, fetch_names,
                        fetches, plan, new_state, scope) -> None:
        """Run the sentinel BEFORE the state writes (run and run_steps
        share this): a fatal verdict keeps the poisoned post-optimizer
        state out of the scope — the pre-step copy goes back in, since
        donation consumed the live buffers."""
        if not mode:
            return
        try:
            self._check_numerics(fetch_names, fetches,
                                 plan.persist_writes, new_state, mode)
        except FloatingPointError:
            if state_backup is not None:
                for name, val in zip(plan.donated_reads, state_backup):
                    scope.set_var(name, val)
            raise

    def _check_numerics(self, fetch_names, fetches, persist_names,
                        new_state, mode: str) -> None:
        """Post-dispatch NaN/Inf sentinel over every float fetch and
        updated persistable var.  Device-side ``jnp.isnan``/``jnp.isinf``
        reductions, ONE batched readback of the tiny flags — never a
        full-tensor host scan (that is FLAGS_check_nan_inf's job).

        Runs BEFORE the state writes: at ``mode='fatal'`` a poisoned
        step dumps a flight record and raises while the scope still
        holds the pre-step parameters — the optimizer never applies the
        poison.  ``mode='warn'`` names the variables, bumps
        ``numerics.{nan,inf}`` and notes the flight ring, then lets the
        step land (the counters make a slow-motion blow-up visible
        without killing a run that might recover)."""
        names: List[str] = []
        flags = []
        seen = set()
        for name, val in list(zip(fetch_names, fetches)) + \
                list(zip(persist_names, new_state)):
            if name in seen:  # a fetched persistable counts once
                continue
            v = val.values if isinstance(val, SelectedRows) else val
            dt = getattr(v, "dtype", None)
            if dt is None or not jnp.issubdtype(dt, jnp.floating):
                continue
            seen.add(name)
            names.append(name)
            flags.append(jnp.any(jnp.isnan(v)))
            flags.append(jnp.any(jnp.isinf(v)))
        m = _nm()
        m.checked.inc()
        if not names:
            return
        host = jax.device_get(flags)  # one batched tiny-flag readback
        nan_vars = [n for n, f in zip(names, host[0::2]) if bool(f)]
        inf_vars = [n for n, f in zip(names, host[1::2]) if bool(f)]
        if not nan_vars and not inf_vars:
            return
        m.nan.inc(len(nan_vars))
        m.inf.inc(len(inf_vars))
        from ..observability import flight as _flight
        _flight.note("numerics_sentinel", mode=mode,
                     nan_vars=nan_vars[:16], inf_vars=inf_vars[:16])
        msg = (f"numerics sentinel (FLAGS_numerics_check={mode}): "
               f"NaN in {nan_vars or '[]'}, Inf in {inf_vars or '[]'}")
        if mode == "fatal":
            # full post-mortem BEFORE the raise (the step's spans and
            # the poisoned-step note are still in the rings)
            _flight.dump("numerics_fatal")
            raise FloatingPointError(
                msg + " — step NOT applied (the pre-step state snapshot "
                "is restored into the scope)")
        import sys as _sys
        print("[numerics] " + msg, file=_sys.stderr, flush=True)

    # -- placement hooks (overridden by ParallelExecutor) ------------------
    def _prepare_program(self, program: Program, feed: Dict) -> Program:
        return program

    def _mesh(self):
        return None

    def _put_feed(self, arr):
        return arr

    def _put_rng(self, rng):
        return rng

    def _put_state(self, name: str, val):
        return val

    def _note_state_write(self, name: str) -> None:
        pass

    # -- helpers -----------------------------------------------------------
    def _state_val(self, scope: Scope, block, name: str):
        val = scope.find_var(name)
        if val is None:
            raise RuntimeError(
                f"variable {name!r} is not initialized in the scope — run the "
                f"startup program first (fluid.default_startup_program())"
            )
        val = _as_device_array(val, block.var_or_none(name))
        placed = self._put_state(name, val)
        if placed is not val:
            scope.set_var(name, placed)
        return placed

    def close(self) -> None:
        self._cache.clear()
