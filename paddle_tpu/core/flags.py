"""Framework flags: the gflags + env-var bootstrap analogue.

Reference: gflags ``DEFINE_*`` at point-of-use (``executor.cc:27``,
``operator.cc:31`` FLAGS_check_nan_inf, ``scope.cc:23-34``,
``memory/malloc.cc:25``) re-exported to Python through
``fluid.__init__.__bootstrap__`` collecting ``--tryfromenv`` names
(``python/paddle/fluid/__init__.py:112-132``, ``pybind.cc:560``).

Here flags live in one registry; values bootstrap from the environment
(``FLAGS_<name>=...`` variables, the reference's spelling) at import and
can be read/written at runtime with ``get_flags``/``set_flags`` (the
paddle 1.x public API).  Consumers poll at use-sites, e.g. the executor's
NaN/Inf guard.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Union

_DEFS: Dict[str, dict] = {}
_VALUES: Dict[str, Any] = {}


def _parse(value: str, default):
    if isinstance(default, bool):
        return value.strip().lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(value)
    if isinstance(default, float):
        return float(value)
    return value


def define_flag(name: str, default, help_str: str = "") -> None:
    _DEFS[name] = {"default": default, "help": help_str}
    env = os.environ.get("FLAGS_" + name)
    _VALUES[name] = _parse(env, default) if env is not None else default


def get_flags(names: Union[str, Iterable[str]]):
    """fluid.get_flags parity: str → value; list → {name: value}."""
    if isinstance(names, str):
        if names.startswith("FLAGS_"):
            names = names[len("FLAGS_"):]
        if names not in _DEFS:
            raise KeyError(f"unknown flag {names!r}")
        return _VALUES[names]
    return {n: get_flags(n) for n in names}


def set_flags(flags: Dict[str, Any]) -> None:
    """fluid.set_flags parity: {\"FLAGS_x\": v} or {\"x\": v}."""
    for name, value in flags.items():
        if name.startswith("FLAGS_"):
            name = name[len("FLAGS_"):]
        if name not in _DEFS:
            raise KeyError(f"unknown flag {name!r}")
        default = _DEFS[name]["default"]
        _VALUES[name] = (_parse(value, default) if isinstance(value, str)
                         else type(default)(value) if default is not None
                         else value)


def all_flags() -> Dict[str, Any]:
    return dict(_VALUES)


# ---------------------------------------------------------------------------
# flag definitions (the reference's DEFINE_* sites, TPU-relevant subset)
# ---------------------------------------------------------------------------

define_flag("check_nan_inf", False,
            "after each executor run, scan fetches and updated state for "
            "NaN/Inf and raise (operator.cc:31 post-kernel check, moved to "
            "post-block granularity under whole-block XLA compilation)")
define_flag("benchmark", False,
            "log per-run wall time from the executor (executor.cc:399)")
define_flag("eager_delete_tensor_gb", -1.0,
            "accepted for API parity; device memory lifetime is owned by "
            "XLA buffer assignment")
define_flag("fraction_of_gpu_memory_to_use", 0.92,
            "accepted for API parity; HBM is managed by the XLA runtime")
define_flag("cpu_deterministic", False,
            "accepted for parity; lowerings are deterministic by "
            "construction (threaded PRNG state)")
define_flag("rpc_deadline", 120.0,
            "pserver transport connect deadline in seconds "
            "(distributed/transport.py)")
define_flag("rpc_transport", "native",
            "pserver byte-transport backend: 'native' (C framed-TCP in "
            "native/paddle_tpu_native.cc, the reference's C++ gRPC layer "
            "role) or 'python' (stdlib sockets fallback)")
define_flag("paddle_num_threads", 1,
            "accepted for parity; host threading is owned by XLA")
define_flag("sparse_dense_update_max_elems", 32_000_000,
            "lazy sparse optimizers (adam/momentum/adagrad) use the "
            "masked-dense update (2 scatters + full-table elementwise; "
            "4x faster on TPU for medium tables) when the table has at "
            "most this many elements; larger tables fall back to the "
            "sorted merge_rows path whose cost is independent of height. "
            "Read at trace time: set it before the first Executor.run of "
            "a program (cached executables keep the path they compiled)")
define_flag("sparse_fused_kernel", False,
            "lower the sparse-embedding hot path through the fused Pallas "
            "kernels (paddle_tpu/kernels/sparse.py): lookup_table ops "
            "sharing one id batch gather through ONE multi-table launch, "
            "and the lazy sparse optimizers (adam/momentum/adagrad) "
            "replace their per-table gather/scatter/moment-sweep chain "
            "with ONE sorted-segment row-wise update launch that touches "
            "only the looked-up rows (in-place via input_output_aliases). "
            "Off-TPU the kernels run in Pallas interpret mode.  Each "
            "stage independently falls back to the masked-dense / sorted "
            "merge_rows paths on any build fault (counted in "
            "sparse_fused.*_fallbacks — a fault can never fail a step). "
            "Read at trace time like sparse_dense_update_max_elems; off "
            "(default) keeps the update path byte-identical")
define_flag("runtime_stats", True,
            "collect runtime telemetry (paddle_tpu/observability): "
            "executor compile-cache and StepStats records, lowering/RPC/"
            "collective counters and latency histograms, and runtime:: "
            "profiler spans.  Collection is cheap (dict increments); set "
            "FLAGS_runtime_stats=0 to disable every hook for true-zero "
            "overhead")
define_flag("executor_cache_capacity", 256,
            "max cached compiled executables per Executor; exceeding it "
            "evicts the oldest entry (counted in executor.cache_evictions "
            "— an eviction storm means shape churn is defeating the "
            "compile cache).  0 = unbounded (the pre-telemetry behavior)")
define_flag("rpc_conns_per_endpoint", 2,
            "striped persistent connections per pserver endpoint "
            "(distributed/transport.py RPCClient): concurrent requests "
            "to one pserver pipeline across stripes instead of "
            "serializing on a single connection lock (the reference's "
            "multi-channel grpc_client).  Latched per endpoint at first "
            "use; 1 restores the single-connection behavior")
define_flag("rpc_vectored_io", True,
            "send multi-buffer RPC frames scatter-gather "
            "(socket.sendmsg / native sendmsg-iovec) straight from the "
            "ndarray views — no Python-level concat copy of tensor "
            "bytes.  0 falls back to joining buffers before send")
define_flag("rpc_stripe_chunk_bytes", 8 << 20,
            "SEND_VARS batches whose tensor payload exceeds this many "
            "bytes are split (at var granularity) into per-stripe "
            "sub-batches sent concurrently across the striped "
            "connections; 0 disables splitting (always one frame per "
            "endpoint per round)")
define_flag("rpc_batch_vars", True,
            "group send/recv host-op variables by endpoint into batched "
            "SEND_VARS/GET_VARS frames (one RPC per pserver per round "
            "instead of one per variable).  0 restores per-var "
            "SEND_VAR/GET_VAR wire behavior (e.g. against a peer that "
            "predates the batched frames)")
define_flag("rpc_server_profile_period", 0,
            "pserver self-profiling: log request-rate stats every N "
            "handled RPCs (reference FLAGS_rpc_server_profile_period, "
            "python/paddle/fluid/__init__.py:121); 0 disables")
define_flag("debug_server_port", 0,
            "port for the in-process observability debug HTTP server "
            "(observability/debug_server.py: /metrics /healthz /statusz "
            "/stepz).  0 (default) disables it entirely — no socket is "
            "opened and no thread is started")
define_flag("debug_server_host", "127.0.0.1",
            "bind address for the debug HTTP server; loopback by default "
            "(expose beyond the host deliberately, e.g. 0.0.0.0 behind a "
            "pod-network firewall)")
define_flag("health_suspect_misses", 1.0,
            "missed heartbeat-lease terms (units of each worker's own "
            "TTL) after which the health registry marks a worker SUSPECT "
            "(observability/health.py)")
define_flag("health_dead_misses", 3.0,
            "missed lease terms after which a worker is DEAD: its health "
            "gauge flips, and a TaskMaster consulting the registry "
            "requeues the worker's task leases immediately instead of "
            "waiting out the lease timeout")
define_flag("trace_sample_rate", 0.0,
            "distributed-tracing head-sampling rate in [0,1] "
            "(observability/trace.py): each top-level Executor.run rolls "
            "once and, when sampled, opens a step-root span whose context "
            "propagates over the RPC wire so trainer and pserver spans "
            "stitch under one trace id.  0 (default) disables tracing "
            "entirely — no span-ring writes and zero extra wire bytes")
define_flag("trace_ring_spans", 4096,
            "capacity of the in-memory completed-span ring each process "
            "keeps for TRACE_PULL / the /tracez debug page; oldest spans "
            "fall off — bound memory, never block the hot path")
define_flag("flight_record_dir", "",
            "directory for crash flight-recorder dumps "
            "(observability/flight.py): when set, unhandled exceptions, "
            "SIGTERM and Heartbeat.stop(bye=False)-style dirty exits "
            "write a JSON post-mortem (recent + in-flight spans, log "
            "events, step-stats tail) there.  Empty (default) disarms "
            "the recorder — no hooks installed")
define_flag("compile_cache_dir", "",
            "directory for the persistent cross-process compilation "
            "cache (core/compile_cache.py): AOT-compiled executables "
            "are serialized into content-addressed entry files keyed "
            "by a canonical program fingerprint (tier A), and "
            "jax_compilation_cache_dir is pointed at <dir>/xla for "
            "XLA-level reuse of anything tier A cannot serialize "
            "(tier B).  A warm process hydrates its executable cache "
            "from disk instead of recompiling (elastic restarts, "
            "bench worker respawns).  Empty (default) disables the "
            "cache entirely — no disk I/O, no new threads")
define_flag("compile_cache_max_bytes", 2 << 30,
            "LRU size cap for the persistent compile-cache directory: "
            "after each store, oldest-used entry files (mtime, touched "
            "on every hit) are pruned until the tier-A entries fit; "
            "counted in compile_cache.evictions.  0 = unbounded")
define_flag("fault_inject", "",
            "chaos-suite fault injection rules (distributed/faults.py): "
            "semicolon-separated 'kind[:target][:k=v,...]' rules — "
            "drop_conn (sever a matching request's connection), delay "
            "(sleep ms before handling), kill_after (os._exit(137) when "
            "the matching counter reaches n), refuse_accept (slam new "
            "connections).  Targets are RPC message names or loop "
            "events (apply_round, lease_grant).  Empty (default) "
            "disables every injection point — the transport is "
            "byte-identical to the fault-free build.  Runtime injection "
            "against a live fleet goes through the debug server's "
            "/chaosz endpoint (tools/chaos.py)")
define_flag("perf_attribution", False,
            "harvest XLA cost_analysis() (flops, bytes accessed) and "
            "memory_analysis() (argument/output/temp bytes) on every "
            "executable build (fresh compile, AOT warm start, or "
            "compile-cache hydrate) into per-executable perf records "
            "(observability/perf.py), combine them with measured step "
            "wall time into roofline positions vs the platform peak "
            "table (platform.PLATFORM_PEAKS), and sample live "
            "device-memory gauges per step.  Served on /profilez and "
            "/memz.  Forces ahead-of-time lower().compile() (same "
            "executable, eager compile) so the compiled handle is "
            "analyzable; off (default) keeps the lazy-jit path "
            "byte-identical")
define_flag("run_log_dir", "",
            "directory for the append-only run-scalar JSONL log "
            "(observability/runlog.py): each Executor.run/run_steps "
            "appends one record per step — step index, wall clock, "
            "every scalar fetch by name (loss, ...), grad global norm "
            "over fetched @GRAD vars, step_ms, samples/sec — with "
            "atomic size-capped rotation.  tools/runlog_report.py "
            "renders/compares logs.  Empty (default): zero new I/O")
define_flag("run_log_max_mb", 64,
            "rotation cap for one run-scalar log file in MB: when an "
            "append would exceed it, the file atomically rotates into "
            "a generation chain (<name>.1 newest .. .8 oldest, older "
            "ages out) and a fresh file starts.  0 = never rotate")
define_flag("numerics_check", "",
            "post-step NaN/Inf sentinel (observability plane): after "
            "each executor dispatch, device-side jnp.isfinite "
            "reductions over every float fetch and updated persistable "
            "var are read back as tiny flags (never a full-tensor host "
            "scan like FLAGS_check_nan_inf).  Offending variables are "
            "NAMED, numerics.{nan,inf} counters increment, and the "
            "flight recorder gets a note.  'warn' (or any truthy "
            "value) logs and continues; 'fatal' dumps a full flight "
            "record and raises BEFORE the poisoned state is applied "
            "to the scope (fatal keeps a pre-step device copy of the "
            "donated state so the scope is restored intact — one "
            "state copy per step is its price).  Either mode's flag "
            "readback waits on the dispatch, so with async fetches "
            "(sync=False) the sentinel serializes each step — the "
            "cost of a verdict before the next apply.  Empty "
            "(default) disables the pass")
define_flag("serving_buckets", "1,2,4,8,16,32",
            "batch-size bucket ladder for the model-serving plane "
            "(paddle_tpu/serving): concurrent requests coalesce into "
            "padded batches snapped to the smallest bucket that fits, "
            "so a handful of warmed executables cover all traffic and "
            "no dispatch ever recompiles.  Per-model override via "
            "DynamicBatcher(buckets=...) / ModelManager.load(buckets=...)")
define_flag("serving_max_queue_delay_ms", 5.0,
            "continuous-batching dispatch SLO: a queued request waits at "
            "most this long for more requests to coalesce before its "
            "(possibly partial, padded) batch dispatches.  Lower = "
            "latency-biased, higher = occupancy-biased")
define_flag("serving_max_queue_rows", 1024,
            "admission-control bound on a model's request queue in ROWS "
            "(sum of queued request batch sizes): past it, new requests "
            "are shed immediately with a typed Overloaded reply instead "
            "of queueing into timeout (counted in serving.<model>.shed)")
define_flag("serving_queue_delay_slo_ms", 0.0,
            "optional queue-delay SLO for admission control: when "
            "backlog x observed per-batch service time says a new "
            "request cannot be answered within this many ms, it is shed "
            "with a typed Overloaded reply.  0 (default) disables the "
            "estimate — only the serving_max_queue_rows bound sheds")
define_flag("decode_block_tokens", 16,
            "paged-KV-cache block size in TOKENS for the autoregressive "
            "decode plane (paddle_tpu/decode): per-request key/value "
            "state lives in fixed-size device blocks drawn from a "
            "preallocated pool, so admission/eviction moves block-table "
            "ENTRIES, never compiled shapes.  Latched when a "
            "DecodeEngine is built")
define_flag("decode_max_slots", 8,
            "decode-batch width of the continuous-batching decode step "
            "(paddle_tpu/decode/engine.py): requests join and leave a "
            "running batch of this many slots at token granularity; the "
            "slot count is a compiled shape, so it is fixed per engine "
            "(inactive slots ride along masked into the reserved trash "
            "block)")
define_flag("decode_prefill_buckets", "16,32,64,128",
            "prompt-length bucket ladder for the decode plane's split "
            "prefill dispatch (the serving_buckets discipline applied "
            "to the TIME axis): a joining prompt pads to the smallest "
            "bucket that fits, so a handful of prefill executables "
            "cover all prompt lengths and a long new prompt never "
            "recompiles (or stalls) the running decode step")
define_flag("decode_max_queue", 64,
            "admission-control bound on a decode engine's pending "
            "request queue: past it, new generation requests are shed "
            "with the serving plane's typed Overloaded reply (counted "
            "in decode.shed) instead of queueing into timeout")
define_flag("decode_prefix_cache", False,
            "content-addressed prefix caching for the decode plane "
            "(paddle_tpu/decode/cache.py PrefixCache): full prompt "
            "blocks are keyed by a rolling hash of (model, token ids "
            "to the block boundary); admission walks the new prompt's "
            "block-aligned prefix against the cache and adopts hits "
            "as refcounted copy-on-write references, so a shared "
            "system prompt prefills ONCE and later requests prefill "
            "only their suffix.  Zero-refcount cached blocks park in "
            "an LRU and are reclaimed under pool pressure.  Latched "
            "when a DecodeEngine is built; off (default): legacy "
            "full-reservation behavior, byte-identical")
define_flag("decode_overcommit", False,
            "lazy block reservation + preemption for the decode plane "
            "(paddle_tpu/decode/engine.py): admission reserves only "
            "ceil((P+1)/block_tokens) blocks instead of the full "
            "prompt+max_new worst case and grows one block per decode "
            "step; when growth cannot allocate, the newest running "
            "stream is preempted (blocks freed, generated tokens kept "
            "host-side) and re-admitted head-of-line via suffix "
            "re-prefill — token-for-token identical to an "
            "uninterrupted run (counter-hash sampling is positional). "
            "Latched when a DecodeEngine is built; off (default): "
            "full reservation at admission, byte-identical")
define_flag("decode_kv_dtype", "float32",
            "storage dtype of the paged decode KV cache "
            "(paddle_tpu/decode/cache.py PagedKVCache): 'int8' stores "
            "key/value blocks quantized to int8 with per-block-per-head "
            "abs-max scales in a parallel f32 scale pool, quartering the "
            "KV bytes per token (~0.53x incl. scales) so overcommit "
            "admission fits ~2x the resident sequences per HBM byte; the "
            "paged decode-attention kernel dequantizes blocks in VMEM "
            "(counted XLA dequantize-gather fallback on any build "
            "fault).  Prefix-cache hashing, COW forking, preemption/"
            "re-prefill and the block-pool accounting move block IDS "
            "only, so they operate on quantized blocks unchanged — the "
            "scale pool rides the same block axis (COW copies the scale "
            "row with the block).  Latched when a DecodeEngine is "
            "built; 'float32' (default) keeps the cache layout, state "
            "threading and metric surface byte-identical")
define_flag("int8_inference", False,
            "serving-plane kill-switch default for int8 inference: when "
            "on, create_predictor appends the 'quantize_int8' "
            "calibration pass (inference/passes.py) to every "
            "AnalysisConfig as if enable_int8() had been called — "
            "per-out-channel weight scales derived from QAT fake-quant "
            "stats when present (else post-training abs-max over the "
            "weight scope), activations quantized dynamically (or with "
            "the QAT moving-average scale), and calibrated mul/fused_fc "
            "ops lowered through the fused-dequant int8 Pallas matmul "
            "(kernels/quant.py; int8xint8->int32 accumulation, dequant+"
            "bias+activation epilogue).  Non-TPU backends run the "
            "kernel in interpret mode; odd shapes or build faults take "
            "the counted XLA dequantized path (quant.* counters — a "
            "fault can never fail a dispatch).  Off (default): only "
            "configs that explicitly call enable_int8() quantize; "
            "programs without the pass lower byte-identically")
define_flag("phase_attribution", False,
            "per-request latency-phase attribution for the serving and "
            "decode planes (observability/phase.py): each request "
            "stamps monotonic phase timestamps through its lifecycle "
            "(queue -> assemble -> dispatch -> device -> reply; decode "
            "adds queue -> prefill/TTFT -> per-token), recorded into "
            "per-phase histograms plus a bounded per-request sample "
            "ring with slowest-request exemplars linked to their trace "
            "ids — so a p99 regression NAMES its phase on /servingz / "
            "/decodez.  Also arms the decode TTFT/TBT histograms and "
            "goodput counters.  Host-side time.monotonic() stamps only "
            "— zero extra device syncs.  Off (default): no stamps, no "
            "new metric series")
define_flag("capacity_attribution", False,
            "phase-level utilization and capacity modeling for the "
            "serving and decode planes (observability/capacity.py): "
            "each pipeline component (batcher assemble/dispatch, "
            "device materialization, reply slicing; decode prefill and "
            "step) accounts its busy time into a bounded sliding "
            "window, turned into *.util.* gauges, operational-law "
            "service-time fits per shape bucket (U = X*S) and a "
            "predicted_max_qps + headroom_frac estimate naming the "
            "binding phase — rendered on /capacityz, merged over "
            "STATS_PULL, and riding the serving/decode lease-data "
            "payloads into the elastic controller as an informational "
            "capacity input.  Host-side clock reads only — no extra "
            "device syncs.  Off (default): no accounting, no new "
            "metric series, heartbeats byte-identical")
define_flag("tenant_accounting", False,
            "per-tenant usage metering for the serving and decode "
            "planes (observability/tenant.py): requests carrying an "
            "optional wire-level tenant id are accounted per tenant "
            "(requests/rows/prefill-tokens/decode-tokens/cancellations "
            "plus device-ms attributed proportionally from the shared "
            "batch's device wall) into a space-saving top-K heavy-"
            "hitter sketch with an 'other' rollup, rendered on "
            "/tenantz and merged over STATS_PULL.  Tenant ids are "
            "CLIENT-SUPPLIED and unauthenticated — attribution, not "
            "isolation.  Off (default): ids are ignored, no sketch, "
            "no new metric series")
define_flag("tenant_top_k", 20,
            "cardinality bound of the per-tenant accounting sketch "
            "(observability/tenant.py): at most this many tenants are "
            "tracked exactly; past it the space-saving sketch evicts "
            "the smallest tenant into the 'other' rollup, so an "
            "adversarial id stream cannot grow memory or the /tenantz "
            "payload")
define_flag("metrics_history_interval_s", 0.0,
            "sampling period for the in-process metric history rings "
            "(observability/history.py): every counter/gauge in the "
            "default registry retains a bounded, resolution-doubling "
            "downsampled time series, queryable as /varz?window=<s> "
            "and carried through the STATS_PULL fleet merge (aligned "
            "by sample AGE, so skewed worker wall clocks cannot "
            "misalign the fleet view).  0 (default) disables the "
            "sampler thread and the rings entirely")
define_flag("metrics_history_points", 512,
            "capacity of one metric's history ring in POINTS: past it "
            "the ring halves its resolution (adjacent samples merge "
            "into their mean) instead of growing — memory stays "
            "bounded while the window keeps extending")
define_flag("slo_rules", "",
            "declarative SLO watchdog rules (observability/slo.py), "
            "semicolon-separated "
            "'name=metric:stat(op)threshold:for=sustain_s' — e.g. "
            "'ttft=decode.lm.ttft_ms:p99>250:for=5'.  stat is p50/p90/"
            "p99/p999 (histograms), rate (counter per-second), or "
            "value (gauges).  Rules are evaluated in-process; a "
            "condition sustained for its window BREACHES (slo.* "
            "counters, flight-recorder note, /sloz, and an 'slo' "
            "health dimension in the registry heartbeat payload that "
            "ElasticController/supervisor consume as a damped, "
            "HOLD-safe decision input).  Empty (default): no watchdog "
            "thread, no heartbeat bytes added")
define_flag("slo_eval_interval_s", 1.0,
            "SLO watchdog evaluation period in seconds (only read when "
            "FLAGS_slo_rules is non-empty)")
define_flag("canary_probe", False,
            "golden canary prober for the serving and decode planes "
            "(observability/canary.py): a background thread "
            "periodically replays a small golden set (recorded "
            "input -> expected-output pairs, captured with "
            "'tools/golden.py record' against a trusted build) through "
            "the REAL submit path of every registered replica target, "
            "compares replies against the goldens with per-model rtol, "
            "and maintains per-replica pass/fail streaks (canary.* "
            "counters, /canaryz, a 'canary' health dimension on every "
            "registry heartbeat, and a STATS_PULL rider).  Probes are "
            "tenant-tagged '__canary__' so per-tenant metering "
            "(FLAGS_tenant_accounting) excludes them from user "
            "accounting.  A canary pass is a REGRESSION check against "
            "a recorded build, not a proof of correctness.  Off "
            "(default): no thread, no series, heartbeats and "
            "STATS_PULL byte-identical")
define_flag("canary_interval_s", 5.0,
            "golden canary probe period in seconds (only read when "
            "FLAGS_canary_probe is on): each cycle replays the full "
            "golden set through every registered target once")
define_flag("canary_golden_path", "",
            "path of the golden-set JSON consumed by the canary prober "
            "(written by 'tools/golden.py record'); empty with "
            "FLAGS_canary_probe on means the prober idles armed with "
            "zero goldens (streaks stay empty) until a set is loaded")
define_flag("canary_rtol", 1e-5,
            "default relative tolerance for golden-vs-reply numeric "
            "comparison in the canary prober; a golden set may carry a "
            "tighter/looser per-model rtol which wins over this flag")
define_flag("canary_fail_streak", 3,
            "consecutive canary-probe failures on one replica target "
            "before its heartbeat 'canary' health dimension flips to "
            "'fail' (the supervisor additionally applies its own "
            "hysteresis before quarantining, so a single flake can "
            "never drain a replica)")
define_flag("divergence_check", False,
            "cross-replica divergence sentinel "
            "(observability/audit.py): serving replicas fold a content "
            "digest of each reply batch (decode servers a per-stream "
            "token-id rolling hash) into a bounded audit ring that "
            "rides their registry lease data; the supervisor groups "
            "digests by (model, version, request-hash) across replicas "
            "and NAMES a minority replica whose digest disagrees with "
            "the majority (divergence.* counters, flight-recorder "
            "note, /canaryz audit section).  Training: "
            "ParallelExecutor folds a periodic u64 parameter checksum "
            "per DP replica (every FLAGS_divergence_param_steps steps) "
            "so state divergence is caught within K steps.  Off "
            "(default): no digests, no series, lease payloads "
            "byte-identical")
define_flag("divergence_param_steps", 50,
            "period in optimizer steps of the cross-DP-replica "
            "parameter checksum (only read when FLAGS_divergence_check "
            "is on): every K-th step each replica folds a u64 checksum "
            "of its persistable parameters into the audit plane")
define_flag("memory_attribution", False,
            "memory anatomy (observability/memory.py): every "
            "byte-holding subsystem (decode KV block pool, executor "
            "executable cache + persistent scope, compile-cache disk "
            "store, serving batch staging, checkpoint snapshot "
            "buffers) registers a pool on the process MemoryLedger; "
            "the ledger reconciles pool sums against live PJRT "
            "bytes_in_use per device into an explicit "
            "unattributed_bytes residual, keeps a bounded allocation "
            "event ring (alloc/free/park/reclaim/preempt/evict), runs "
            "a leak sentinel promoting failed refcount audits to a "
            "'memory' health dimension on registry heartbeats, and "
            "dumps OOM forensics (full ledger + top holders + event "
            "tail) on any RESOURCE_EXHAUSTED escaping a dispatch.  "
            "Surfaces: /allocz (+?text=1), /memz ledger section, "
            "STATS_PULL rider with fleet merge, compact lease-data "
            "rider for ElasticController.memory_headroom().  Off "
            "(default): no pools, no series, no thread, heartbeat / "
            "lease / STATS_PULL payloads byte-identical")
define_flag("memory_audit_interval_s", 5.0,
            "period of the memory leak sentinel's refcount-invariant "
            "audit sweep (only read when FLAGS_memory_attribution is "
            "on); <= 0 disables the sentinel thread while keeping "
            "ledger attribution available for pull-based audits")
define_flag("memory_event_ring", 1024,
            "bounded capacity of the allocation event ring "
            "(alloc/free/park/reclaim/preempt/evict records with "
            "sizes and pool ids; oldest events are overwritten) — "
            "only allocated when FLAGS_memory_attribution is on")
define_flag("pserver_registry", "",
            "host:port of the pserver discovery registry "
            "(distributed/registry.py — the etcd analogue): pservers "
            "register their logical endpoint with a TTL lease, trainers "
            "re-resolve on connection failure; empty = static endpoints")
