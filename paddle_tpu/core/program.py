"""Program IR: Variable / Operator / Block / Program.

This is the framework's serialized-program contract, the TPU-native
re-design of the reference's ProgramDesc stack
(``paddle/fluid/framework/framework.proto:42-190`` and
``python/paddle/fluid/framework.py:204,494,920,1404``).  The essential idea
is preserved: Python layer calls append typed OpDescs to nested BlockDescs,
autodiff and transpilers rewrite the program as more graph, and a runtime
executes it.  What changes for TPU: the runtime does NOT interpret ops
one-by-one against device memory — whole blocks are lowered to a single pure
JAX function and JIT-compiled by XLA (see ``core/lowering.py``), so the IR
here carries exactly what that lowering needs (static shapes, dtypes,
persistability, stop-gradient sets, sub-block references for control flow).

Serialization is JSON (``Program.to_dict``/``from_dict``) rather than
protobuf; the structure mirrors the reference proto field-for-concept.
"""
from __future__ import annotations

import contextlib
import copy
import itertools
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import unique_name
from .types import VarType, normalize_dtype

GRAD_SUFFIX = "@GRAD"
TEMP_VAR_PREFIX = "_generated_var"
EMPTY_VAR = "@EMPTY@"  # positional placeholder for absent optional args

# Op-role attribute: lets program rewrites (backward, transpilers, parallel
# lowering) classify ops without pattern matching (reference:
# paddle/fluid/framework/op_proto_maker.cc, op_role/op_role_var attrs).
OP_ROLE_ATTR = "op_role"
OP_ROLE_VAR_ATTR = "op_role_var"


class OpRole:
    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 3
    Dist = 4
    LRSched = 16
    Loss = 256


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


class Variable:
    """A typed slot in a Block (reference VarDesc, framework.proto:164 +
    python Variable, framework.py:204).

    Shapes use -1 for the batch dimension only; everything else is static so
    blocks lower to fixed-shape XLA programs (the reference's
    runtime-InferShape model does not translate to XLA).
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        type: VarType = VarType.DENSE_TENSOR,
        persistable: bool = False,
        stop_gradient: bool = False,
        lod_level: int = 0,
        is_parameter: bool = False,
        trainable: bool = True,
        initializer: Optional[dict] = None,
        regularizer=None,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = normalize_dtype(dtype) if dtype is not None else None
        self.type = VarType(type)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.lod_level = lod_level
        self.is_parameter = is_parameter
        self.trainable = trainable
        self.initializer = initializer
        self.regularizer = regularizer

    # -- convenience -------------------------------------------------------
    @property
    def ndim(self) -> int:
        return len(self.shape) if self.shape is not None else 0

    def astype_shape(self, batch: int) -> tuple:
        return tuple(batch if s == -1 else s for s in self.shape)

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={self.shape}, dtype={self.dtype},"
            f" persistable={self.persistable})"
        )

    # grad var helpers
    @property
    def grad_name(self) -> str:
        return grad_var_name(self.name)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "type": int(self.type),
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "lod_level": self.lod_level,
            "is_parameter": self.is_parameter,
            "trainable": self.trainable,
            "initializer": self.initializer,
        }

    @staticmethod
    def from_dict(block: "Block", d: dict) -> "Variable":
        return Variable(
            block,
            d["name"],
            shape=d.get("shape"),
            dtype=d.get("dtype") or "float32",
            type=VarType(d.get("type", 0)),
            persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            lod_level=d.get("lod_level", 0),
            is_parameter=d.get("is_parameter", False),
            trainable=d.get("trainable", True),
            initializer=d.get("initializer"),
        )


class _NameScope:
    """Hierarchical debug-name prefixes with sibling dedup (reference
    framework.py:53 NameScope — second ``with name_scope("fc")`` at the
    same level becomes ``fc_1``)."""

    def __init__(self, name: str = "", parent: "_NameScope" = None):
        self._children: Dict[str, int] = {}
        self._name = name
        self._parent = parent

    def child(self, prefix: str) -> "_NameScope":
        n = self._children.get(prefix, 0)
        self._children[prefix] = n + 1
        return _NameScope(prefix if n == 0 else f"{prefix}_{n}", self)


_name_scope = _NameScope()

# current pipeline stage for ops created under ``pipeline_stage_guard``
# (None = unmarked).  The pipeline transpiler reads the stamped
# ``pipeline_stage`` attr as a user-chosen cut assignment; unmarked ops
# inherit the previous op's stage (see paddle_tpu/pipeline/transpiler.py).
_pipeline_stage: Optional[int] = None


@contextlib.contextmanager
def pipeline_stage_guard(stage: int):
    """Stamp ops created in this block with ``pipeline_stage=stage``
    (the user-marked cut-point API of the pipeline transpiler; the
    reference's layer-placement precedent is ParallelNeuralNetwork's
    per-layer device assignment, legacy/gserver §2.7).  Stages must be
    used in non-decreasing program order — the transpiler validates
    that dataflow never crosses a stage boundary backwards."""
    global _pipeline_stage
    saved = _pipeline_stage
    _pipeline_stage = int(stage)
    try:
        yield
    finally:
        _pipeline_stage = saved


@contextlib.contextmanager
def name_scope(prefix: str):
    """Prefix ops created in this block with a hierarchical debug name
    (reference framework.py:80 — visualization/debugging only; carried
    on each op as the ``op_namescope`` attr)."""
    assert prefix, "name_scope prefix cannot be empty"
    global _name_scope
    _name_scope = _name_scope.child(prefix)
    try:
        yield
    finally:
        _name_scope = _name_scope._parent


def _full_name_scope() -> str:
    parts = []
    s = _name_scope
    while s is not None and s._name:
        parts.append(s._name)
        s = s._parent
    return "/".join(reversed(parts))


class Operator:
    """One node: type + name-keyed input/output var-name lists + typed attrs
    (reference OpDesc, framework.proto:42; python Operator, framework.py:494).

    Attr values are JSON-able scalars/lists; ``blocks``-typed attrs hold
    sub-block indices (control flow) as ints under attr names ending in
    ``_block`` by convention.
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, List[str]]] = None,
        outputs: Optional[Dict[str, List[str]]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        self.attrs.setdefault(OP_ROLE_ATTR, OpRole.Forward)

    # -- access ------------------------------------------------------------
    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def input_arg_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_arg_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def set_attr(self, name: str, val):
        self.attrs[name] = val

    def has_attr(self, name: str) -> bool:
        return name in self.attrs

    @property
    def sub_block_ids(self) -> List[int]:
        """Indices of sub-blocks referenced by this op's attrs."""
        out = []
        for k, v in self.attrs.items():
            if k.endswith("sub_block") and isinstance(v, int):
                out.append(v)
            elif k.endswith("sub_blocks") and isinstance(v, list):
                out.extend(int(x) for x in v)
        return out

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"{self.type}({ins} -> {outs})"

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": _jsonable_attrs(self.attrs),
        }

    @staticmethod
    def from_dict(block: "Block", d: dict) -> "Operator":
        return Operator(block, d["type"], d["inputs"], d["outputs"], d["attrs"])


def _jsonable_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


class Block:
    """Ordered op list + var table, with parent lookup for control-flow
    sub-blocks (reference BlockDesc, framework.proto:170; Block,
    framework.py:920)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []
        # forward_block_idx used by grad-of-control-flow (framework.proto:175)
        self.forward_block_idx = -1
        # padded-sequence bookkeeping: var name -> companion length var name
        # (the LoDTensor-offsets redesign; see layers/nn.py module docstring)
        self.seq_len_map: Dict[str, str] = {}
        # nested (lod_level 2) inner lengths: var name -> [B, S] companion
        self.seq_len2_map: Dict[str, str] = {}

    # -- vars --------------------------------------------------------------
    def create_var(self, name: Optional[str] = None, **kwargs) -> Variable:
        if name is None:
            name = unique_name.generate(TEMP_VAR_PREFIX)
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, **kwargs) -> Variable:
        kwargs.setdefault("persistable", True)
        kwargs["is_parameter"] = True
        v = self.create_var(name=name, shape=shape, dtype=dtype, **kwargs)
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def var(self, name: str) -> Variable:
        """Lookup with parent-block fallback (reference Scope-like chain for
        descs: framework.py `_var_recursive`)."""
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = self.program.blocks[b.parent_idx] if b.parent_idx >= 0 else None
        raise KeyError(f"variable {name!r} not found in block {self.idx} or ancestors")

    def var_or_none(self, name: str) -> Optional[Variable]:
        try:
            return self.var(name)
        except KeyError:
            return None

    # -- ops ---------------------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        # stamp the debug name_scope at CREATION time only — never in
        # Operator.__init__, which from_dict/clone also route through
        # (deserialization must restore attrs verbatim)
        ns = _full_name_scope()
        if ns:
            op.attrs.setdefault("op_namescope", f"/{ns}/")
        if _pipeline_stage is not None:
            op.attrs.setdefault("pipeline_stage", _pipeline_stage)
        self.ops.append(op)
        self.program._version += 1
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        ns = _full_name_scope()
        if ns:
            op.attrs.setdefault("op_namescope", f"/{ns}/")
        self.ops.insert(0, op)
        self.program._version += 1
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._version += 1
        return op

    def remove_op(self, index: int) -> None:
        del self.ops[index]
        self.program._version += 1

    @property
    def parent_block(self) -> Optional["Block"]:
        return self.program.blocks[self.parent_idx] if self.parent_idx >= 0 else None

    def all_parameters(self) -> List[Variable]:
        return [v for v in self.vars.values() if v.is_parameter]

    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "seq_len_map": dict(self.seq_len_map),
            "seq_len2_map": dict(self.seq_len2_map),
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


class Program:
    """A whole trainable/runnable program: a list of blocks, block 0 global
    (reference ProgramDesc, framework.proto:183; Program, framework.py:1404).
    """

    # process-monotonic identity for executor cache keys: id(program) is
    # REUSED by CPython after GC, and a fresh program landing on a dead
    # one's address (with an equal _version) silently hit the dead
    # program's cached executable — the root cause of the intermittently
    # "zero" numeric gradients in long test runs
    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self._version = 0  # bumped on mutation → invalidates executor caches
        self._uid = next(Program._uid_counter)
        self.random_seed = 0
        self._op_role = OpRole.Forward
        self._op_role_vars: List[str] = []
        # model-health scalars the executor should stamp into StepStats
        # when fetched: var name -> short stat key (e.g. switch_moe's
        # aux-loss / dropped-token fraction under "moe.<prefix>.*");
        # serialized with the program so transpiled clones keep it
        self.step_stat_vars: Dict[str, str] = {}

    # -- block management --------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        self._version += 1
        return b

    def _rollback(self) -> None:
        self._current_block_idx = self.current_block().parent_idx

    @contextlib.contextmanager
    def block_guard(self, parent_idx: Optional[int] = None):
        b = self._create_block(parent_idx)
        try:
            yield b
        finally:
            self._rollback()

    # -- op role guards (reference framework.py:1448-1484) -----------------
    @contextlib.contextmanager
    def op_role_guard(self, role: int, role_vars: Sequence[str] = ()):
        saved, saved_vars = self._op_role, self._op_role_vars
        self._op_role, self._op_role_vars = role, list(role_vars)
        try:
            yield
        finally:
            self._op_role, self._op_role_vars = saved, saved_vars

    @property
    def op_role(self):
        return self._op_role

    @property
    def op_role_vars(self):
        return list(self._op_role_vars)

    # -- queries -----------------------------------------------------------
    def all_parameters(self) -> List[Variable]:
        return self.global_block.all_parameters()

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    # -- clone / prune (reference framework.py:1545,1634) ------------------
    def clone(self) -> "Program":
        p = Program.from_dict(self.to_dict())
        p.random_seed = self.random_seed
        return p

    def prune(self, targets: Sequence[str]) -> "Program":
        """Dead-op elimination given fetch targets (reference
        framework/prune.cc).  Keeps ops whose outputs are (transitively)
        needed, preserving program order."""
        p = self.clone()
        blk = p.global_block
        needed = set(targets)
        keep: List[Operator] = []
        for op in reversed(blk.ops):
            if needed & set(op.output_arg_names()) or op.type in ("feed", "fetch"):
                keep.append(op)
                needed |= set(op.input_arg_names())
        keep.reverse()
        blk.ops = keep
        used = set()
        for op in blk.ops:
            used |= set(op.input_arg_names()) | set(op.output_arg_names())
        blk.vars = {n: v for n, v in blk.vars.items() if n in used}
        p._version += 1
        return p

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> dict:
        d = {"version": 1, "blocks": [b.to_dict() for b in self.blocks]}
        if self.step_stat_vars:
            d["step_stat_vars"] = dict(self.step_stat_vars)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program()
        p.blocks = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd.get("parent_idx", -1))
            b.forward_block_idx = bd.get("forward_block_idx", -1)
            b.seq_len_map = dict(bd.get("seq_len_map", {}))
            b.seq_len2_map = dict(bd.get("seq_len2_map", {}))
            for vd in bd["vars"]:
                b.vars[vd["name"]] = Variable.from_dict(b, vd)
            for od in bd["ops"]:
                b.ops.append(Operator.from_dict(b, od))
            p.blocks.append(b)
        p._current_block_idx = 0
        p.step_stat_vars = dict(d.get("step_stat_vars", {}))
        return p

    def to_string(self) -> str:
        lines = []
        for b in self.blocks:
            lines.append(f"-- block {b.idx} (parent {b.parent_idx}) --")
            for v in b.vars.values():
                tag = "param" if v.is_parameter else ("persist" if v.persistable else "var")
                lines.append(f"  {tag} {v.name}: {v.dtype}{list(v.shape) if v.shape else []}")
            for i, op in enumerate(b.ops):
                lines.append(f"  [{i}] {op!r}")
        return "\n".join(lines)

    def serialize(self) -> bytes:
        return json.dumps(self.to_dict()).encode("utf-8")

    @staticmethod
    def deserialize(data: bytes) -> "Program":
        return Program.from_dict(json.loads(data.decode("utf-8")))


# ---------------------------------------------------------------------------
# Default program singletons + guards (reference framework.py:2052-2120)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    global _main_program, _startup_program
    saved_main, saved_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program = saved_main
        _startup_program = saved_startup


def switch_main_program(program: Program) -> Program:
    global _main_program
    prev, _main_program = _main_program, program
    return prev
