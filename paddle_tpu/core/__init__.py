from . import types, unique_name  # noqa: F401
from .program import (  # noqa: F401
    Block,
    Operator,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
)
