"""Program-level inference passes: is_test stamping, fc+act fusion,
conv+bn folding.

Reference: ``framework/ir/fc_fuse_pass.cc`` (mul+add+act → fc),
``transpiler/inference_transpiler.py`` (conv+bn weight folding) and the
analysis predictor's pass pipeline (``analysis_predictor.cc``).  These
rewrite the serialized Program (and, for conv+bn, the weight Scope)
before the first XLA compile — XLA fuses elementwise chains anyway, so
the wins here are fewer ops to trace, BN statistics folded into conv
weights (one less memory-bound op), and reference capability parity.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ..core import registry
from ..core.program import Program

# activations the fc fuser recognizes (fc_fuse_pass handles relu; we take
# any registered unary activation with a plain X→Out contract)
_FUSABLE_ACTS = {"relu", "sigmoid", "tanh", "softmax", "gelu", "relu6",
                 "leaky_relu", "elu", "softplus", "swish"}


def apply_is_test(program: Program) -> None:
    """Stamp is_test=True on every op that distinguishes train/test
    (dropout, batch_norm, fused_attention, …) — inference programs run in
    test mode (the NaiveExecutor contract)."""
    for block in program.blocks:
        for op in block.ops:
            base = op.type[:-5] if op.type.endswith("_grad") else op.type
            if registry.has(base) and registry.get(base).stateful:
                op.set_attr("is_test", True)
            if op.type in ("batch_norm", "fused_attention", "dropout"):
                op.set_attr("is_test", True)
    program._version += 1  # invalidate any cached executable


def _use_counts(program: Program, keep_vars=()) -> Dict[str, int]:
    """Input-use counts; ``keep_vars`` (fetch targets) count as external
    uses so a fetched intermediate is never fused away or left holding
    rewritten values."""
    uses: Dict[str, int] = {}
    for block in program.blocks:
        for op in block.ops:
            for n in op.input_arg_names():
                uses[n] = uses.get(n, 0) + 1
    for n in keep_vars:
        uses[n] = uses.get(n, 0) + 1
    return uses


def fuse_fc_act(program: Program, scope=None, keep_vars=()) -> int:
    """mul → elementwise_add(bias) → activation collapses into one
    ``fused_fc`` op (fc_fuse_pass.cc); also fuses the act-less mul+add
    pair.  Returns the number of fusions applied."""
    block = program.global_block
    uses = _use_counts(program, keep_vars)
    fused = 0
    i = 0
    while i < len(block.ops) - 1:
        op = block.ops[i]
        nxt = block.ops[i + 1]
        if (op.type == "mul" and nxt.type == "elementwise_add"
                and op.output("Out") == nxt.input("X")
                and uses.get(op.output("Out")[0], 0) == 1):
            act_op = block.ops[i + 2] if i + 2 < len(block.ops) else None
            has_act = (act_op is not None
                       and act_op.type in _FUSABLE_ACTS
                       and act_op.input("X") == nxt.output("Out")
                       and uses.get(nxt.output("Out")[0], 0) == 1)
            out = (act_op.output("Out") if has_act else nxt.output("Out"))
            attrs = {
                "x_num_col_dims": op.attr("x_num_col_dims", 1),
                "y_num_col_dims": op.attr("y_num_col_dims", 1),
                "axis": nxt.attr("axis", -1),
                "act": act_op.type if has_act else "",
                # activation attrs travel verbatim (leaky_relu alpha, …)
                "act_attrs": dict(act_op.attrs) if has_act else {},
            }
            new = block.ops[i]
            new.type = "fused_fc"
            new.inputs = {"X": op.input("X"), "W": op.input("Y"),
                          "Bias": nxt.input("Y")}
            new.outputs = {"Out": out}
            new.attrs.update(attrs)
            del block.ops[i + 1:i + (3 if has_act else 2)]
            program._version += 1
            fused += 1
        i += 1
    return fused


def fuse_conv_bn(program: Program, scope, keep_vars=()) -> int:
    """conv2d → batch_norm(is_test) folds the BN affine into the conv
    filter and a bias add (inference_transpiler.py _fuse_param):
    W' = W·γ/σ (per out-channel), b' = β − μ·γ/σ.  Mutates the weight
    scope; returns the number of folds.  Shared (weight-tied) filters are
    skipped — scaling them would corrupt the sibling conv."""
    if scope is None:
        return 0
    block = program.global_block
    uses = _use_counts(program, keep_vars)
    folded = 0
    i = 0
    while i < len(block.ops) - 1:
        op = block.ops[i]
        nxt = block.ops[i + 1]
        if not (op.type == "conv2d" and nxt.type == "batch_norm"
                and nxt.input("X") == op.output("Output")
                and uses.get(op.output("Output")[0], 0) == 1):
            i += 1
            continue
        w_name = op.input("Filter")[0]
        if uses.get(w_name, 0) > 1:
            i += 1
            continue
        scale = np.asarray(scope.find_var(nxt.input("Scale")[0]))
        bias = np.asarray(scope.find_var(nxt.input("Bias")[0]))
        mean = np.asarray(scope.find_var(nxt.input("Mean")[0]))
        var = np.asarray(scope.find_var(nxt.input("Variance")[0]))
        eps = float(nxt.attr("epsilon", 1e-5))
        std = np.sqrt(var + eps)
        w = np.asarray(scope.find_var(w_name))
        scope.set_var(w_name, (w * (scale / std)[:, None, None, None]
                               ).astype(w.dtype))
        # keyed by the BN's own scale var: unique even if filters repeat
        fold_bias_name = nxt.input("Scale")[0] + "@BN_FOLD_BIAS"
        fold_bias = (bias - mean * scale / std).astype(w.dtype)
        block.create_var(name=fold_bias_name, shape=fold_bias.shape,
                         dtype=str(w.dtype), persistable=True)
        scope.set_var(fold_bias_name, fold_bias)
        # batch_norm op becomes the bias add (axis=1: per channel)
        nxt.type = "elementwise_add"
        nxt.inputs = {"X": op.output("Output"), "Y": [fold_bias_name]}
        nxt.outputs = {"Out": nxt.output("Y")}
        nxt.attrs = {"axis": 1}
        program._version += 1
        folded += 1
        i += 1
    return folded


# ---------------------------------------------------------------------------
# NHWC layout pass (the reference transpiler family's layout rewrites +
# the TPU analogue of TF grappler's layout optimizer): convert NCHW
# conv/bn/pool chains to channels-last, the MXU-preferred layout, with
# boundary transposes.  Opt-in (AnalysisConfig pass "convert_to_nhwc").
# ---------------------------------------------------------------------------

_LAYOUT_OPS = {"conv2d", "depthwise_conv2d", "pool2d", "batch_norm"}
# elementwise/activation ops that pass a layout through untouched when all
# their 4-D inputs share it
_LAYOUT_TRANSPARENT = {"relu", "relu6", "sigmoid", "tanh", "leaky_relu",
                       "elu", "swish", "gelu", "abs", "sqrt", "square",
                       "scale", "dropout", "elementwise_add",
                       "elementwise_sub", "elementwise_mul"}
# NOTE: prelu is NOT layout-transparent — its lowering reshapes Alpha
# assuming channel dim 1 (mode='channel'/'element'), so passing NHWC
# through it would broadcast Alpha against W instead of C.


def _nchw_shape(s):
    return (s[0], s[3], s[1], s[2])


def _nhwc_shape(s):
    return (s[0], s[2], s[3], s[1])


def convert_to_nhwc(program: Program, scope=None, keep_vars=()) -> int:
    """Rewrite layout-sensitive ops of the global block to
    data_layout=NHWC (inference programs; conv filters stay OIHW so the
    Scope is untouched — the conv lowering retargets its spec).

    Walks ops in order keeping the set of vars currently holding NHWC
    values; inserts boundary transposes for NCHW consumers and for the
    ``keep_vars`` fetch targets.  Returns the number of ops converted."""
    from ..core.program import Operator

    block = program.global_block
    nhwc: set = set()
    new_ops = []
    converted = 0

    def transpose(src, axis, dst_name, dst_shape):
        dst = block.create_var(name=dst_name,
                               dtype=block.var(src).dtype,
                               shape=dst_shape)
        new_ops.append(Operator(block, "transpose", {"X": [src]},
                                {"Out": [dst.name]}, {"axis": axis}))
        return dst.name

    def rename_in(op, old, new):
        op.inputs = {k: [new if n == old else n for n in v]
                     for k, v in op.inputs.items()}

    for op in block.ops:
        ins = op.input_arg_names()
        if (op.type in _LAYOUT_OPS
                and op.attr("data_layout", "NCHW") == "NCHW"):
            data_slot = "Input" if "conv" in op.type else "X"
            xname = op.input(data_slot)[0]
            xvar = block.var_or_none(xname)
            if xvar is None or xvar.shape is None or len(xvar.shape) != 4:
                new_ops.append(op)
                continue
            if xname not in nhwc:
                t = transpose(xname, [0, 2, 3, 1], f"{xname}@NHWC",
                              _nhwc_shape(xvar.shape))
                rename_in(op, xname, t)
                nhwc.add(t)
            op.set_attr("data_layout", "NHWC")
            out = op.output("Output" if "conv" in op.type
                            else ("Y" if op.type == "batch_norm"
                                  else "Out"))[0]
            ovar = block.var(out)
            ovar.shape = _nhwc_shape(ovar.shape)
            nhwc.add(out)
            converted += 1
            new_ops.append(op)
            continue
        if op.type in _LAYOUT_TRANSPARENT and ins and ins[0] in nhwc:
            ok = True
            for other in ins[1:]:
                v = block.var_or_none(other)
                if (v is not None and v.shape is not None
                        and len(v.shape) == 4 and other not in nhwc):
                    ok = False
            if ok and op.type.startswith("elementwise")                     and op.attr("axis", -1) == 1:
                yv = block.var_or_none(op.input("Y")[0])
                if yv is not None and yv.shape is not None                         and len(yv.shape) == 1:
                    op.set_attr("axis", 3)  # channel bias rides last now
                else:
                    ok = False
            if ok:
                for oname in op.output_arg_names():
                    ovar = block.var_or_none(oname)
                    if ovar is not None and ovar.shape is not None                             and len(ovar.shape) == 4:
                        ovar.shape = _nhwc_shape(ovar.shape)
                        nhwc.add(oname)
                new_ops.append(op)
                continue
        # NCHW consumer of NHWC vars: transpose back before this op
        for name in set(ins):
            if name in nhwc:
                back = transpose(name, [0, 3, 1, 2], f"{name}@NCHW",
                                 _nchw_shape(block.var(name).shape))
                rename_in(op, name, back)
        new_ops.append(op)

    # fetch targets left in NHWC: rename the producing chain to an inner
    # var and transpose back into the original name/shape
    for name in keep_vars:
        if name in nhwc:
            v = block.var(name)
            inner = block.create_var(name=f"{name}@NHWCVAL", dtype=v.dtype,
                                     shape=v.shape)
            for op in new_ops:
                op.outputs = {k: [inner.name if n == name else n
                                  for n in vs]
                              for k, vs in op.outputs.items()}
                rename_in(op, name, inner.name)
            v.shape = _nchw_shape(v.shape)
            new_ops.append(Operator(block, "transpose",
                                    {"X": [inner.name]}, {"Out": [name]},
                                    {"axis": [0, 3, 1, 2]}))
    block.ops[:] = new_ops
    program._version += 1
    return converted


# ---------------------------------------------------------------------------
# fc+RNN fusion (fc_lstm_fuse_pass.cc / fc_gru_fuse_pass.cc): the
# x-projection matmul (+ bias adds) feeding an lstm/gru collapses into the
# fusion_lstm / fusion_gru op, whose lowering runs the projection and the
# scan in one op (the CPU jit-kernel fusion's graph form).
# ---------------------------------------------------------------------------

def _bias_vec(scope, name):
    """1-D bias param value (reshaped), or None."""
    v = scope.find_var(name) if scope is not None else None
    if v is None:
        return None
    return np.asarray(v).reshape(-1)


def _fuse_fc_rnn(program, scope, keep_vars, rnn_type, fused_type,
                 gate_mult):
    """Shared fc+lstm / fc+gru rewrite.  Pattern (use-counts == 1 on the
    intermediates, LastH/LastC unused):

        mul(X, Wx)[x_num_col_dims=2] -> [elementwise_add(b)]{1,2} -> rnn

    becomes ``fused_type`` with the bias vectors summed into one [1, G·H]
    Bias param (created in the scope).  ``gate_mult`` (4 for lstm, 3 for
    gru) validates every folded bias is a true gate bias of length G·H —
    an add of any other 1-D vector (e.g. a per-timestep offset broadcast
    along T) is left alone."""
    block = program.global_block
    uses = _use_counts(program, keep_vars)
    fused = 0
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type != rnn_type:
            i += 1
            continue
        if op.attrs.get("use_pallas_kernel") is not None:
            i += 1
            continue
        # LastH/LastC must be dead (fusion ops don't emit them)
        last_names = [n for slot in ("LastH", "LastC")
                      for n in op.output(slot)]
        if any(uses.get(n, 0) > 0 for n in last_names):
            i += 1
            continue
        # walk the Input producer chain: up to two bias adds then the mul
        chain = []          # ops to delete (in block order)
        biases = []
        cur = op.input("Input")[0]
        j = i - 1
        mul_op = None
        while j >= 0 and len(chain) < 3:
            p = block.ops[j]
            if cur in p.output_arg_names():
                y_shape = tuple(block.var(p.input("Y")[0]).shape or ())
                H = (block.var(op.input("Weight")[0]).shape or (0,))[0]
                if (p.type == "elementwise_add"
                        and p.output("Out") == [cur]
                        and uses.get(cur, 0) == 1
                        and y_shape == (gate_mult * H,)
                        and p.attr("axis", -1) in (-1, 2)):
                    biases.append(p.input("Y")[0])
                    chain.append(p)
                    cur = p.input("X")[0]
                elif (p.type == "mul" and p.output("Out") == [cur]
                        and uses.get(cur, 0) == 1
                        and p.attr("x_num_col_dims", 1) == 2):
                    mul_op = p
                    chain.append(p)
                    break
                else:
                    break
            j -= 1
        if mul_op is None or not biases:
            i += 1
            continue
        bias_vals = [_bias_vec(scope, n) for n in biases]
        if any(b is None for b in bias_vals):
            i += 1
            continue
        total = bias_vals[0]
        for b in bias_vals[1:]:
            total = total + b
        bias_name = f"{op.output('Hidden')[0]}@FUSED_BIAS"
        block.create_var(name=bias_name, shape=(1, total.shape[0]),
                         dtype=str(total.dtype), persistable=True)
        scope.set_var(bias_name, total.reshape(1, -1))

        ins = {"X": mul_op.input("X"), "WeightX": mul_op.input("Y"),
               "WeightH": op.input("Weight"), "Bias": [bias_name]}
        for slot in ("H0", "C0", "SeqLen"):
            if op.input(slot):
                ins[slot] = op.input(slot)
        xx = block.create_var(
            name=f"{op.output('Hidden')[0]}@XX",
            dtype=block.var(op.output("Hidden")[0]).dtype,
            shape=block.var(mul_op.output("Out")[0]).shape)
        outs = {"Hidden": op.output("Hidden"), "XX": [xx.name]}
        if rnn_type == "lstm":
            outs["Cell"] = op.output("Cell")
        op.type = fused_type
        op.inputs = ins
        op.outputs = outs
        for dead in chain:
            block.ops.remove(dead)
            i -= 1
        program._version += 1
        fused += 1
        # the rewrite removed ops and rewired inputs: refresh use-counts
        # so a later RNN sharing intermediates can't pass a stale
        # use-count==1 check
        uses = _use_counts(program, keep_vars)
        i += 1
    return fused


def fuse_fc_lstm(program: Program, scope=None, keep_vars=()) -> int:
    return _fuse_fc_rnn(program, scope, keep_vars, "lstm", "fusion_lstm", 4)


def fuse_fc_gru(program: Program, scope=None, keep_vars=()) -> int:
    return _fuse_fc_rnn(program, scope, keep_vars, "gru", "fusion_gru", 3)


# ---------------------------------------------------------------------------
# int8 serving calibration (ROADMAP item 3 leg (a)): derive scales from
# QAT fake-quant stats (or post-training weight abs-max), stamp
# mul/fused_fc ops for the fused-dequant int8 Pallas matmul peephole
# (kernels/quant.py Int8Plan, consulted by core/lowering.py).
# ---------------------------------------------------------------------------

# the epilogue set kernels/quant.py implements; a fused_fc with any
# other activation (or act attrs) stays f32
_INT8_ACTS = {"", "relu", "sigmoid", "tanh", "gelu"}

_FAKE_QUANT_OPS = ("fake_quantize_abs_max",
                   "fake_channel_wise_quantize_abs_max",
                   "fake_quantize_moving_average_abs_max")


def quantize_int8(program: Program, scope, keep_vars=()) -> int:
    """Calibrate the program for int8 inference (AnalysisConfig
    ``enable_int8()``; run by create_predictor like every pass).

    Two steps, mirroring the reference's freeze path:

    1. QAT fake-quant ops (``contrib/quantize.py`` inserted them) fold
       OUT of the graph: consumers rewire to the raw var, and a
       moving-average quantizer's calibrated running scale
       (``InScale``, frozen by training) is harvested as the consumer's
       static activation scale.  abs_max quantizers are dynamic by
       design (quantize_transpiler.py:96) — their consumers quantize
       from the batch abs-max at dispatch, same math, no graph op.
    2. Every mul/fused_fc whose weight is a 2-D persistable scope var
       gains the int8 stamp: the weight is quantized NOW (per-out-
       channel abs-max — finer than the QAT per-tensor scale, and free
       at pass time) into ``<w>@INT8`` / ``<w>@INT8_SCALE`` sidecar
       scope vars + ``quant_int8``/``in_scale`` attrs.  The original
       f32 weight stays in scope so the per-op fallback (and a
       fault-recovery re-lower) keeps the untouched reference path.

    Returns the number of ops calibrated."""
    from ..kernels import quant as Q

    block = program.global_block
    # -- 1) fold fake-quant ops, harvesting calibrated scales ----------
    in_scale_of: Dict[str, float] = {}
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type not in _FAKE_QUANT_OPS:
            i += 1
            continue
        out = op.output("Out")[0]
        if out in keep_vars:
            i += 1
            continue
        src = op.input("X")[0]
        scale = 0.0  # dynamic (batch abs-max at dispatch)
        if op.type == "fake_quantize_moving_average_abs_max" \
                and scope is not None and op.input("InScale"):
            sv = scope.find_var(op.input("InScale")[0])
            if sv is not None:
                scale = float(np.asarray(sv).reshape(-1)[0])
        for c in block.ops:
            if c is op:
                continue
            c.inputs = {slot: [src if n == out else n for n in names]
                        for slot, names in c.inputs.items()}
        in_scale_of[src] = scale
        del block.ops[i]
        program._version += 1

    # -- 2) stamp calibrated FC ops ------------------------------------
    count = 0
    for op in block.ops:
        if op.type not in ("mul", "fused_fc"):
            continue
        if op.attrs.get("quant_int8"):
            continue  # already calibrated (pass re-run)
        w_slot = "Y" if op.type == "mul" else "W"
        w_names = op.inputs.get(w_slot) or []
        if len(w_names) != 1 or scope is None:
            continue
        w_name = w_names[0]
        wv = scope.find_var(w_name)
        if wv is None:
            continue
        w = np.asarray(wv)
        if w.ndim != 2:
            continue
        if int(op.attrs.get("y_num_col_dims", 1)) != 1:
            continue
        act = op.attrs.get("act", "") or "" if op.type == "fused_fc" else ""
        # op_role rides every op's attrs (bookkeeping, not an
        # activation parameter); any OTHER act attr means the epilogue
        # can't reproduce the activation exactly
        if act not in _INT8_ACTS or (
                op.type == "fused_fc"
                and any(k != "op_role"
                        for k in (op.attrs.get("act_attrs") or {}))):
            continue
        q, scales = Q.quantize_weight(w)
        qi_name = f"{w_name}@INT8"
        qs_name = f"{w_name}@INT8_SCALE"
        block.create_var(name=qi_name, shape=tuple(w.shape), dtype="int8",
                         persistable=True)
        block.create_var(name=qs_name, shape=(int(w.shape[1]),),
                         dtype="float32", persistable=True)
        scope.set_var(qi_name, q)
        scope.set_var(qs_name, scales)
        x_name = op.input("X")[0]
        in_scale = float(in_scale_of.get(x_name, 0.0))
        op.inputs["WInt8"] = [qi_name]
        op.inputs["WScale"] = [qs_name]
        op.attrs["quant_int8"] = True
        op.attrs["in_scale"] = in_scale
        program._version += 1
        count += 1
        Q.note_calibration({
            "op": op.type,
            "weight": w_name,
            "shape": [int(d) for d in w.shape],
            "act": act,
            "in_scale": in_scale,  # 0.0 = dynamic per-dispatch
            "w_scale_min": float(scales.min()),
            "w_scale_max": float(scales.max()),
            "clip_fraction": Q.clip_fraction(q),
        })
    return count


# what the fused_elemwise_activation LOWERING implements (nn_ops.py
# unary dict) — narrower than _FUSABLE_ACTS, and attr-free
_ELEWISE_ACTS = {"relu", "sigmoid", "tanh"}


def fuse_elewise_add_act(program: Program, scope=None, keep_vars=()) -> int:
    """elementwise_add -> activation collapses into
    fused_elemwise_activation (fuse_elewise_add_act_pass.cc)."""
    block = program.global_block
    uses = _use_counts(program, keep_vars)
    fused = 0
    i = 0
    while i < len(block.ops) - 1:
        op, nxt = block.ops[i], block.ops[i + 1]
        if (op.type == "elementwise_add" and nxt.type in _ELEWISE_ACTS
                and nxt.input("X") == op.output("Out")
                and uses.get(op.output("Out")[0], 0) == 1):
            op.type = "fused_elemwise_activation"
            op.outputs = {"Out": nxt.output("Out")}
            op.attrs["functor_list"] = ["elementwise_add", nxt.type]
            del block.ops[i + 1]
            program._version += 1
            fused += 1
        i += 1
    return fused
