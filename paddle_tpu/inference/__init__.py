"""Inference subsystem: compiled-executable predictor + fusion passes.

Reference: ``paddle/fluid/inference/api/paddle_inference_api.h:141,211``
(``PaddlePredictor`` with the clone-per-thread contract,
``CreatePaddlePredictor``), ``api/analysis_predictor.cc`` (IR fusion
passes before compilation) and ``transpiler/inference_transpiler.py``
(conv+bn folding).

TPU-native shape: the predictor wraps a pruned inference Program + a
weight Scope; the first ``run`` per input signature JIT-compiles the
whole block to one XLA executable (cached thereafter — the NaiveExecutor
hot path becomes a single device call).  ``clone()`` shares program and
weights but owns a fresh executable cache, so clones are independently
usable across threads.  Program-level fusion passes (fc+act, conv+bn
fold) shrink the op graph and fold BN statistics into conv weights
before compilation.
"""
from .predictor import (AnalysisConfig, NativeConfig, Predictor,
                        create_predictor, create_paddle_predictor)
from . import passes  # noqa: F401

__all__ = ["AnalysisConfig", "NativeConfig", "Predictor",
           "create_predictor", "create_paddle_predictor", "passes"]
