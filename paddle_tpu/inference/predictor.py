"""Predictor: the PaddlePredictor analogue over compiled executables.

Reference: ``inference/api/paddle_inference_api.h:141`` (PaddlePredictor:
``Run``, ``Clone``), ``api_impl.cc`` (NativeConfig path) and
``analysis_predictor.cc`` (runs IR passes first when ir_optim is on).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import io as _io
from ..core.executor import Executor, Scope, scope_guard
from ..core.program import Program


class AnalysisConfig:
    """Predictor configuration (NativeConfig/AnalysisConfig:183)."""

    def __init__(self, model_dir: Optional[str] = None):
        self.model_dir = model_dir
        self.ir_optim = True
        self._passes = ["fuse_fc_lstm", "fuse_fc_gru",
                        "fuse_conv_bn", "fuse_fc_act"]
        # warm-start wiring (serving plane): batch sizes to AOT-warm at
        # create_predictor time when FLAGS_compile_cache_dir is set, so
        # a served model's first request never pays an XLA compile
        # (Executor.warm_start; counted via executor.persistent_hits).
        # None (default) keeps create_predictor byte-identical.
        self.warm_start_batch_sizes: Optional[List[int]] = None
        self._warm_sample_shapes: Optional[Dict[str, tuple]] = None

    def set_model(self, model_dir: str) -> None:
        self.model_dir = model_dir

    def set_warm_start(self, batch_sizes,
                       sample_shapes: Optional[Dict[str, tuple]] = None
                       ) -> None:
        """Ask ``create_predictor`` to precompile one executable per
        batch size (specs derived from the program's static feed
        declarations; ``sample_shapes`` overrides feeds whose non-batch
        dims are symbolic, e.g. padded sequence models).  Effective
        only with the persistent compile cache enabled
        (``FLAGS_compile_cache_dir``) — without it the first request
        would pay the same compile either way and cold create stays
        cheap."""
        self.warm_start_batch_sizes = [int(b) for b in batch_sizes]
        self._warm_sample_shapes = (
            {k: tuple(v) for k, v in sample_shapes.items()}
            if sample_shapes else None)

    def switch_ir_optim(self, flag: bool = True) -> None:
        self.ir_optim = flag

    def pass_names(self) -> List[str]:
        return list(self._passes) if self.ir_optim else []

    def delete_pass(self, name: str) -> None:
        self._passes = [p for p in self._passes if p != name]

    def add_pass(self, name: str) -> None:
        """Append an optional pass (e.g. "convert_to_nhwc", the
        channels-last layout rewrite) to the ir_optim pipeline."""
        if name not in self._passes:
            self._passes.append(name)

    def enable_int8(self) -> None:
        """Calibrate the loaded model for int8 serving (the reference's
        MkldnnQuantizer/TensorRT-int8 config knob, TPU-shaped): appends
        the ``quantize_int8`` pass, which folds QAT fake-quant ops into
        harvested scales (post-training weight abs-max when no QAT
        stats exist) and stamps mul/fused_fc ops for the fused-dequant
        int8 Pallas matmul (``kernels/quant.py``).  AFTER the fusion
        passes — fuse_fc_act must build fused_fc ops first so the int8
        epilogue absorbs bias+activation too."""
        self.add_pass("quantize_int8")

    def int8_enabled(self) -> bool:
        return "quantize_int8" in self._passes


NativeConfig = AnalysisConfig


class Predictor:
    """Compiled-program predictor with the clone-per-thread contract."""

    def __init__(self, program: Program, feed_names: Sequence[str],
                 fetch_names: Sequence[str], scope: Scope):
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        self._scope = scope          # shared weights (clone keeps sharing)
        self._exe = Executor(training=False)   # inference lowering mode
        self._lock = threading.Lock()  # executor cache is per-predictor

    # -- PaddlePredictor::Run ---------------------------------------------
    def run(self, inputs) -> List[np.ndarray]:
        """inputs: dict name→array, or list of arrays in feed order."""
        if not isinstance(inputs, dict):
            inputs = dict(zip(self._feed_names, inputs))
        missing = [n for n in self._feed_names if n not in inputs]
        if missing:
            raise ValueError(f"predictor missing feeds: {missing}")
        with self._lock:
            return self._exe.run(self._program, feed=inputs,
                                 fetch_list=self._fetch_names,
                                 scope=self._scope)

    # -- warm start (serving plane / persistent compile cache) ------------
    def warm_start(self, feed_specs, hydrate_only: bool = False) -> dict:
        """AOT-precompile this predictor's executables before the first
        request (``Executor.warm_start``): ``feed_specs`` is one
        name→spec dict, or a LIST of them (a serving bucket ladder —
        one executable per batch size).  With
        ``FLAGS_compile_cache_dir`` set, warm entries hydrate from /
        store to the persistent cache, so a redeployed server compiles
        nothing (executor.persistent_hits counts the wins)."""
        with self._lock:
            return self._exe.warm_start(self._program, feed_specs,
                                        self._fetch_names,
                                        scope=self._scope,
                                        hydrate_only=hydrate_only)

    def feed_specs_for_batch(self, batch_size: int,
                             sample_shapes: Optional[Dict] = None) -> Dict:
        """One warm_start spec dict at ``batch_size``, shapes from the
        program's static feed declarations (``(-1, *sample)``);
        ``sample_shapes`` fills feeds with symbolic non-batch dims."""
        block = self._program.global_block
        specs = {}
        for n in self._feed_names:
            var = block.var_or_none(n)
            dtype = (var.dtype if var is not None and var.dtype is not None
                     else "float32")
            if sample_shapes and n in sample_shapes:
                sample = tuple(int(s) for s in sample_shapes[n])
            else:
                if var is None or var.shape is None:
                    raise ValueError(
                        f"feed {n!r} has no static declaration; pass "
                        "sample_shapes")
                sample = tuple(var.shape[1:])
                if any(s < 0 for s in sample):
                    raise ValueError(
                        f"feed {n!r} declares symbolic dims {var.shape}; "
                        "pass sample_shapes with the served padded shape")
            specs[n] = ((int(batch_size),) + sample, dtype)
        return specs

    # -- PaddlePredictor::Clone -------------------------------------------
    def clone(self) -> "Predictor":
        """Same program + shared weights, own executable cache — safe to
        hand one clone per serving thread (api_impl.cc Clone)."""
        return Predictor(self._program, self._feed_names,
                         self._fetch_names, self._scope)

    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)

    def program(self) -> Program:
        return self._program


def create_predictor(config: AnalysisConfig) -> Predictor:
    """Load an inference model dir and build a Predictor
    (CreatePaddlePredictor:211; the analysis path applies fusion passes
    before the first compile)."""
    from . import passes as P

    if not config.model_dir:
        raise ValueError("AnalysisConfig.model_dir is not set")
    scope = Scope()
    exe = Executor()
    with scope_guard(scope):
        program, feed_names, fetch_vars = _io.load_inference_model(
            config.model_dir, exe)
    # inference programs run in test mode: stamp is_test on stateful ops
    P.apply_is_test(program)
    fetch_names = [v.name for v in fetch_vars]
    # FLAGS_int8_inference: fleet-wide default-on switch for the int8
    # calibration pass, as if every config called enable_int8().  Off
    # (default): only explicit enable_int8() configs quantize
    from ..core import flags as _flags
    if _flags.get_flags("int8_inference") and config.ir_optim:
        config.enable_int8()
    for name in config.pass_names():
        # fetch targets count as external uses: never fused away/rewritten
        getattr(P, name)(program, scope, keep_vars=fetch_names)
    pred = Predictor(program, feed_names, [v.name for v in fetch_vars],
                     scope)
    if config.warm_start_batch_sizes:
        from ..core import compile_cache as _compile_cache
        if _compile_cache.enabled():
            # persistent-cache warm start: a redeployed/served model's
            # first request hydrates AOT executables from disk instead
            # of paying the XLA compile (executor.persistent_hits);
            # with the cache cold this stores them for the next process.
            # Flag unset: skipped — create_predictor stays byte-identical
            pred.warm_start([
                pred.feed_specs_for_batch(b, config._warm_sample_shapes)
                for b in config.warm_start_batch_sizes])
    return pred


create_paddle_predictor = create_predictor
