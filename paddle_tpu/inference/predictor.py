"""Predictor: the PaddlePredictor analogue over compiled executables.

Reference: ``inference/api/paddle_inference_api.h:141`` (PaddlePredictor:
``Run``, ``Clone``), ``api_impl.cc`` (NativeConfig path) and
``analysis_predictor.cc`` (runs IR passes first when ir_optim is on).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import io as _io
from ..core.executor import Executor, Scope, scope_guard
from ..core.program import Program


class AnalysisConfig:
    """Predictor configuration (NativeConfig/AnalysisConfig:183)."""

    def __init__(self, model_dir: Optional[str] = None):
        self.model_dir = model_dir
        self.ir_optim = True
        self._passes = ["fuse_fc_lstm", "fuse_fc_gru",
                        "fuse_conv_bn", "fuse_fc_act"]

    def set_model(self, model_dir: str) -> None:
        self.model_dir = model_dir

    def switch_ir_optim(self, flag: bool = True) -> None:
        self.ir_optim = flag

    def pass_names(self) -> List[str]:
        return list(self._passes) if self.ir_optim else []

    def delete_pass(self, name: str) -> None:
        self._passes = [p for p in self._passes if p != name]

    def add_pass(self, name: str) -> None:
        """Append an optional pass (e.g. "convert_to_nhwc", the
        channels-last layout rewrite) to the ir_optim pipeline."""
        if name not in self._passes:
            self._passes.append(name)


NativeConfig = AnalysisConfig


class Predictor:
    """Compiled-program predictor with the clone-per-thread contract."""

    def __init__(self, program: Program, feed_names: Sequence[str],
                 fetch_names: Sequence[str], scope: Scope):
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        self._scope = scope          # shared weights (clone keeps sharing)
        self._exe = Executor(training=False)   # inference lowering mode
        self._lock = threading.Lock()  # executor cache is per-predictor

    # -- PaddlePredictor::Run ---------------------------------------------
    def run(self, inputs) -> List[np.ndarray]:
        """inputs: dict name→array, or list of arrays in feed order."""
        if not isinstance(inputs, dict):
            inputs = dict(zip(self._feed_names, inputs))
        missing = [n for n in self._feed_names if n not in inputs]
        if missing:
            raise ValueError(f"predictor missing feeds: {missing}")
        with self._lock:
            return self._exe.run(self._program, feed=inputs,
                                 fetch_list=self._fetch_names,
                                 scope=self._scope)

    # -- PaddlePredictor::Clone -------------------------------------------
    def clone(self) -> "Predictor":
        """Same program + shared weights, own executable cache — safe to
        hand one clone per serving thread (api_impl.cc Clone)."""
        return Predictor(self._program, self._feed_names,
                         self._fetch_names, self._scope)

    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)

    def program(self) -> Program:
        return self._program


def create_predictor(config: AnalysisConfig) -> Predictor:
    """Load an inference model dir and build a Predictor
    (CreatePaddlePredictor:211; the analysis path applies fusion passes
    before the first compile)."""
    from . import passes as P

    if not config.model_dir:
        raise ValueError("AnalysisConfig.model_dir is not set")
    scope = Scope()
    exe = Executor()
    with scope_guard(scope):
        program, feed_names, fetch_vars = _io.load_inference_model(
            config.model_dir, exe)
    # inference programs run in test mode: stamp is_test on stateful ops
    P.apply_is_test(program)
    fetch_names = [v.name for v in fetch_vars]
    for name in config.pass_names():
        # fetch targets count as external uses: never fused away/rewritten
        getattr(P, name)(program, scope, keep_vars=fetch_names)
    return Predictor(program, feed_names, [v.name for v in fetch_vars],
                     scope)


create_paddle_predictor = create_predictor
