"""Marshal layer for the native C inference API (native/paddle_tpu_capi.cc).

The embedded interpreter calls ONLY these three functions, passing plain
Python ints/strs/bytes — no numpy C-API or ctypes on the C side, so the
native library compiles against Python.h alone.  Reference role: the
glue the legacy capi's gradient_machine.cpp plays between C structs and
the C++ core (paddle/legacy/capi/gradient_machine.cpp), redesigned as a
bytes-protocol bridge.

Wire format per tensor: (name:str, dtype:str, shape:tuple[int], data:bytes).
"""
from __future__ import annotations

import threading
from typing import List, Tuple

import numpy as np

_DTYPES = {
    "float32": np.float32,
    "int64": np.int64,
    "int32": np.int32,
    "float64": np.float64,
    "uint8": np.uint8,
}

class HandleRegistry:
    """Thread-safe int-handle table; shared by the C-API bridges (this
    one and paddle_tpu/train/capi_bridge.py)."""

    def __init__(self):
        self._handles = {}
        self._next = 1
        self._lock = threading.Lock()

    def add(self, obj) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._handles[h] = obj
            return h

    def get(self, h: int):
        with self._lock:
            return self._handles[h]

    def pop(self, h: int) -> None:
        with self._lock:
            self._handles.pop(h, None)


_registry = HandleRegistry()


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(_DTYPES[name])


def create(model_dir: str) -> int:
    import os

    if os.environ.get("PT_CAPI_JAX_PLATFORM"):
        # the env-var JAX_PLATFORMS route is dead once a PJRT plugin has
        # registered; honor an explicit platform request in-process (the
        # C smoke test runs on the forced-CPU mesh this way)
        import jax

        jax.config.update("jax_platforms",
                          os.environ["PT_CAPI_JAX_PLATFORM"])
    from .predictor import AnalysisConfig, create_predictor

    pred = create_predictor(AnalysisConfig(model_dir))
    return _registry.add(pred)


def clone(handle: int) -> int:
    return _registry.add(_registry.get(handle).clone())


def feed_names(handle: int) -> List[str]:
    return _registry.get(handle).feed_names


def fetch_count(handle: int) -> int:
    return len(_registry.get(handle).fetch_names)


def run(handle: int,
        inputs: List[Tuple[str, str, tuple, bytes]]
        ) -> List[Tuple[str, tuple, bytes]]:
    pred = _registry.get(handle)
    feed = {}
    for name, dtype, shape, data in inputs:
        feed[name] = np.frombuffer(data, dtype=_np_dtype(dtype)).reshape(shape)
    outs = pred.run(feed)
    wire = []
    for o in outs:
        a = np.ascontiguousarray(np.asarray(o))
        dt = str(a.dtype)
        wire.append((dt, tuple(int(d) for d in a.shape), a.tobytes()))
    return wire


def destroy(handle: int) -> None:
    _registry.pop(handle)
