"""Marshal layer for the native C inference API (native/paddle_tpu_capi.cc).

The embedded interpreter calls ONLY these three functions, passing plain
Python ints/strs/bytes — no numpy C-API or ctypes on the C side, so the
native library compiles against Python.h alone.  Reference role: the
glue the legacy capi's gradient_machine.cpp plays between C structs and
the C++ core (paddle/legacy/capi/gradient_machine.cpp), redesigned as a
bytes-protocol bridge.

Wire format per tensor: (name:str, dtype:str, shape:tuple[int], data:bytes).
"""
from __future__ import annotations

import threading
from typing import List, Tuple

import numpy as np

_DTYPES = {
    "float32": np.float32,
    "int64": np.int64,
    "int32": np.int32,
    "float64": np.float64,
    "uint8": np.uint8,
}

_handles = {}
_next = [1]
_lock = threading.Lock()


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(_DTYPES[name])


def create(model_dir: str) -> int:
    import os

    if os.environ.get("PT_CAPI_JAX_PLATFORM"):
        # the env-var JAX_PLATFORMS route is dead once a PJRT plugin has
        # registered; honor an explicit platform request in-process (the
        # C smoke test runs on the forced-CPU mesh this way)
        import jax

        jax.config.update("jax_platforms",
                          os.environ["PT_CAPI_JAX_PLATFORM"])
    from .predictor import AnalysisConfig, create_predictor

    pred = create_predictor(AnalysisConfig(model_dir))
    with _lock:
        h = _next[0]
        _next[0] += 1
        _handles[h] = pred
    return h


def clone(handle: int) -> int:
    with _lock:
        pred = _handles[handle]
    c = pred.clone()
    with _lock:
        h = _next[0]
        _next[0] += 1
        _handles[h] = c
    return h


def feed_names(handle: int) -> List[str]:
    with _lock:
        return _handles[handle].feed_names


def fetch_count(handle: int) -> int:
    with _lock:
        return len(_handles[handle].fetch_names)


def run(handle: int,
        inputs: List[Tuple[str, str, tuple, bytes]]
        ) -> List[Tuple[str, tuple, bytes]]:
    with _lock:
        pred = _handles[handle]
    feed = {}
    for name, dtype, shape, data in inputs:
        feed[name] = np.frombuffer(data, dtype=_np_dtype(dtype)).reshape(shape)
    outs = pred.run(feed)
    wire = []
    for o in outs:
        a = np.ascontiguousarray(np.asarray(o))
        dt = str(a.dtype)
        wire.append((dt, tuple(int(d) for d in a.shape), a.tobytes()))
    return wire


def destroy(handle: int) -> None:
    with _lock:
        _handles.pop(handle, None)
