"""fluid.annotations (reference python/paddle/fluid/annotations.py:19):
the ``deprecated`` decorator — warns once per call site with the
since-version and replacement API."""
from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(since, instead, extra_message=""):
    """Mark an API deprecated since ``since``; point users at
    ``instead``."""

    def decorator(func):
        err_msg = (f"API {func.__name__} is deprecated since {since}. "
                   f"Please use {instead} instead.")
        if extra_message:
            full_msg = err_msg + "\n" + extra_message
        else:
            full_msg = err_msg

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(full_msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__doc__ = (err_msg + "\n\n" + (func.__doc__ or ""))
        return wrapper

    return decorator
