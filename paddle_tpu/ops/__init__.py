"""Importing this package registers every op lowering rule."""
from . import array_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import ctc_crf_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import math_ops  # noqa: F401
from . import metric_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
