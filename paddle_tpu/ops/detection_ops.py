"""Detection op subset: prior_box, box_coder, iou_similarity,
multiclass_nms, bipartite_match.

Reference: ``paddle/fluid/operators/detection/`` (prior_box_op.cc,
box_coder_op.cc, iou_similarity_op.cc, multiclass_nms_op.cc,
bipartite_match_op.cc) — the SSD inference path.

TPU-native redesign: the reference's dynamically-sized outputs (NMS keeps
a variable box count per image) become fixed-capacity padded outputs with
an explicit count — NMS runs as a fixed-iteration suppression scan on
device instead of the reference's host-side std::sort loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


@register("prior_box", no_grad_slots=("Input", "Image"))
def _prior_box(ctx, ins, attrs):
    """SSD anchor generation (prior_box_op.cc): per feature-map cell, one
    box per (min_size, aspect_ratio) + optional max_size boxes.  Outputs
    Boxes [H, W, P, 4] (normalized xmin,ymin,xmax,ymax) and Variances."""
    feat = ins["Input"][0]
    image = ins["Image"][0]
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if attrs.get("flip", True):
                ars.append(1.0 / ar)
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / W
    step_h = float(attrs.get("step_h", 0.0)) or img_h / H
    offset = float(attrs.get("offset", 0.5))
    clip = attrs.get("clip", True)

    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError("prior_box: max_sizes must pair 1:1 with min_sizes")
    whs = []
    for i, ms in enumerate(min_sizes):
        for ar in ars:
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        if max_sizes:  # one sqrt(min_i * max_i) box per pair (SSD recipe)
            xs = max_sizes[i]
            whs.append(((ms * xs) ** 0.5, (ms * xs) ** 0.5))
    P = len(whs)
    wh = jnp.asarray(whs, jnp.float32)                     # [P, 2]
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                        # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    half_w = wh[None, None, :, 0] / 2
    half_h = wh[None, None, :, 1] / 2
    boxes = jnp.stack([(cxg - half_w) / img_w, (cyg - half_h) / img_h,
                       (cxg + half_w) / img_w, (cyg + half_h) / img_h],
                      axis=-1)                             # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    return {"Boxes": [boxes], "Variances": [var]}


def _corner_to_center(b):
    w = b[..., 2] - b[..., 0]
    h = b[..., 3] - b[..., 1]
    return b[..., 0] + w / 2, b[..., 1] + h / 2, w, h


@register("box_coder", no_grad_slots=("PriorBox", "PriorBoxVar"))
def _box_coder(ctx, ins, attrs):
    """Encode targets against priors / decode offsets back to boxes
    (box_coder_op.cc).  PriorBox [M,4], TargetBox [N,M,4] (decode) or
    [N,4] (encode); variances broadcast."""
    prior = ins["PriorBox"][0].astype(jnp.float32)
    target = ins["TargetBox"][0].astype(jnp.float32)
    pv = (ins["PriorBoxVar"][0].astype(jnp.float32)
          if ins.get("PriorBoxVar") else jnp.ones_like(prior))
    code_type = attrs.get("code_type", "encode_center_size")
    pcx, pcy, pw, ph = _corner_to_center(prior)
    if "encode" in code_type:
        tcx, tcy, tw, th = _corner_to_center(target)
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / pv[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / pv[None, :, 1],
            jnp.log(tw[:, None] / pw[None, :]) / pv[None, :, 2],
            jnp.log(th[:, None] / ph[None, :]) / pv[None, :, 3],
        ], axis=-1)                                        # [N, M, 4]
    else:
        d = target                                        # [N, M, 4]
        cx = pv[None, :, 0] * d[..., 0] * pw[None, :] + pcx[None, :]
        cy = pv[None, :, 1] * d[..., 1] * ph[None, :] + pcy[None, :]
        w = jnp.exp(pv[None, :, 2] * d[..., 2]) * pw[None, :]
        h = jnp.exp(pv[None, :, 3] * d[..., 3]) * ph[None, :]
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                        axis=-1)
    return {"OutputBox": [out]}


def _iou_matrix(a, b):
    """IoU of [N,4] x [M,4] corner boxes → [N,M]."""
    ax1, ay1, ax2, ay2 = (a[:, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[:, i] for i in range(4))
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0.0)
    area_b = jnp.maximum((bx2 - bx1) * (by2 - by1), 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("iou_similarity", no_grad_slots=("X", "Y"))
def _iou_similarity(ctx, ins, attrs):
    return {"Out": [_iou_matrix(ins["X"][0].astype(jnp.float32),
                                ins["Y"][0].astype(jnp.float32))]}


@register("bipartite_match", no_grad_slots=("DistMat",))
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly take
    the globally largest entry, retire its row+column.  DistMat [N, M]
    (rows: ground truth, cols: priors) → per-column matched row id (−1 if
    none) + matched distance."""
    dist = ins["DistMat"][0].astype(jnp.float32)
    n, m = dist.shape
    iters = min(n, m)

    def step(carry, _):
        d, row_ids, match_d = carry
        flat = jnp.argmax(d)
        r, c = flat // m, flat % m
        best = d[r, c]
        take = best > 0
        row_ids = jnp.where(take, row_ids.at[c].set(r.astype(jnp.int32)),
                            row_ids)
        match_d = jnp.where(take, match_d.at[c].set(best), match_d)
        d = jnp.where(take, d.at[r, :].set(-1.0).at[:, c].set(-1.0), d)
        return (d, row_ids, match_d), None

    init = (dist, jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), jnp.float32))
    (_, row_ids, match_d), _ = lax.scan(step, init, None, length=iters)
    if attrs.get("match_type", "") == "per_prediction":
        thr = float(attrs.get("dist_threshold", 0.5))
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        unmatched = row_ids < 0
        fill = (best_val >= thr) & unmatched
        row_ids = jnp.where(fill, best_row, row_ids)
        match_d = jnp.where(fill, best_val, match_d)
    return {"ColToRowMatchIndices": [row_ids[None, :]],
            "ColToRowMatchDist": [match_d[None, :]]}


@register("multiclass_nms", no_grad_slots=("BBoxes", "Scores"))
def _multiclass_nms(ctx, ins, attrs):
    """Padded multiclass NMS (multiclass_nms_op.cc): per class, iterative
    greedy suppression for ``nms_top_k`` slots; survivors across classes
    re-ranked to ``keep_top_k``.  Outputs Out [B, keep, 6] =
    (label, score, x1, y1, x2, y2) with -1 labels padding, and the valid
    count per image."""
    bboxes = ins["BBoxes"][0].astype(jnp.float32)   # [B, M, 4]
    scores = ins["Scores"][0].astype(jnp.float32)   # [B, C, M]
    B, C, M = scores.shape
    score_thr = float(attrs.get("score_threshold", 0.0))
    nms_thr = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = min(int(attrs.get("nms_top_k", 64)), M)
    keep_top_k = int(attrs.get("keep_top_k", 100))
    bg = int(attrs.get("background_label", 0))

    def per_class(boxes, cls_scores):
        """[M,4],[M] → padded (scores, idx) of nms_top_k survivors."""
        top_s, top_i = lax.top_k(cls_scores, nms_top_k)
        top_b = boxes[top_i]
        iou = _iou_matrix(top_b, top_b)

        def body(keep, i):
            # keep candidate i only if not suppressed by a kept earlier box
            sup = jnp.any(keep & (jnp.arange(nms_top_k) < i)
                          & (iou[i] > nms_thr))
            ok = (top_s[i] > score_thr) & ~sup
            return keep.at[i].set(ok), None

        keep0 = jnp.zeros((nms_top_k,), bool)
        keep, _ = lax.scan(body, keep0, jnp.arange(nms_top_k))
        return jnp.where(keep, top_s, -1.0), top_i

    if all(c == bg for c in range(C)):
        raise ValueError("multiclass_nms: no non-background class "
                         f"(C={C}, background_label={bg})")

    def per_image(boxes, img_scores):
        all_s, all_i, all_c = [], [], []
        for c in range(C):
            if c == bg:
                continue
            s, i = per_class(boxes, img_scores[c])
            all_s.append(s)
            all_i.append(i)
            all_c.append(jnp.full((nms_top_k,), c, jnp.float32))
        cat_s = jnp.concatenate(all_s)
        cat_i = jnp.concatenate(all_i)
        cat_c = jnp.concatenate(all_c)
        k = min(keep_top_k, cat_s.shape[0])
        fin_s, order = lax.top_k(cat_s, k)
        fin_i = cat_i[order]
        fin_c = cat_c[order]
        fin_b = boxes[fin_i]
        valid = fin_s > 0
        out = jnp.concatenate(
            [jnp.where(valid, fin_c, -1.0)[:, None], fin_s[:, None], fin_b],
            axis=1)
        if k < keep_top_k:  # pad to the declared [keep_top_k, 6] shape
            pad = jnp.full((keep_top_k - k, 6), -1.0, out.dtype)
            out = jnp.concatenate([out, pad], axis=0)
        return out, jnp.sum(valid).astype(jnp.int64)

    outs, counts = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [outs], "NmsRoisNum": [counts]}
