"""Detection op subset: prior_box, box_coder, iou_similarity,
multiclass_nms, bipartite_match.

Reference: ``paddle/fluid/operators/detection/`` (prior_box_op.cc,
box_coder_op.cc, iou_similarity_op.cc, multiclass_nms_op.cc,
bipartite_match_op.cc) — the SSD inference path.

TPU-native redesign: the reference's dynamically-sized outputs (NMS keeps
a variable box count per image) become fixed-capacity padded outputs with
an explicit count — NMS runs as a fixed-iteration suppression scan on
device instead of the reference's host-side std::sort loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register


@register("prior_box", no_grad_slots=("Input", "Image"))
def _prior_box(ctx, ins, attrs):
    """SSD anchor generation (prior_box_op.cc): per feature-map cell, one
    box per (min_size, aspect_ratio) + optional max_size boxes.  Outputs
    Boxes [H, W, P, 4] (normalized xmin,ymin,xmax,ymax) and Variances."""
    feat = ins["Input"][0]
    image = ins["Image"][0]
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if attrs.get("flip", True):
                ars.append(1.0 / ar)
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / W
    step_h = float(attrs.get("step_h", 0.0)) or img_h / H
    offset = float(attrs.get("offset", 0.5))
    clip = attrs.get("clip", True)

    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError("prior_box: max_sizes must pair 1:1 with min_sizes")
    whs = []
    for i, ms in enumerate(min_sizes):
        for ar in ars:
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        if max_sizes:  # one sqrt(min_i * max_i) box per pair (SSD recipe)
            xs = max_sizes[i]
            whs.append(((ms * xs) ** 0.5, (ms * xs) ** 0.5))
    P = len(whs)
    wh = jnp.asarray(whs, jnp.float32)                     # [P, 2]
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                        # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    half_w = wh[None, None, :, 0] / 2
    half_h = wh[None, None, :, 1] / 2
    boxes = jnp.stack([(cxg - half_w) / img_w, (cyg - half_h) / img_h,
                       (cxg + half_w) / img_w, (cyg + half_h) / img_h],
                      axis=-1)                             # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    return {"Boxes": [boxes], "Variances": [var]}


def _corner_to_center(b):
    w = b[..., 2] - b[..., 0]
    h = b[..., 3] - b[..., 1]
    return b[..., 0] + w / 2, b[..., 1] + h / 2, w, h


@register("box_coder", no_grad_slots=("PriorBox", "PriorBoxVar"))
def _box_coder(ctx, ins, attrs):
    """Encode targets against priors / decode offsets back to boxes
    (box_coder_op.cc).  PriorBox [M,4], TargetBox [N,M,4] (decode) or
    [N,4] (encode); variances broadcast."""
    prior = ins["PriorBox"][0].astype(jnp.float32)
    target = ins["TargetBox"][0].astype(jnp.float32)
    pv = (ins["PriorBoxVar"][0].astype(jnp.float32)
          if ins.get("PriorBoxVar") else jnp.ones_like(prior))
    code_type = attrs.get("code_type", "encode_center_size")
    pcx, pcy, pw, ph = _corner_to_center(prior)
    if "encode" in code_type:
        tcx, tcy, tw, th = _corner_to_center(target)
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / pv[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / pv[None, :, 1],
            jnp.log(tw[:, None] / pw[None, :]) / pv[None, :, 2],
            jnp.log(th[:, None] / ph[None, :]) / pv[None, :, 3],
        ], axis=-1)                                        # [N, M, 4]
    else:
        d = target                                        # [N, M, 4]
        cx = pv[None, :, 0] * d[..., 0] * pw[None, :] + pcx[None, :]
        cy = pv[None, :, 1] * d[..., 1] * ph[None, :] + pcy[None, :]
        w = jnp.exp(pv[None, :, 2] * d[..., 2]) * pw[None, :]
        h = jnp.exp(pv[None, :, 3] * d[..., 3]) * ph[None, :]
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                        axis=-1)
    return {"OutputBox": [out]}


def _iou_matrix(a, b):
    """IoU of [N,4] x [M,4] corner boxes → [N,M]."""
    ax1, ay1, ax2, ay2 = (a[:, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[:, i] for i in range(4))
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0.0)
    area_b = jnp.maximum((bx2 - bx1) * (by2 - by1), 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("iou_similarity", no_grad_slots=("X", "Y"))
def _iou_similarity(ctx, ins, attrs):
    return {"Out": [_iou_matrix(ins["X"][0].astype(jnp.float32),
                                ins["Y"][0].astype(jnp.float32))]}


@register("bipartite_match", no_grad_slots=("DistMat",))
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly take
    the globally largest entry, retire its row+column.  DistMat [N, M]
    (rows: ground truth, cols: priors) → per-column matched row id (−1 if
    none) + matched distance."""
    dist = ins["DistMat"][0].astype(jnp.float32)
    n, m = dist.shape
    iters = min(n, m)

    def step(carry, _):
        d, row_ids, match_d = carry
        flat = jnp.argmax(d)
        r, c = flat // m, flat % m
        best = d[r, c]
        take = best > 0
        row_ids = jnp.where(take, row_ids.at[c].set(r.astype(jnp.int32)),
                            row_ids)
        match_d = jnp.where(take, match_d.at[c].set(best), match_d)
        d = jnp.where(take, d.at[r, :].set(-1.0).at[:, c].set(-1.0), d)
        return (d, row_ids, match_d), None

    init = (dist, jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), jnp.float32))
    (_, row_ids, match_d), _ = lax.scan(step, init, None, length=iters)
    if attrs.get("match_type", "") == "per_prediction":
        thr = float(attrs.get("dist_threshold", 0.5))
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        unmatched = row_ids < 0
        fill = (best_val >= thr) & unmatched
        row_ids = jnp.where(fill, best_row, row_ids)
        match_d = jnp.where(fill, best_val, match_d)
    return {"ColToRowMatchIndices": [row_ids[None, :]],
            "ColToRowMatchDist": [match_d[None, :]]}


@register("multiclass_nms", no_grad_slots=("BBoxes", "Scores"))
def _multiclass_nms(ctx, ins, attrs):
    """Padded multiclass NMS (multiclass_nms_op.cc): per class, iterative
    greedy suppression for ``nms_top_k`` slots; survivors across classes
    re-ranked to ``keep_top_k``.  Outputs Out [B, keep, 6] =
    (label, score, x1, y1, x2, y2) with -1 labels padding, and the valid
    count per image."""
    bboxes = ins["BBoxes"][0].astype(jnp.float32)   # [B, M, 4]
    scores = ins["Scores"][0].astype(jnp.float32)   # [B, C, M]
    B, C, M = scores.shape
    score_thr = float(attrs.get("score_threshold", 0.0))
    nms_thr = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = min(int(attrs.get("nms_top_k", 64)), M)
    keep_top_k = int(attrs.get("keep_top_k", 100))
    bg = int(attrs.get("background_label", 0))

    def per_class(boxes, cls_scores):
        """[M,4],[M] → padded (scores, idx) of nms_top_k survivors."""
        top_s, top_i = lax.top_k(cls_scores, nms_top_k)
        top_b = boxes[top_i]
        iou = _iou_matrix(top_b, top_b)

        def body(keep, i):
            # keep candidate i only if not suppressed by a kept earlier box
            sup = jnp.any(keep & (jnp.arange(nms_top_k) < i)
                          & (iou[i] > nms_thr))
            ok = (top_s[i] > score_thr) & ~sup
            return keep.at[i].set(ok), None

        keep0 = jnp.zeros((nms_top_k,), bool)
        keep, _ = lax.scan(body, keep0, jnp.arange(nms_top_k))
        return jnp.where(keep, top_s, -1.0), top_i

    if all(c == bg for c in range(C)):
        raise ValueError("multiclass_nms: no non-background class "
                         f"(C={C}, background_label={bg})")

    def per_image(boxes, img_scores):
        all_s, all_i, all_c = [], [], []
        for c in range(C):
            if c == bg:
                continue
            s, i = per_class(boxes, img_scores[c])
            all_s.append(s)
            all_i.append(i)
            all_c.append(jnp.full((nms_top_k,), c, jnp.float32))
        cat_s = jnp.concatenate(all_s)
        cat_i = jnp.concatenate(all_i)
        cat_c = jnp.concatenate(all_c)
        k = min(keep_top_k, cat_s.shape[0])
        fin_s, order = lax.top_k(cat_s, k)
        fin_i = cat_i[order]
        fin_c = cat_c[order]
        fin_b = boxes[fin_i]
        valid = fin_s > 0
        out = jnp.concatenate(
            [jnp.where(valid, fin_c, -1.0)[:, None], fin_s[:, None], fin_b],
            axis=1)
        if k < keep_top_k:  # pad to the declared [keep_top_k, 6] shape
            pad = jnp.full((keep_top_k - k, 6), -1.0, out.dtype)
            out = jnp.concatenate([out, pad], axis=0)
        return out, jnp.sum(valid).astype(jnp.int64)

    outs, counts = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [outs], "NmsRoisNum": [counts]}


# ---------------------------------------------------------------------------
# detection tail: anchors, target assignment, hard-example mining, RPN,
# polygon transform (anchor_generator_op.cc, target_assign_op.cc,
# mine_hard_examples_op.cc, rpn_target_assign_op.cc,
# polygon_box_transform_op.cc)
# ---------------------------------------------------------------------------

@register("anchor_generator", no_grad_slots=("Input",))
def _anchor_generator(ctx, ins, attrs):
    """Faster-RCNN anchors (anchor_generator_op.h): per cell, one anchor
    per (aspect_ratio, anchor_size); boxes centered on the stride grid."""
    x = ins["Input"][0]  # [N, C, H, W]
    H, W = x.shape[2], x.shape[3]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    stride = [float(s) for s in attrs["stride"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))
    sw, sh = stride[0], stride[1]

    x_ctr = jnp.arange(W, dtype=jnp.float32) * sw + offset * (sw - 1)
    y_ctr = jnp.arange(H, dtype=jnp.float32) * sh + offset * (sh - 1)
    dims = []
    for ar in ratios:
        area = sw * sh
        base_w = round(float(np.sqrt(area / ar)))
        base_h = round(float(base_w * ar))
        for size in sizes:
            dims.append((size / sw * base_w, size / sh * base_h))
    wh = jnp.asarray(dims, jnp.float32)  # [A, 2]
    A = wh.shape[0]
    xc = jnp.broadcast_to(x_ctr[None, :, None], (H, W, A))
    yc = jnp.broadcast_to(y_ctr[:, None, None], (H, W, A))
    aw = jnp.broadcast_to(wh[None, None, :, 0], (H, W, A))
    ah = jnp.broadcast_to(wh[None, None, :, 1], (H, W, A))
    anchors = jnp.stack([xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                         xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1)], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (H, W, A, 4))
    return {"Anchors": [anchors], "Variances": [var]}


@register("polygon_box_transform", no_grad_slots=())
def _polygon_box_transform(ctx, ins, attrs):
    """polygon_box_transform_op.cc: even channels x-offsets -> 4*w - in,
    odd channels y-offsets -> 4*h - in (EAST geometry decode)."""
    x = ins["Input"][0]
    n, c, h, w = x.shape
    wgrid = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4
    hgrid = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4
    even = (jnp.arange(c) % 2 == 0).reshape(1, c, 1, 1)
    return {"Output": [jnp.where(even, wgrid - x, hgrid - x)]}


@register("target_assign",
          no_grad_slots=("MatchIndices", "NegIndices", "XLen", "NegLen"))
def _target_assign(ctx, ins, attrs):
    """target_assign_op.cc on the padded contract: X [B, M, K] per-image
    gt entities, MatchIndices [B, P] (-1 = background).  Out[b, p] =
    X[b, MatchIndices[b, p]] (weight 1) or mismatch_value (weight 0);
    rows listed in NegIndices get weight 1 back."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0].astype(jnp.int32)  # [B, P]
    mismatch = attrs.get("mismatch_value", 0)
    B, P = match.shape
    K = x.shape[-1]
    safe = jnp.maximum(match, 0)
    gathered = jnp.take_along_axis(x, safe[..., None], axis=1)
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch, x.dtype))
    weight = matched.astype(jnp.float32)
    if ins.get("NegIndices"):
        neg = ins["NegIndices"][0].reshape(B, -1).astype(jnp.int32)  # [B, Nn]
        if ins.get("NegLen"):
            nl = ins["NegLen"][0].reshape(B, 1)
            nvalid = jnp.arange(neg.shape[1])[None, :] < nl
        else:
            nvalid = neg >= 0
        wflat = weight[..., 0]
        wflat = wflat.at[
            jnp.broadcast_to(jnp.arange(B)[:, None], neg.shape),
            jnp.maximum(neg, 0),
        ].max(jnp.where(nvalid, 1.0, 0.0))
        weight = wflat[..., None]
    return {"Out": [out], "OutWeight": [weight]}


@register("mine_hard_examples",
          no_grad_slots=("ClsLoss", "LocLoss", "MatchIndices", "MatchDist"))
def _mine_hard_examples(ctx, ins, attrs):
    """mine_hard_examples_op.cc (max_negative mode): per image, pick the
    top-loss negative anchors, capped at neg_pos_ratio * #positives.
    Outputs NegIndices [B, Mn] padded with -1 + UpdatedMatchIndices."""
    cls_loss = ins["ClsLoss"][0]
    loc_loss = ins["LocLoss"][0] if ins.get("LocLoss") else None
    match = ins["MatchIndices"][0].astype(jnp.int32)  # [B, P]
    dist = ins["MatchDist"][0]
    ratio = float(attrs.get("neg_pos_ratio", 1.0))
    thr = float(attrs.get("neg_dist_threshold", 0.5))
    loss = cls_loss if loc_loss is None else cls_loss + loc_loss
    loss = loss.reshape(match.shape)
    B, P = match.shape

    eligible = (match == -1) & (dist.reshape(B, P) < thr)
    masked_loss = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked_loss, axis=1)  # desc by loss
    npos = jnp.sum((match >= 0), axis=1, keepdims=True)
    quota = jnp.minimum((npos * ratio).astype(jnp.int32),
                        jnp.sum(eligible, axis=1, keepdims=True))
    take = jnp.arange(P)[None, :] < quota
    neg_idx = jnp.where(take, order, -1)
    # negatives keep match -1; everything is already -1 there
    return {"NegIndices": [neg_idx.astype(jnp.int64)],
            "UpdatedMatchIndices": [match.astype(jnp.int32)]}


@register("rpn_target_assign",
          no_grad_slots=("DistMat", "Anchor", "GtBox"))
def _rpn_target_assign(ctx, ins, attrs):
    """rpn_target_assign_op.cc (simplified deterministic variant): per
    image, anchors with IoU > pos_threshold (plus the best anchor per gt)
    are positives, IoU < neg_threshold negatives; returns padded index
    lists + target labels.  The reference subsamples randomly to
    rpn_batch_size_per_im; the TPU redesign keeps the deterministic
    top-loss ordering (fixed shapes) and caps at the same budget."""
    dist = ins["DistMat"][0]  # [M anchors, G gt] IoU
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    M, G = dist.shape
    best_gt = jnp.argmax(dist, axis=1)            # [M]
    best_iou = jnp.max(dist, axis=1)
    best_anchor = jnp.argmax(dist, axis=0)        # [G]
    is_best = jnp.zeros((M,), bool).at[best_anchor].set(True)
    pos = (best_iou >= pos_thr) | is_best
    neg = (best_iou < neg_thr) & ~pos

    fg_cap = int(batch * fg_frac)
    pos_order = jnp.argsort(-jnp.where(pos, best_iou, -jnp.inf))
    pos_take = jnp.arange(M) < jnp.minimum(jnp.sum(pos), fg_cap)
    loc_idx = jnp.where(pos_take, pos_order, -1)[:fg_cap]
    neg_order = jnp.argsort(-jnp.where(neg, 1.0 - best_iou, -jnp.inf))
    neg_cap = batch - fg_cap
    neg_take = jnp.arange(M) < jnp.minimum(jnp.sum(neg), neg_cap)
    neg_idx = jnp.where(neg_take, neg_order, -1)[:neg_cap]
    score_idx = jnp.concatenate([loc_idx, neg_idx])
    tgt_lbl = jnp.concatenate([
        jnp.where(loc_idx >= 0, 1, -1), jnp.where(neg_idx >= 0, 0, -1)])
    return {"LocationIndex": [loc_idx.astype(jnp.int64)],
            "ScoreIndex": [score_idx.astype(jnp.int64)],
            "TargetLabel": [tgt_lbl.astype(jnp.int64)],
            "TargetAnchorGt": [best_gt.astype(jnp.int64)]}


@register("ssd_loss",
          no_grad_slots=("GtBox", "GtLabel", "GtLen", "PriorBox",
                         "PriorBoxVar"))
def _ssd_loss(ctx, ins, attrs):
    """Fused SSD multibox loss (the 5-step algorithm of the reference's
    layers/detection.py ssd_loss composition, detection/*_op.cc kernels):
    match -> confidence loss -> max_negative hard mining -> target
    assignment -> weighted smooth-L1 + softmax-xent.  One XLA region
    instead of the reference's 14-op graph — same math on padded
    [B, Mg, ...] ground truth with a GtLen mask.
    Output Loss [B, P]."""
    loc = ins["Loc"][0].astype(jnp.float32)        # [B, P, 4]
    conf = ins["Conf"][0].astype(jnp.float32)      # [B, P, C]
    gt_box = ins["GtBox"][0].astype(jnp.float32)   # [B, Mg, 4]
    gt_label = ins["GtLabel"][0].reshape(gt_box.shape[0], -1)  # [B, Mg]
    prior = ins["PriorBox"][0].astype(jnp.float32)  # [P, 4]
    pvar = (ins["PriorBoxVar"][0].astype(jnp.float32)
            if ins.get("PriorBoxVar") else None)
    gt_len = (ins["GtLen"][0] if ins.get("GtLen")
              else jnp.full((gt_box.shape[0],), gt_box.shape[1]))
    bg = int(attrs.get("background_label", 0))
    overlap_thr = float(attrs.get("overlap_threshold", 0.5))
    neg_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    neg_overlap = float(attrs.get("neg_overlap", 0.5))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    normalize = bool(attrs.get("normalize", True))
    B, P, C = conf.shape
    Mg = gt_box.shape[1]

    def encode(gt):  # box_coder encode_center_size against priors
        pw = prior[:, 2] - prior[:, 0] + 1.0
        ph = prior[:, 3] - prior[:, 1] + 1.0
        px = prior[:, 0] + pw * 0.5
        py = prior[:, 1] + ph * 0.5
        gw = gt[..., 2] - gt[..., 0] + 1.0
        gh = gt[..., 3] - gt[..., 1] + 1.0
        gx = gt[..., 0] + gw * 0.5
        gy = gt[..., 1] + gh * 0.5
        t = jnp.stack([(gx - px) / pw, (gy - py) / ph,
                       jnp.log(jnp.maximum(gw / pw, 1e-8)),
                       jnp.log(jnp.maximum(gh / ph, 1e-8))], axis=-1)
        if pvar is not None:
            t = t / pvar
        return t

    def per_image(loc_i, conf_i, gt_i, lab_i, n_gt):
        valid_gt = jnp.arange(Mg) < n_gt
        iou = _iou_matrix(gt_i, prior)             # [Mg, P]
        iou = jnp.where(valid_gt[:, None], iou, -1.0)
        # per-prediction match + bipartite guarantee for each gt
        best_gt = jnp.argmax(iou, axis=0)          # [P]
        best_iou = jnp.max(iou, axis=0)
        match = jnp.where(best_iou > overlap_thr, best_gt, -1)
        best_prior = jnp.argmax(iou, axis=1)       # [Mg]
        match = match.at[best_prior].set(
            jnp.where(valid_gt, jnp.arange(Mg), match[best_prior]))
        pos = match >= 0

        safe = jnp.maximum(match, 0)
        tgt_label = jnp.where(pos, lab_i[safe].astype(jnp.int32), bg)
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        conf_loss = -jnp.take_along_axis(
            logp, tgt_label[:, None], axis=1)[:, 0]  # [P]

        # max_negative mining
        eligible = (~pos) & (best_iou < neg_overlap)
        npos = jnp.sum(pos)
        quota = jnp.minimum((npos * neg_ratio).astype(jnp.int32),
                            jnp.sum(eligible))
        order = jnp.argsort(-jnp.where(eligible, conf_loss, -jnp.inf))
        neg_sel = jnp.zeros((P,), bool).at[order].set(
            jnp.arange(P) < quota)
        neg_sel = neg_sel & eligible

        tgt_box = encode(gt_i[safe])               # [P, 4]
        d = loc_i - tgt_box
        ad = jnp.abs(d)
        sl1 = jnp.sum(jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5), axis=-1)
        loss = (conf_w * conf_loss * (pos | neg_sel)
                + loc_w * sl1 * pos)
        if normalize:
            loss = loss / jnp.maximum(npos.astype(jnp.float32), 1.0)
        return loss

    loss = jax.vmap(per_image)(loc, conf, gt_box, gt_label,
                               gt_len.astype(jnp.int32))
    return {"Loss": [loss]}


@register("detection_map",
          no_grad_slots=("DetectRes", "Label", "GtLen", "PosCount",
                         "TruePos", "FalsePos"))
def _detection_map(ctx, ins, attrs):
    """detection_map_op.cc as an IN-GRAPH device op (padded redesign).

    DetectRes [B, K, 6] = (label, score, x1, y1, x2, y2), label -1 = pad;
    Label [B, Mg, 6] = (label, x1, y1, x2, y2, difficult) with GtLen [B].

    Matching is the reference's greedy rule vectorized on device: per
    image, detections in descending-score order claim their best-IoU
    unmatched ground truth of the same class (IoU >= overlap_threshold).

    Accumulative state redesign: the reference keeps dynamic per-class
    score lists (LoD state); static shapes use score-BUCKETED histograms
    instead — TruePos/FalsePos [C, BINS] counts per score bin (BINS=1000
    over [0,1]) + PosCount [C].  AP from the bin-cumulative curves is the
    same integral/11point formula with <=1/BINS recall-ordering error.
    """
    det = ins["DetectRes"][0]                 # [B,K,6]
    gt = ins["Label"][0]                      # [B,Mg,6]
    B, K, _ = det.shape
    Mg = gt.shape[1]
    C = int(attrs["class_num"])
    bg = int(attrs.get("background_label", 0))
    thr = float(attrs.get("overlap_threshold", 0.5))
    eval_diff = bool(attrs.get("evaluate_difficult", True))
    version = attrs.get("ap_version", "integral")
    BINS = 1000
    gt_len = (ins["GtLen"][0] if ins.get("GtLen")
              else jnp.full((B,), Mg, jnp.int32))

    d_label = det[..., 0].astype(jnp.int32)           # [B,K]
    d_score = jnp.clip(det[..., 1].astype(jnp.float32), 0.0, 1.0)
    d_box = det[..., 2:6].astype(jnp.float32)
    g_label = gt[..., 0].astype(jnp.int32)            # [B,Mg]
    g_box = gt[..., 1:5].astype(jnp.float32)
    g_diff = gt[..., 5] > 0
    g_valid = (jnp.arange(Mg)[None, :] < gt_len[:, None].astype(jnp.int32))
    g_counted = g_valid & (eval_diff | ~g_diff)       # enters PosCount
    d_valid = d_label >= 0

    # IoU [B,K,Mg]
    lt = jnp.maximum(d_box[:, :, None, :2], g_box[:, None, :, :2])
    rb = jnp.minimum(d_box[:, :, None, 2:], g_box[:, None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_d = ((d_box[..., 2] - d_box[..., 0])
              * (d_box[..., 3] - d_box[..., 1]))[:, :, None]
    area_g = ((g_box[..., 2] - g_box[..., 0])
              * (g_box[..., 3] - g_box[..., 1]))[:, None, :]
    union = jnp.maximum(area_d + area_g - inter, 1e-12)
    iou = inter / union
    can_match = (iou >= thr) & g_valid[:, None, :] \
        & (d_label[:, :, None] == g_label[:, None, :])

    order = jnp.argsort(-d_score, axis=1)             # [B,K] score-desc

    def match_image(order_b, can_b, iou_b, dval_b):
        def step(used, k):
            cand = can_b[k] & ~used                  # [Mg]
            best = jnp.argmax(jnp.where(cand, iou_b[k], -1.0))
            hit = cand[best] & dval_b[k]
            used = used.at[best].set(used[best] | hit)
            return used, hit
        _, hits = lax.scan(step, jnp.zeros((Mg,), bool), order_b)
        # hits are in score order; return to detection order
        return jnp.zeros((K,), bool).at[order_b].set(hits)

    is_tp = jax.vmap(match_image)(order,
                                  can_match, iou, d_valid)   # [B,K]

    # bucket detections into [C, BINS] histograms
    bins = jnp.minimum((d_score * BINS).astype(jnp.int32), BINS - 1)
    flat_cls = jnp.clip(d_label.reshape(-1), 0, C - 1)
    flat_idx = flat_cls * BINS + bins.reshape(-1)
    w = d_valid.reshape(-1).astype(jnp.float32)
    tp_new = jnp.zeros((C * BINS,), jnp.float32).at[flat_idx].add(
        w * is_tp.reshape(-1)).reshape(C, BINS)
    fp_new = jnp.zeros((C * BINS,), jnp.float32).at[flat_idx].add(
        w * (~is_tp.reshape(-1).astype(bool)).astype(jnp.float32)
    ).reshape(C, BINS)
    pos_new = jnp.zeros((C,), jnp.float32).at[
        jnp.clip(g_label.reshape(-1), 0, C - 1)].add(
        g_counted.reshape(-1).astype(jnp.float32))

    if ins.get("PosCount"):
        pos_new = pos_new + ins["PosCount"][0]
        tp_new = tp_new + ins["TruePos"][0]
        fp_new = fp_new + ins["FalsePos"][0]

    # AP per class from descending-score bin cumsums
    tp_cum = jnp.cumsum(tp_new[:, ::-1], axis=1)       # [C,BINS] desc
    fp_cum = jnp.cumsum(fp_new[:, ::-1], axis=1)
    npos = jnp.maximum(pos_new, 1e-12)
    rec = tp_cum / npos[:, None]
    prec = tp_cum / jnp.maximum(tp_cum + fp_cum, 1e-12)
    if version == "11point":
        ts = jnp.arange(11, dtype=jnp.float32) / 10.0   # [11]
        pmax = jnp.max(jnp.where(rec[:, None, :] >= ts[None, :, None],
                                 prec[:, None, :], 0.0), axis=2)
        ap = jnp.sum(pmax, axis=1) / 11.0
    else:
        prev_rec = jnp.concatenate(
            [jnp.zeros((C, 1)), rec[:, :-1]], axis=1)
        ap = jnp.sum(prec * (rec - prev_rec), axis=1)
    cls_mask = (pos_new > 0) & (jnp.arange(C) != bg)
    n_cls = jnp.maximum(jnp.sum(cls_mask.astype(jnp.float32)), 1.0)
    m = jnp.sum(jnp.where(cls_mask, ap, 0.0)) / n_cls
    return {"MAP": [m.reshape((1,))],
            "AccumPosCount": [pos_new],
            "AccumTruePos": [tp_new],
            "AccumFalsePos": [fp_new]}


@register("generate_proposals",
          no_grad_slots=("Scores", "BboxDeltas", "ImInfo", "Anchors",
                         "Variances"))
def _generate_proposals(ctx, ins, attrs):
    """generate_proposals_op.cc (RPN): per image, top pre_nms_top_n
    anchors by objectness, deltas decoded, clipped to the image, tiny
    boxes masked, greedy NMS to post_nms_top_n.  Fixed-capacity padded
    outputs: RpnRois [N, post_n, 4], RpnRoiProbs [N, post_n, 1],
    RpnRoisNum [N]."""
    scores = ins["Scores"][0].astype(jnp.float32)       # [N, A, H, W]
    deltas = ins["BboxDeltas"][0].astype(jnp.float32)   # [N, 4A, H, W]
    im_info = ins["ImInfo"][0].astype(jnp.float32)      # [N, 3]
    anchors = ins["Anchors"][0].astype(jnp.float32).reshape(-1, 4)
    variances = ins["Variances"][0].astype(jnp.float32).reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thr = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))
    N = scores.shape[0]
    A, H, W = scores.shape[1], scores.shape[2], scores.shape[3]
    M = A * H * W
    pre_n = min(pre_n, M)
    post_n = min(post_n, pre_n)

    if anchors.shape[0] != M:
        raise ValueError(
            f"generate_proposals: anchors hold {anchors.shape[0]} boxes "
            f"but Scores imply A*H*W = {M}")

    def per_image(sc, dl, info):
        s = sc.transpose(1, 2, 0).reshape(-1)           # [H*W*A]
        d = dl.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        top_s, top_i = lax.top_k(s, pre_n)
        a = anchors[top_i]
        v = variances[top_i]
        t = d[top_i]
        # decode (generate_proposals_op.cc:99-133: +1 widths, corners at
        # center +/- w/2 with the -1 pixel offset on the far corner)
        aw = a[:, 2] - a[:, 0] + 1.0
        ah = a[:, 3] - a[:, 1] + 1.0
        ax = a[:, 0] + aw * 0.5
        ay = a[:, 1] + ah * 0.5
        cx = v[:, 0] * t[:, 0] * aw + ax
        cy = v[:, 1] * t[:, 1] * ah + ay
        w = jnp.exp(jnp.minimum(v[:, 2] * t[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(v[:, 3] * t[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2 - 1.0, cy + h / 2 - 1.0], axis=1)
        # clip to image
        hmax, wmax = info[0] - 1.0, info[1] - 1.0
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, wmax), jnp.clip(boxes[:, 1], 0, hmax),
            jnp.clip(boxes[:, 2], 0, wmax), jnp.clip(boxes[:, 3], 0, hmax),
        ], axis=1)
        # filter small
        ms = min_size * info[2]
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1 >= ms)
                   & (boxes[:, 3] - boxes[:, 1] + 1 >= ms))
        cand_s = jnp.where(keep_sz, top_s, -jnp.inf)
        # pixel-convention IoU (JaccardOverlap normalized=false: +1 on
        # widths) — _iou_matrix is the normalized variant
        wi = (jnp.maximum(0.0,
                          jnp.minimum(boxes[:, None, 2], boxes[None, :, 2])
                          - jnp.maximum(boxes[:, None, 0], boxes[None, :, 0])
                          + 1.0))
        hi = (jnp.maximum(0.0,
                          jnp.minimum(boxes[:, None, 3], boxes[None, :, 3])
                          - jnp.maximum(boxes[:, None, 1], boxes[None, :, 1])
                          + 1.0))
        inter = wi * hi
        area = ((boxes[:, 2] - boxes[:, 0] + 1.0)
                * (boxes[:, 3] - boxes[:, 1] + 1.0))
        iou = inter / (area[:, None] + area[None, :] - inter)

        def body(keep, i):
            sup = jnp.any(keep & (jnp.arange(pre_n) < i) & (iou[i] > nms_thr))
            ok = jnp.isfinite(cand_s[i]) & ~sup
            return keep.at[i].set(ok), None

        keep, _ = lax.scan(body, jnp.zeros((pre_n,), bool),
                           jnp.arange(pre_n))
        sel_s = jnp.where(keep, cand_s, -jnp.inf)
        fin_s, order = lax.top_k(sel_s, post_n)
        fin_b = boxes[order]
        valid = jnp.isfinite(fin_s)
        return (jnp.where(valid[:, None], fin_b, 0.0),
                jnp.where(valid, fin_s, 0.0)[:, None],
                jnp.sum(valid).astype(jnp.int64))

    rois, probs, counts = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs],
            "RpnRoisNum": [counts]}
