"""Control-flow lowering: sub-block ops → functional XLA control flow.

Reference: ``paddle/fluid/operators/while_op.cc:36`` (step-scope executor
loop), ``recurrent_op.cc:222`` (StaticRNN with StepScopes),
``conditional_block_op.cc``.  The reference runs an Executor over a
sub-block per iteration, mutating step scopes; under XLA this becomes
``lax.while_loop`` / ``lax.scan`` / ``lax.cond`` with the carried state
explicit — listed in the op's ``carry_vars`` attr (computed by the layer
from the sub-block's writes).  Grad-of-scan is the reverse scan jax derives
(the functional equivalent of while_grad's reversed step-scope walk,
while_op.cc:101).

These handlers get *name-level* env access (unlike regular lowering rules)
because carries are program variables; ``core/lowering.py`` dispatches
``CONTROL_FLOW_OPS`` here.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..core.program import EMPTY_VAR
from ..core.registry import register


def _never(ctx, ins, attrs):  # pragma: no cover
    raise RuntimeError("control-flow ops lower via CONTROL_FLOW_OPS dispatch")


# registry entries let append_backward build grad op descs generically
register("static_rnn", no_grad_slots=())(_never)


def lower_while(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """while op: attrs sub_block (idx), carry_vars (names, first is the
    condition var).  Repeats the sub-block until the condition var, which
    the block must reassign, is false."""
    sub = program.blocks[op.attr("sub_block")]
    cond_name = op.input("Condition")[0]
    carry_names = [n for n in op.attr("carry_vars") if n != cond_name]

    def cond_fn(carry):
        return carry[0].reshape(()).astype(jnp.bool_)

    def body_fn(carry):
        benv = dict(env)
        benv[cond_name] = carry[0]
        benv.update(zip(carry_names, carry[1:]))
        lower_block_ops(ctx, program, sub, benv)
        return (benv[cond_name],) + tuple(benv[n] for n in carry_names)

    init = (env[cond_name],) + tuple(env[n] for n in carry_names)
    res = lax.while_loop(cond_fn, body_fn, init)
    env[cond_name] = res[0]
    env.update(zip(carry_names, res[1:]))


def lower_conditional_block(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """conditional_block: run sub-block iff the scalar condition is true;
    carried vars keep their prior values otherwise (both branches traced —
    lax.cond semantics)."""
    sub = program.blocks[op.attr("sub_block")]
    cond = env[op.input("Condition")[0]].reshape(()).astype(jnp.bool_)
    carry_names = list(op.attr("carry_vars"))
    # vars created inside the block need an initial value for the false
    # branch: zeros shaped like the true branch's result
    def true_branch(carry):
        benv = dict(env)
        benv.update(zip(carry_names, carry))
        lower_block_ops(ctx, program, sub, benv)
        return tuple(benv[n] for n in carry_names)

    def false_branch(carry):
        return tuple(carry)

    init = []
    for n in carry_names:
        if n in env:
            init.append(env[n])
        else:
            raise RuntimeError(
                f"conditional_block carry {n!r} has no prior value; "
                f"initialize it before the block (layers.fill_constant)")
    res = lax.cond(cond, true_branch, false_branch, tuple(init))
    env.update(zip(carry_names, res))


def lower_static_rnn(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """static_rnn op (recurrent_op.cc:222 redesigned as lax.scan).

    attrs: sub_block, step_inputs (outer [B,T,...] names), step_input_vars
    (inner per-step names), memories [(inner_mem_name, init_name,
    updated_inner_name)], step_outputs [(inner_name, outer_name)].
    """
    sub = program.blocks[op.attr("sub_block")]
    step_in_outer = op.attr("step_inputs")
    step_in_inner = op.attr("step_input_vars")
    memories = op.attr("memories")  # list of [mem, init, updated]
    step_outputs = op.attr("step_outputs")  # list of [inner, outer]

    xs = tuple(jnp.swapaxes(env[n], 0, 1) for n in step_in_outer)  # [T,B,...]
    init = tuple(env[init_n] for _, init_n, _ in memories)

    def body(carry, x_t):
        benv = dict(env)
        for (mem, _, _), c in zip(memories, carry):
            benv[mem] = c
        for name, v in zip(step_in_inner, x_t):
            benv[name] = v
        lower_block_ops(ctx, program, sub, benv)
        new_carry = tuple(benv[upd] for _, _, upd in memories)
        outs = tuple(benv[inner] for inner, _ in step_outputs)
        return new_carry, outs

    last_carry, stacked = lax.scan(body, init, xs)
    for (inner, outer), seq in zip(step_outputs, stacked):
        env[outer] = jnp.swapaxes(seq, 0, 1)  # back to [B,T,...]
    for (mem, _, _), c in zip(memories, last_carry):
        env[mem + "@LAST"] = c


CONTROL_FLOW_OPS = {
    "while": lower_while,
    "conditional_block": lower_conditional_block,
    "static_rnn": lower_static_rnn,
}


def lower_static_rnn_grad(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """Grad of static_rnn: jax.vjp over the scan lowering (reverse scan —
    the functional form of recurrent_op.cc's backward step-scope walk).
    Differentiates wrt outer step inputs, memory inits, and captured vars."""
    diff_slots = ("X", "Init", "Captured")
    diff_names = []
    for slot in diff_slots:
        for n in op.input(slot):
            if n and n in env and jnp.issubdtype(jnp.asarray(env[n]).dtype, jnp.inexact):
                diff_names.append(n)
    outer_outs = [outer for _, outer in op.attr("step_outputs")]

    def f(vals: Dict):
        benv = dict(env)
        benv.update(vals)
        lower_static_rnn(ctx, program, op, benv, lower_block_ops)
        return {n: benv[n] for n in outer_outs}

    primals, vjp_fn = jax.vjp(f, {n: env[n] for n in diff_names})
    cot = {}
    grad_names = dict(zip(op.input("Out"), op.input("Out@GRAD")))
    for n in outer_outs:
        gname = grad_names.get(n)
        g = env.get(gname) if gname and gname != EMPTY_VAR else None
        cot[n] = g if g is not None else jnp.zeros_like(primals[n])
    (grads,) = vjp_fn(cot)
    for slot in diff_slots:
        out_names = op.output(slot + "@GRAD")
        for src, dst in zip(op.input(slot), out_names):
            if dst and dst != EMPTY_VAR and src in grads:
                env[dst] = grads[src]


CONTROL_FLOW_OPS["static_rnn_grad"] = lower_static_rnn_grad
