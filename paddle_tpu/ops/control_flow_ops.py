"""Control-flow lowering: sub-block ops → functional XLA control flow.

Reference: ``paddle/fluid/operators/while_op.cc:36`` (step-scope executor
loop), ``recurrent_op.cc:222`` (StaticRNN with StepScopes),
``conditional_block_op.cc``.  The reference runs an Executor over a
sub-block per iteration, mutating step scopes; under XLA this becomes
``lax.while_loop`` / ``lax.scan`` / ``lax.cond`` with the carried state
explicit — listed in the op's ``carry_vars`` attr (computed by the layer
from the sub-block's writes).  Grad-of-scan is the reverse scan jax derives
(the functional equivalent of while_grad's reversed step-scope walk,
while_op.cc:101).

These handlers get *name-level* env access (unlike regular lowering rules)
because carries are program variables; ``core/lowering.py`` dispatches
``CONTROL_FLOW_OPS`` here.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..core.program import EMPTY_VAR
from ..core.registry import register


def _never(ctx, ins, attrs):  # pragma: no cover
    raise RuntimeError("control-flow ops lower via CONTROL_FLOW_OPS dispatch")


# registry entries let append_backward build grad op descs generically
register("static_rnn", no_grad_slots=())(_never)
register("dynamic_rnn", no_grad_slots=("SeqLen",))(_never)
register("while", no_grad_slots=("Condition", "Init"))(_never)
register("conditional_block", no_grad_slots=("Condition", "Init"))(_never)


def _carry_inits(op, env) -> Dict:
    """Pre-op carry values from the explicit @INIT snapshot vars the layer
    emitted (while_op.cc:56 step-scope capture as program state; survives
    host-op segmentation, unlike a trace-local stash)."""
    carried = op.attr("carry_vars")
    init_names = op.input("Init")
    assert len(init_names) == len(carried), (
        f"Init snapshot count {len(init_names)} != carries {len(carried)} "
        f"for {op.type}")
    return {n: env[i] for n, i in zip(carried, init_names)}


def lower_while(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """while op: attrs sub_block (idx), carry_vars (names, first is the
    condition var).  Repeats the sub-block until the condition var, which
    the block must reassign, is false.  With ``max_iters`` set the loop
    lowers as a bounded masked scan — identical iteration semantics, and
    exactly the computation the grad lowering differentiates (exceeding
    the bound then truncates forward AND backward consistently, loudly
    visible in the loss rather than silently only in the grads)."""
    sub = program.blocks[op.attr("sub_block")]
    cond_name = op.input("Condition")[0]
    carry_names = [n for n in op.attr("carry_vars") if n != cond_name]

    if op.attr("max_iters") and ctx.training:
        # training: the same masked scan the grad differentiates (fwd/bwd
        # truncate together at the bound); inference keeps lax.while_loop
        # and exits early instead of paying max_iters masked iterations
        inits = {n: env[n] for n in [cond_name] + carry_names}
        out = _while_as_masked_scan(ctx, program, op, env, lower_block_ops,
                                    inits, {})
        env.update(out)
        return

    # max_iters stays a hard cap at inference too (early exit, but never
    # more than the bound — matching the training masked scan)
    max_iters = int(op.attr("max_iters", 0) or 0)

    def cond_fn(carry):
        alive = carry[0].reshape(()).astype(jnp.bool_)
        if max_iters:
            alive = jnp.logical_and(alive, carry[1] < max_iters)
        return alive

    def body_fn(carry):
        benv = dict(env)
        benv[cond_name] = carry[0]
        benv.update(zip(carry_names, carry[2:]))
        lower_block_ops(ctx, program, sub, benv)
        return ((benv[cond_name], carry[1] + 1)
                + tuple(benv[n] for n in carry_names))

    init = ((env[cond_name], jnp.zeros((), jnp.int32))
            + tuple(env[n] for n in carry_names))
    res = lax.while_loop(cond_fn, body_fn, init)
    env[cond_name] = res[0]
    env.update(zip(carry_names, res[2:]))


def _while_as_masked_scan(ctx, program, op, env: Dict, lower_block_ops,
                          inits: Dict, overrides: Dict):
    """Differentiable forward of a bounded while: a ``max_iters``-step scan
    whose iterations after the condition turns false are select-no-ops.
    The reverse scan jax derives from this is the functional equivalent of
    while_grad's reversed step-scope walk (while_op.cc:101-263)."""
    sub = program.blocks[op.attr("sub_block")]
    cond_name = op.input("Condition")[0]
    carry_names = [n for n in op.attr("carry_vars") if n != cond_name]
    max_iters = int(op.attr("max_iters"))

    def body(carry, _):
        cond, state = carry[0], carry[1:]
        benv = dict(env)
        benv.update(overrides)
        benv[cond_name] = cond
        benv.update(zip(carry_names, state))
        lower_block_ops(ctx, program, sub, benv)
        active = cond.reshape(()).astype(jnp.bool_)
        new_state = tuple(
            jnp.where(active, benv[n].astype(jnp.result_type(old)), old)
            for n, old in zip(carry_names, state))
        new_cond = jnp.where(active, benv[cond_name], cond)
        return (new_cond,) + new_state, None

    init = (inits[cond_name],) + tuple(inits[n] for n in carry_names)
    final, _ = lax.scan(body, init, None, length=max_iters)
    out = dict(zip([cond_name] + carry_names, final))
    return out


def _is_float_val(v):
    return jnp.issubdtype(jnp.result_type(v), jnp.inexact)


def _subblock_vjp(op, env, inits, fwd, diff_carries, diff_capt) -> None:
    """Shared grad plumbing for while/conditional_block: vjp of ``fwd``
    over {diff carries (init values) + diff captured (env values)},
    cotangents from the Out@GRAD slots, results written to the X@GRAD /
    Captured@GRAD slots."""
    primal_in = {**{n: inits[n] for n in diff_carries},
                 **{n: env[n] for n in diff_capt}}
    primals, vjp_fn = jax.vjp(fwd, primal_in)
    grad_of = dict(zip(op.input("Out"), op.input("Out@GRAD")))
    cot = {}
    for n in diff_carries:
        gname = grad_of.get(n)
        g = env.get(gname) if gname and gname != EMPTY_VAR else None
        cot[n] = g if g is not None else jnp.zeros_like(primals[n])
    (grads,) = vjp_fn(cot)
    for slot in ("X", "Captured"):
        for src, dst in zip(op.input(slot), op.output(slot + "@GRAD")):
            if dst and dst != EMPTY_VAR and src in grads:
                env[dst] = grads[src]


def lower_while_grad(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """Grad of a bounded while: vjp over the masked-scan forward, from the
    @INIT-snapshot carry values.  Differentiates wrt float carries and
    captured outer vars (while_op.cc:101 while_grad)."""
    if not op.attr("max_iters"):
        raise NotImplementedError(
            "gradient of While requires max_iters (a static trip-count "
            "bound): While(cond, max_iters=N)")
    cond_name = op.input("Condition")[0]
    carry_names = [n for n in op.attr("carry_vars") if n != cond_name]
    captured = [n for n in op.attr("captured_vars", ()) or ()]
    inits = _carry_inits(op, env)

    diff_carries = [n for n in carry_names if _is_float_val(inits[n])]
    diff_capt = [n for n in captured if n in env and _is_float_val(env[n])]

    def fwd(vals: Dict):
        full_inits = dict(inits)
        full_inits.update({n: vals[n] for n in diff_carries})
        overrides = {n: vals[n] for n in diff_capt}
        out = _while_as_masked_scan(ctx, program, op, env, lower_block_ops,
                                    full_inits, overrides)
        return {n: out[n] for n in diff_carries}

    _subblock_vjp(op, env, inits, fwd, diff_carries, diff_capt)


def lower_conditional_block(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """conditional_block: run sub-block iff the scalar condition is true;
    carried vars keep their prior values otherwise (both branches traced —
    lax.cond semantics)."""
    sub = program.blocks[op.attr("sub_block")]
    cond = env[op.input("Condition")[0]].reshape(()).astype(jnp.bool_)
    carry_names = list(op.attr("carry_vars"))
    # vars created inside the block need an initial value for the false
    # branch: zeros shaped like the true branch's result
    def true_branch(carry):
        benv = dict(env)
        benv.update(zip(carry_names, carry))
        lower_block_ops(ctx, program, sub, benv)
        return tuple(benv[n] for n in carry_names)

    def false_branch(carry):
        return tuple(carry)

    init = []
    for n in carry_names:
        if n in env:
            init.append(env[n])
        else:
            raise RuntimeError(
                f"conditional_block carry {n!r} has no prior value; "
                f"initialize it before the block (layers.fill_constant)")
    res = lax.cond(cond, true_branch, false_branch, tuple(init))
    env.update(zip(carry_names, res))


def lower_conditional_block_grad(ctx, program, op, env: Dict,
                                 lower_block_ops) -> None:
    """Grad of conditional_block: vjp through lax.cond from the
    @INIT-snapshot carry values; differentiates wrt float carries and
    captured outer vars (conditional_block_op.cc grad)."""
    sub = program.blocks[op.attr("sub_block")]
    cond = env[op.input("Condition")[0]].reshape(()).astype(jnp.bool_)
    carry_names = list(op.attr("carry_vars"))
    captured = [n for n in op.attr("captured_vars", ()) or ()]
    inits = _carry_inits(op, env)

    diff_carries = [n for n in carry_names if _is_float_val(inits[n])]
    diff_capt = [n for n in captured if n in env and _is_float_val(env[n])]

    def fwd(vals: Dict):
        def true_branch(v):
            benv = dict(env)
            benv.update(inits)
            benv.update(v)
            lower_block_ops(ctx, program, sub, benv)
            return {n: benv[n] for n in diff_carries}

        def false_branch(v):
            out = dict(inits)
            out.update({n: v[n] for n in diff_carries})
            return {n: out[n] for n in diff_carries}

        return lax.cond(cond, true_branch, false_branch, vals)

    _subblock_vjp(op, env, inits, fwd, diff_carries, diff_capt)


def lower_static_rnn(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """static_rnn op (recurrent_op.cc:222 redesigned as lax.scan).

    attrs: sub_block, step_inputs (outer [B,T,...] names), step_input_vars
    (inner per-step names), memories [(inner_mem_name, init_name,
    updated_inner_name)], step_outputs [(inner_name, outer_name)].
    """
    sub = program.blocks[op.attr("sub_block")]
    step_in_outer = op.attr("step_inputs")
    step_in_inner = op.attr("step_input_vars")
    memories = op.attr("memories")  # list of [mem, init, updated]
    step_outputs = op.attr("step_outputs")  # list of [inner, outer]
    # dynamic_rnn: per-row sequence lengths mask memory updates + outputs
    # (the scan translation of the reference's rank-table batch shrinking,
    # layers/control_flow.py:1541 DynamicRNN / lod_rank_table)
    seq_len = env[op.input("SeqLen")[0]] if op.input("SeqLen") else None

    xs = tuple(jnp.swapaxes(env[n], 0, 1) for n in step_in_outer)  # [T,B,...]
    init = tuple(env[init_n] for _, init_n, _ in memories)
    t_steps = xs[0].shape[0] if xs else int(op.attr("max_len", 0))

    def mask_to(active, new, old):
        m = active.reshape(active.shape + (1,) * (new.ndim - active.ndim))
        return jnp.where(m, new, old)

    def body(carry, tx):
        t, x_t = tx
        benv = dict(env)
        for (mem, _, _), c in zip(memories, carry):
            benv[mem] = c
        for name, v in zip(step_in_inner, x_t):
            benv[name] = v
        lower_block_ops(ctx, program, sub, benv)
        if seq_len is None:
            new_carry = tuple(benv[upd] for _, _, upd in memories)
            outs = tuple(benv[inner] for inner, _ in step_outputs)
        else:
            active = t < seq_len.reshape(-1).astype(t.dtype)  # [B]
            new_carry = tuple(
                mask_to(active, benv[upd], c)
                for (_, _, upd), c in zip(memories, carry))
            outs = tuple(
                mask_to(active, benv[inner], jnp.zeros_like(benv[inner]))
                for inner, _ in step_outputs)
        return new_carry, outs

    ts = jnp.arange(t_steps)
    last_carry, stacked = lax.scan(body, init, (ts, xs))
    for (inner, outer), seq in zip(step_outputs, stacked):
        env[outer] = jnp.swapaxes(seq, 0, 1)  # back to [B,T,...]
    for (mem, _, _), c in zip(memories, last_carry):
        env[mem + "@LAST"] = c


CONTROL_FLOW_OPS = {
    "while": lower_while,
    "while_grad": lower_while_grad,
    "conditional_block": lower_conditional_block,
    "conditional_block_grad": lower_conditional_block_grad,
    "static_rnn": lower_static_rnn,
    "dynamic_rnn": lower_static_rnn,
}


def lower_static_rnn_grad(ctx, program, op, env: Dict, lower_block_ops) -> None:
    """Grad of static_rnn: jax.vjp over the scan lowering (reverse scan —
    the functional form of recurrent_op.cc's backward step-scope walk).
    Differentiates wrt outer step inputs, memory inits, and captured vars."""
    diff_slots = ("X", "Init", "Captured")
    diff_names = []
    for slot in diff_slots:
        for n in op.input(slot):
            if n and n in env and jnp.issubdtype(jnp.asarray(env[n]).dtype, jnp.inexact):
                diff_names.append(n)
    outer_outs = [outer for _, outer in op.attr("step_outputs")]

    def f(vals: Dict):
        benv = dict(env)
        benv.update(vals)
        lower_static_rnn(ctx, program, op, benv, lower_block_ops)
        return {n: benv[n] for n in outer_outs}

    primals, vjp_fn = jax.vjp(f, {n: env[n] for n in diff_names})
    cot = {}
    grad_names = dict(zip(op.input("Out"), op.input("Out@GRAD")))
    for n in outer_outs:
        gname = grad_names.get(n)
        g = env.get(gname) if gname and gname != EMPTY_VAR else None
        cot[n] = g if g is not None else jnp.zeros_like(primals[n])
    (grads,) = vjp_fn(cot)
    for slot in diff_slots:
        out_names = op.output(slot + "@GRAD")
        for src, dst in zip(op.input(slot), out_names):
            if dst and dst != EMPTY_VAR and src in grads:
                env[dst] = grads[src]


CONTROL_FLOW_OPS["static_rnn_grad"] = lower_static_rnn_grad
CONTROL_FLOW_OPS["dynamic_rnn_grad"] = lower_static_rnn_grad
