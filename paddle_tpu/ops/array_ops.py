"""TensorArray + beam-search ops: the dynamic-decode toolkit.

Reference: ``paddle/fluid/operators/tensor_array_read_write_op.cc``,
``beam_search_op.cc``, ``beam_search_decode_op.cc`` and the
LoDTensorArray type (``framework/lod_tensor_array.h``).

TPU-native redesign: a TensorArray is a *preallocated* ``[max_len, ...]``
tensor plus an int64 length scalar (XLA wants static shapes; the
reference's grow-on-write vector of LoDTensors cannot trace).  Reads and
writes are dynamic-index gathers/scatters — differentiable, so the same
machinery backs while-grad.  Beam search works on the padded
``[batch*beam, ...]`` layout (the LoD-free translation of the reference's
per-source candidate lists), with finished beams persisting via an extra
stay-finished candidate slot.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.registry import register


@register("array_write", no_grad_slots=("I", "ArrayLen"))
def _array_write(ctx, ins, attrs):
    """array[i] = x; length = max(length, i+1)
    (tensor_array_read_write_op.cc WriteToArray)."""
    arr, x = ins["Array"][0], ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    length = ins["ArrayLen"][0]
    new_len = jnp.maximum(length.reshape(()),
                          (i + 1).astype(length.dtype)).reshape(length.shape)
    return {"Out": [arr.at[i].set(x.astype(arr.dtype))],
            "LenOut": [new_len]}


@register("array_read", no_grad_slots=("I",))
def _array_read(ctx, ins, attrs):
    """out = array[i] (ReadFromArray)."""
    arr = ins["Array"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    return {"Out": [arr[i]]}


@register("beam_search", no_grad_slots=("PreIds", "PreScores", "Ids", "Scores"))
def _beam_search(ctx, ins, attrs):
    """One beam-search step (beam_search_op.cc, LoD-free layout).

    Inputs (BW = batch * beam_size):
      PreIds     [BW, 1] int64 — last selected token per beam
      PreScores  [BW, 1] — accumulated log-prob per beam
      Ids        [BW, K] int64 — candidate tokens (e.g. per-beam top-K)
      Scores     [BW, K] — accumulated scores of those candidates
    A finished beam (PreIds == end_id) contributes one stay-finished
    candidate (end_id at its frozen score) instead of its K expansions —
    the reference's pruning of ended hypotheses.  Step 0 convention: seed
    PreScores with 0 for beam 0 and -inf for the rest of each group so
    identical initial beams don't multiply (kInf trick).

    Outputs: SelectedIds [BW, 1], SelectedScores [BW, 1],
             ParentIdx [BW] int64 (global source-beam index per selection).
    """
    beam = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    pre_ids = ins["PreIds"][0].reshape(-1)
    pre_scores = ins["PreScores"][0].reshape(-1)
    ids = ins["Ids"][0]
    scores = ins["Scores"][0]
    bw, k = scores.shape
    assert bw % beam == 0, f"batch*beam {bw} not divisible by beam {beam}"
    b = bw // beam
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)

    finished = pre_ids == end_id
    live_scores = jnp.where(finished[:, None], neg_inf, scores)
    stay = jnp.where(finished, pre_scores, neg_inf)[:, None]
    cand_scores = jnp.concatenate([live_scores, stay], axis=1)     # [BW,K+1]
    cand_ids = jnp.concatenate(
        [ids, jnp.full((bw, 1), end_id, ids.dtype)], axis=1)

    grouped = cand_scores.reshape(b, beam * (k + 1))
    top_scores, top_idx = lax.top_k(grouped, beam)                 # [B,beam]
    parent_local = top_idx // (k + 1)
    parent = (parent_local
              + (jnp.arange(b, dtype=top_idx.dtype) * beam)[:, None])
    sel_ids = jnp.take_along_axis(cand_ids.reshape(b, -1), top_idx, axis=1)
    return {
        "SelectedIds": [sel_ids.reshape(bw, 1).astype(jnp.int64)],
        "SelectedScores": [top_scores.reshape(bw, 1)],
        "ParentIdx": [parent.reshape(bw).astype(jnp.int64)],
    }


@register("beam_search_decode",
          no_grad_slots=("Ids", "Parents", "Scores", "ArrayLen"))
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack stacked per-step selections into the reference's LEVEL-2
    nested result (beam_search_decode_op.cc emits a 2-level LoD:
    source -> candidates -> tokens; framework/lod_tensor.h:58).

    Ids, Parents: [T_max, BW] (TensorArray data); optional Scores
    [T_max, BW] (per-step selected scores); ArrayLen: written steps.
    Walks parent pointers from the last written step back to step 0;
    steps beyond ArrayLen are padded with end_id.

    Padded level-2 encoding (values + two length vectors):
    - SentenceIds    [BW, T_max] int64 — flat token values
    - SentenceScores [BW, T_max]       — scores along the same backtrack
    - SentenceLen    [BW]  int64 — tokens per candidate (up to and
      including the first end_id; T_max when the beam never finished)
    - SourceLen      [B]   int64 — candidates per source sentence
      (beam_size under the padded contract: unlike the reference's
      pruned candidate lists, every beam slot is materialized and
      SentenceLen tells which suffix is padding)
    """
    ids = ins["Ids"][0]          # [T, BW]
    parents = ins["Parents"][0]  # [T, BW]
    t_max, bw = ids.shape
    end_id = int(attrs["end_id"])
    beam_size = int(attrs.get("beam_size", 1))
    length = ins["ArrayLen"][0].reshape(()).astype(jnp.int32) \
        if ins.get("ArrayLen") else jnp.asarray(t_max, jnp.int32)
    scores = ins["Scores"][0] if ins.get("Scores") else None

    def step(cur, tp):
        t, ids_t, par_t, sc_t = tp
        active = t < length
        tok = jnp.where(active, ids_t[cur], jnp.asarray(end_id, ids.dtype))
        sc = jnp.where(active, sc_t[cur], 0.0)
        nxt = jnp.where(active, par_t[cur], cur)
        return nxt, (tok, sc)

    sc_arr = (scores if scores is not None
              else jnp.zeros((t_max, bw), jnp.float32))
    ts = jnp.arange(t_max - 1, -1, -1)
    _, (toks, scs) = lax.scan(
        step, jnp.arange(bw), (ts, ids[::-1], parents[::-1], sc_arr[::-1]))
    sent_ids = toks[::-1].T.astype(jnp.int64)        # [BW, T]
    sent_scores = scs[::-1].T

    is_end = sent_ids == end_id
    has_end = jnp.any(is_end, axis=1)
    first_end = jnp.argmax(is_end, axis=1)
    cand_len = jnp.where(has_end, first_end + 1, t_max).astype(jnp.int64)
    # steps beyond ArrayLen were end_id-padded; cap at the written length
    cand_len = jnp.minimum(cand_len, length.astype(jnp.int64))
    src_len = jnp.full((bw // beam_size,), beam_size, jnp.int64)
    out = {"SentenceIds": [sent_ids],
           "SentenceLen": [cand_len],
           "SourceLen": [src_len]}
    if scores is not None:
        out["SentenceScores"] = [sent_scores]
    return out


# ---------------------------------------------------------------------------
# LoD rank-table machinery (lod_rank_table_op.cc, lod_tensor_to_array_op.cc,
# array_to_lod_tensor_op.cc, shrink_rnn_memory_op.cc,
# reorder_lod_tensor_by_rank_op.cc, max_sequence_len_op.cc,
# split_lod_tensor / merge_lod_tensor) redesigned for the padded contract.
# A rank table is two [B] vectors: stable argsort of sequence indices by
# descending length, and the lengths in that order.
# ---------------------------------------------------------------------------

@register("lod_rank_table", no_grad_slots=("SeqLen",))
def _lod_rank_table(ctx, ins, attrs):
    seq_len = ins["SeqLen"][0].astype(jnp.int64)
    # jnp.argsort is stable: ties keep original order (reference
    # lod_rank_table_op.cc uses stable_sort on (index, length))
    order = jnp.argsort(-seq_len).astype(jnp.int64)
    return {"RankIdx": [order], "RankLen": [seq_len[order]]}


@register("max_sequence_len", no_grad_slots=("RankLen",))
def _max_sequence_len(ctx, ins, attrs):
    return {"Out": [ins["RankLen"][0][:1]]}


@register("reorder_lod_tensor_by_rank", no_grad_slots=("RankIdx", "SeqLen"))
def _reorder_by_rank(ctx, ins, attrs):
    x = ins["X"][0]
    idx = ins["RankIdx"][0].astype(jnp.int32)
    out = {"Out": [x[idx]]}
    if ins.get("SeqLen"):
        out["OutLen"] = [ins["SeqLen"][0][idx]]
    return out


@register("lod_tensor_to_array", no_grad_slots=("RankIdx",))
def _lod_tensor_to_array(ctx, ins, attrs):
    """[B, T, ...] -> TensorArray [T, B, ...] with sequences in rank
    order.  The reference shrinks the batch per step (sequences shorter
    than t drop out); the padded redesign keeps the full batch and relies
    on shrink_rnn_memory-style masking — same math, static shapes."""
    x = ins["X"][0]
    idx = ins["RankIdx"][0].astype(jnp.int32)
    arr = jnp.swapaxes(x[idx], 0, 1)
    T = arr.shape[0]
    return {"Out": [arr], "LenOut": [jnp.full((1,), T, jnp.int64)]}


@register("array_to_lod_tensor", no_grad_slots=("RankIdx", "RankLen"))
def _array_to_lod_tensor(ctx, ins, attrs):
    arr = ins["X"][0]
    idx = ins["RankIdx"][0].astype(jnp.int32)
    x = jnp.swapaxes(arr, 0, 1)  # [B, T, ...] still in rank order
    inv = jnp.zeros_like(idx).at[idx].set(
        jnp.arange(idx.shape[0], dtype=idx.dtype))
    out = {"Out": [x[inv]]}
    if ins.get("RankLen"):
        # Restore lengths to original sequence order so downstream ops
        # mask with the right per-row length (reference restores the
        # original LoD exactly, array_to_lod_tensor_op.cc).
        out["OutLen"] = [ins["RankLen"][0][inv]]
    return out


@register("shrink_rnn_memory", no_grad_slots=("I", "RankLen"))
def _shrink_rnn_memory(ctx, ins, attrs):
    """shrink_rnn_memory_op.cc: at step i, keep memory rows of sequences
    still active (rank-ordered rows 0..n_active).  Static-shape version:
    zero the inactive tail instead of slicing it off — downstream masked
    RNN math is unchanged, XLA keeps one shape."""
    x = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int64)
    rank_len = ins["RankLen"][0]
    active = jnp.sum((rank_len > i).astype(jnp.int32))
    keep = jnp.arange(x.shape[0]) < active
    return {"Out": [jnp.where(keep.reshape((-1,) + (1,) * (x.ndim - 1)),
                              x, 0).astype(x.dtype)]}


@register("split_lod_tensor", no_grad_slots=("Mask",))
def _split_lod_tensor(ctx, ins, attrs):
    """split_lod_tensor_op.cc: route rows by boolean mask.  Static-shape
    redesign: both outputs keep the full batch with non-selected rows
    zeroed; merge_lod_tensor recombines exactly (the IfElse contract)."""
    x = ins["X"][0]
    mask = ins["Mask"][0].reshape(-1).astype(bool)
    m = mask.reshape((-1,) + (1,) * (x.ndim - 1))
    zero = jnp.zeros((), x.dtype)
    return {"OutTrue": [jnp.where(m, x, zero)],
            "OutFalse": [jnp.where(m, zero, x)]}


@register("merge_lod_tensor", no_grad_slots=("Mask",))
def _merge_lod_tensor(ctx, ins, attrs):
    """merge_lod_tensor_op.cc: out[i] = in_true[i] if mask[i] else
    in_false[i] (exact inverse of the masked split)."""
    t, f = ins["InTrue"][0], ins["InFalse"][0]
    mask = ins["Mask"][0].reshape(-1).astype(bool)
    m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
    return {"Out": [jnp.where(m, t, f)]}


@register("lod_array_length", no_grad_slots=("ArrayLen",))
def _lod_array_length(ctx, ins, attrs):
    """lod_array_length_op.cc: written-slot count of a TensorArray."""
    return {"Out": [ins["ArrayLen"][0].reshape(1).astype(jnp.int64)]}
