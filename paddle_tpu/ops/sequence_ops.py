"""Sequence op lowerings over the padded [B,T,...] + lengths contract.

Reference coverage: the LoD sequence op family
(``paddle/fluid/operators/sequence_*`` ~25 ops + ``math/sequence_pooling``,
``math/sequence2batch``).  The reference packs ragged sequences with LoD
offsets; here sequences are padded dense tensors with an explicit length
vector, so these ops lower to masked reductions / gathers that XLA fuses —
no scatter-heavy batch⇄sequence reordering needed on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register


def _mask(x, seq_len):
    """[B,T] validity mask broadcastable to x [B,T,...]."""
    B, T = x.shape[0], x.shape[1]
    m = jnp.arange(T)[None, :] < seq_len[:, None]
    return m.reshape((B, T) + (1,) * (x.ndim - 2))


@register("sequence_pool", no_grad_slots=("SeqLen",))
def _sequence_pool(ctx, ins, attrs):
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    if seq_len is None:
        m = jnp.ones(x.shape[:2] + (1,) * (x.ndim - 2), x.dtype)
        lens = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    else:
        m = _mask(x, seq_len).astype(x.dtype)
        lens = seq_len
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        denom = lens.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype)
        out = jnp.sum(x * m, axis=1) / jnp.maximum(denom, 1)
    elif ptype == "SQRT":
        denom = jnp.sqrt(lens.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype))
        out = jnp.sum(x * m, axis=1) / jnp.maximum(denom, 1)
    elif ptype == "MAX":
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, x.dtype)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": [out]}


@register("sequence_softmax", no_grad_slots=("SeqLen",))
def _sequence_softmax(ctx, ins, attrs):
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    if seq_len is None:
        return {"Out": [jax.nn.softmax(x, axis=1)]}
    m = _mask(x, seq_len)
    neg = jnp.asarray(-1e9, jnp.float32)
    logits = jnp.where(m, x.astype(jnp.float32), neg)
    out = jax.nn.softmax(logits, axis=1) * m.astype(jnp.float32)
    return {"Out": [out.astype(x.dtype)]}


@register("sequence_expand", no_grad_slots=("SeqLen",))
def _sequence_expand(ctx, ins, attrs):
    # expand [B, D] (or [B,1,D]) to [B, T, D] following Y's layout
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == y.ndim:
        return {"Out": [jnp.broadcast_to(x, y.shape[:2] + x.shape[2:])]}
    return {"Out": [jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:])]}


@register("sequence_reverse", no_grad_slots=("SeqLen",))
def _sequence_reverse(ctx, ins, attrs):
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    T = x.shape[1]
    if seq_len is None:
        return {"Out": [jnp.flip(x, axis=1)]}
    # per-row reversal of the valid prefix: index (len-1-t) mod T for t<len
    t = jnp.arange(T)[None, :]
    idx = jnp.where(t < seq_len[:, None], seq_len[:, None] - 1 - t, t)
    out = jnp.take_along_axis(x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)
    return {"Out": [out]}


@register("sequence_concat", no_grad_slots=("SeqLen",))
def _sequence_concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=1)]}


@register("sequence_first_step", no_grad_slots=("SeqLen",))
def _sequence_first_step(ctx, ins, attrs):
    return {"Out": [ins["X"][0][:, 0]]}


@register("sequence_last_step", no_grad_slots=("SeqLen",))
def _sequence_last_step(ctx, ins, attrs):
    return _sequence_pool(ctx, ins, {"pooltype": "LAST"})
