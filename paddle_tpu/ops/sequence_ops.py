"""Sequence op lowerings over the padded [B,T,...] + lengths contract.

Reference coverage: the LoD sequence op family
(``paddle/fluid/operators/sequence_*`` ~25 ops + ``math/sequence_pooling``,
``math/sequence2batch``).  The reference packs ragged sequences with LoD
offsets; here sequences are padded dense tensors with an explicit length
vector, so these ops lower to masked reductions / gathers that XLA fuses —
no scatter-heavy batch⇄sequence reordering needed on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register


def _mask(x, seq_len):
    """[B,T] validity mask broadcastable to x [B,T,...]."""
    B, T = x.shape[0], x.shape[1]
    m = jnp.arange(T)[None, :] < seq_len[:, None]
    return m.reshape((B, T) + (1,) * (x.ndim - 2))


def left_compact(ids, keep):
    """Stable left-compaction of kept [B,T] entries: kept values move to
    the front preserving order, with the new per-row count (shared by
    sequence_erase and ctc_align)."""
    T = ids.shape[1]
    order = jnp.argsort(jnp.where(keep, 0, 1) * T + jnp.arange(T)[None, :],
                        axis=1)
    compacted = jnp.take_along_axis(ids, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int64)
    return compacted, new_len


@register("sequence_pool", no_grad_slots=("SeqLen",))
def _sequence_pool(ctx, ins, attrs):
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    if seq_len is None:
        m = jnp.ones(x.shape[:2] + (1,) * (x.ndim - 2), x.dtype)
        lens = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    else:
        m = _mask(x, seq_len).astype(x.dtype)
        lens = seq_len
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        denom = lens.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype)
        out = jnp.sum(x * m, axis=1) / jnp.maximum(denom, 1)
    elif ptype == "SQRT":
        denom = jnp.sqrt(lens.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype))
        out = jnp.sum(x * m, axis=1) / jnp.maximum(denom, 1)
    elif ptype == "MAX":
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, x.dtype)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    if ptype in ("MAX", "LAST", "FIRST") and seq_len is not None:
        # Length-0 slots (legal in the nested level-2 contract, where
        # padding sentences flatten to empty inner rows) must pool to 0
        # like the masked-sum family — not finfo.min (MAX) or padding
        # reads (LAST/FIRST) that would leak into the outer pool.
        alive = (lens > 0).reshape((-1,) + (1,) * (x.ndim - 2))
        out = jnp.where(alive, out, jnp.zeros((), x.dtype))
    return {"Out": [out]}


@register("sequence_softmax", no_grad_slots=("SeqLen",))
def _sequence_softmax(ctx, ins, attrs):
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    if seq_len is None:
        return {"Out": [jax.nn.softmax(x, axis=1)]}
    m = _mask(x, seq_len)
    neg = jnp.asarray(-1e9, jnp.float32)
    logits = jnp.where(m, x.astype(jnp.float32), neg)
    out = jax.nn.softmax(logits, axis=1) * m.astype(jnp.float32)
    return {"Out": [out.astype(x.dtype)]}


@register("sequence_expand", no_grad_slots=("SeqLen",))
def _sequence_expand(ctx, ins, attrs):
    # expand [B, D] (or [B,1,D]) to [B, T, D] following Y's layout
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == y.ndim:
        return {"Out": [jnp.broadcast_to(x, y.shape[:2] + x.shape[2:])]}
    return {"Out": [jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:])]}


@register("sequence_reverse", no_grad_slots=("SeqLen",))
def _sequence_reverse(ctx, ins, attrs):
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    T = x.shape[1]
    if seq_len is None:
        return {"Out": [jnp.flip(x, axis=1)]}
    # per-row reversal of the valid prefix: index (len-1-t) mod T for t<len
    t = jnp.arange(T)[None, :]
    idx = jnp.where(t < seq_len[:, None], seq_len[:, None] - 1 - t, t)
    out = jnp.take_along_axis(x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)
    return {"Out": [out]}


@register("sequence_concat", no_grad_slots=("SeqLen",))
def _sequence_concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=1)]}


@register("sequence_first_step", no_grad_slots=("SeqLen",))
def _sequence_first_step(ctx, ins, attrs):
    return {"Out": [ins["X"][0][:, 0]]}


@register("sequence_last_step", no_grad_slots=("SeqLen",))
def _sequence_last_step(ctx, ins, attrs):
    return _sequence_pool(ctx, ins, {"pooltype": "LAST"})


@register("sequence_conv", no_grad_slots=("SeqLen",))
def _sequence_conv(ctx, ins, attrs):
    """Context-window convolution over [B,T,D] (sequence_conv_op.cc +
    math/context_project.h): each position sees ``context_length`` steps
    starting at ``context_start``; out-of-range and beyond-length context
    is zero.  Filter: [context_length*D, out_dim]."""
    x = ins["X"][0]
    w = ins["Filter"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    cl = int(attrs.get("contextLength", attrs.get("context_length", 3)))
    cs = int(attrs.get("contextStart", attrs.get("context_start", -(cl // 2))))
    if int(attrs.get("contextStride", 1)) != 1:
        raise NotImplementedError(
            "sequence_conv: contextStride != 1 is unsupported (matches the "
            "reference, sequence_conv_op.cc PADDLE_ENFORCE stride==1)")
    B, T = x.shape[0], x.shape[1]
    xm = x if seq_len is None else x * _mask(x, seq_len).astype(x.dtype)
    cols = []
    for i in range(cl):
        off = cs + i
        shifted = jnp.roll(xm, -off, axis=1)
        t = jnp.arange(T)
        valid = (t + off >= 0) & (t + off < T)
        cols.append(jnp.where(valid[None, :, None], shifted, 0))
    ctx_mat = jnp.concatenate(cols, axis=-1)          # [B,T,cl*D]
    out = jnp.einsum("btd,de->bte", ctx_mat, w.astype(ctx_mat.dtype))
    if seq_len is not None:
        out = out * _mask(out, seq_len).astype(out.dtype)
    return {"Out": [out]}


@register("sequence_slice", no_grad_slots=("Offset", "Length", "SeqLen"))
def _sequence_slice(ctx, ins, attrs):
    """Per-row [offset, offset+length) subsequence, left-aligned into the
    padded layout (sequence_slice_op.cc)."""
    x = ins["X"][0]
    off = ins["Offset"][0].reshape(-1).astype(jnp.int32)
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    T = x.shape[1]
    t = jnp.arange(T)[None, :]
    src = jnp.clip(t + off[:, None], 0, T - 1)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    keep = (t < length[:, None]).reshape(
        (x.shape[0], T) + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(keep, out, 0)], "OutLen": [length.astype(jnp.int64)]}


@register("sequence_erase", no_grad_slots=("SeqLen",))
def _sequence_erase(ctx, ins, attrs):
    """Drop listed tokens and left-compact (sequence_erase_op.cc).  Int id
    sequences [B,T] (or [B,T,1]); emits compacted ids + new lengths."""
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    ids = x.reshape(x.shape[0], x.shape[1]) if squeeze else x
    B, T = ids.shape
    tokens = jnp.asarray(list(attrs.get("tokens", [])), ids.dtype)
    valid = jnp.arange(T)[None, :] < (
        seq_len[:, None] if seq_len is not None else T)
    erase = jnp.isin(ids, tokens) if tokens.size else jnp.zeros_like(valid)
    keep = valid & ~erase
    compacted, new_len = left_compact(ids, keep)
    out = jnp.where(jnp.arange(T)[None, :] < new_len[:, None], compacted, 0)
    if squeeze:
        out = out[..., None]
    return {"Out": [out], "OutLen": [new_len]}


@register("sequence_enumerate", no_grad_slots=("SeqLen",))
def _sequence_enumerate(ctx, ins, attrs):
    """Sliding windows of win_size ids per position
    (sequence_enumerate_op.cc): [B,T] → [B,T,win]; positions whose window
    crosses the sequence end emit pad_value."""
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    ids = x.reshape(x.shape[0], x.shape[1]) if squeeze else x
    B, T = ids.shape
    win = int(attrs["win_size"])
    pad = attrs.get("pad_value", 0)
    lens = seq_len[:, None] if seq_len is not None else jnp.full((B, 1), T)
    outs = []
    t = jnp.arange(T)[None, :]
    for i in range(win):
        shifted = jnp.roll(ids, -i, axis=1)
        ok = (t + i) < lens
        outs.append(jnp.where(ok, shifted, jnp.asarray(pad, ids.dtype)))
    return {"Out": [jnp.stack(outs, axis=-1)]}


@register("sequence_expand_as", no_grad_slots=("SeqLen",))
def _sequence_expand_as(ctx, ins, attrs):
    """Broadcast one row-vector per sequence across Y's time dimension
    (sequence_expand_as_op.cc), masked by Y's lengths."""
    x, y = ins["X"][0], ins["Y"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    out = jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:])
    if seq_len is not None:
        out = out * _mask(out, seq_len).astype(out.dtype)
    return {"Out": [out]}


@register("sequence_pad", no_grad_slots=("PadValue", "SeqLen"))
def _sequence_pad(ctx, ins, attrs):
    """Materialize padding with an explicit pad value up to padded_length
    (sequence_pad_op.cc).  The runtime layout is already padded-with-zeros;
    this rewrites the tail to pad_value and returns per-row lengths."""
    x = ins["X"][0]
    pad_value = ins["PadValue"][0].reshape(()) if ins.get("PadValue") else 0.0
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    padded_len = int(attrs.get("padded_length", -1))
    T = x.shape[1]
    if padded_len > 0 and padded_len != T:
        if padded_len > T:
            widths = [(0, 0), (0, padded_len - T)] + [(0, 0)] * (x.ndim - 2)
            x = jnp.pad(x, widths)
        else:
            x = x[:, :padded_len]
    lens = (seq_len if seq_len is not None
            else jnp.full((x.shape[0],), T, jnp.int64))
    m = _mask(x, lens)
    out = jnp.where(m, x, jnp.asarray(pad_value, x.dtype))
    return {"Out": [out], "Length": [lens.astype(jnp.int64)]}


@register("sequence_unpad", no_grad_slots=("Length",))
def _sequence_unpad(ctx, ins, attrs):
    """Inverse of sequence_pad: zero the tail and alias the lengths
    (sequence_unpad_op.cc — the ragged-ness lives in the length vector)."""
    x = ins["X"][0]
    lens = ins["Length"][0].reshape(-1)
    m = _mask(x, lens)
    return {"Out": [jnp.where(m, x, 0)], "OutLen": [lens.astype(jnp.int64)]}


@register("sequence_reshape", no_grad_slots=("SeqLen",))
def _sequence_reshape(ctx, ins, attrs):
    """Change the step width D→new_dim, merging/splitting steps
    (sequence_reshape_op.cc); lengths scale by D/new_dim."""
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    new_dim = int(attrs["new_dim"])
    B, T, D = x.shape[0], x.shape[1], x.shape[-1]
    total = T * D
    assert total % new_dim == 0, (T, D, new_dim)
    out = x.reshape(B, total // new_dim, new_dim)
    lens = (seq_len * D) // new_dim if seq_len is not None else None
    outs = {"Out": [out]}
    if lens is not None:
        outs["OutLen"] = [lens.astype(jnp.int64)]
    return outs


@register("row_conv", no_grad_slots=("SeqLen",))
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (row_conv_op.cc, DeepSpeech2):
    out[b,t] = Σ_i x[b,t+i]·w[i], i in [0, future_context); elementwise
    per feature."""
    x = ins["X"][0]                    # [B,T,D]
    w = ins["Filter"][0]               # [k, D]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    k = w.shape[0]
    T = x.shape[1]
    xm = x if seq_len is None else x * _mask(x, seq_len).astype(x.dtype)
    out = jnp.zeros_like(xm)
    t = jnp.arange(T)
    for i in range(k):
        shifted = jnp.roll(xm, -i, axis=1)
        ok = (t + i) < T
        out = out + jnp.where(ok[None, :, None], shifted, 0) * w[i][None, None, :]
    if seq_len is not None:
        out = out * _mask(out, seq_len).astype(out.dtype)
    return {"Out": [out]}


@register("sequence_mask", no_grad_slots=("X",))
def _sequence_mask(ctx, ins, attrs):
    """sequence_mask_op.cc: X holds lengths; out[..., j] = j < X[...]."""
    x = ins["X"][0]
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError(
            "sequence_mask requires a static maxlen on TPU (dynamic "
            "max-length would make the output shape data-dependent)")
    from ..core.types import np_dtype
    dt = np_dtype(attrs.get("out_dtype", "int64"))
    mask = jnp.arange(maxlen)[None, :] < x.reshape(-1, 1)
    return {"Y": [mask.reshape(tuple(x.shape) + (maxlen,)).astype(dt)]}


@register("im2sequence", no_grad_slots=("SeqLen",))
def _im2sequence(ctx, ins, attrs):
    """im2sequence_op.cc redesigned for the padded contract: NCHW image ->
    [B, oh*ow, C*kh*kw] patch sequence (+ constant per-sample length)."""
    x = ins["X"][0]
    kh, kw = attrs["kernels"]
    st = attrs.get("strides", [1, 1])
    pd = attrs.get("paddings", [0, 0, 0, 0])  # up, left, down, right
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(st),
        [(pd[0], pd[2]), (pd[1], pd[3])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, oh, ow]
    oh, ow = patches.shape[2], patches.shape[3]
    seq = patches.reshape(n, c * kh * kw, oh * ow).transpose(0, 2, 1)
    lens = jnp.full((n,), oh * ow, jnp.int64)
    return {"Out": [seq], "OutLen": [lens]}


@register("sequence_scatter", no_grad_slots=("Ids", "SeqLen"))
def _sequence_scatter(ctx, ins, attrs):
    """sequence_scatter_op.cc: out = X; out[i, ids[i, j]] += updates[i, j]
    for valid j (per-sequence scatter-add of updates into row i)."""
    x = ins["X"][0]
    ids = ins["Ids"][0].astype(jnp.int32)
    upd = ins["Updates"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    B, T = ids.shape[0], ids.shape[1]
    if seq_len is not None:
        valid = jnp.arange(T)[None, :] < seq_len[:, None]
    else:
        valid = jnp.ones((B, T), bool)
    upd = jnp.where(valid.reshape(valid.shape + (1,) * (upd.ndim - 2)),
                    upd, 0).astype(x.dtype)
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    return {"Out": [x.at[rows, ids].add(upd)]}


@register("lod_reset", no_grad_slots=("Y", "TargetLenTensor"))
def _lod_reset(ctx, ins, attrs):
    """lod_reset_op.cc on the padded contract: data passes through; the new
    length vector comes from Y's lengths (or the target_lod attr via the
    layer).  The layer wires the returned OutLen as Out@LEN."""
    x = ins["X"][0]
    if ins.get("TargetLenTensor"):
        new_len = ins["TargetLenTensor"][0]
    elif ins.get("Y"):
        new_len = ins["Y"][0]
    else:
        tl = attrs.get("target_lod", [])
        # offsets -> lengths (reference target_lod is offset-style)
        new_len = jnp.asarray(
            [tl[i + 1] - tl[i] for i in range(len(tl) - 1)], jnp.int64)
    return {"Out": [x], "OutLen": [new_len.astype(jnp.int64)]}


# ---------------------------------------------------------------------------
# nested (lod_level 2) support: [B, S, W, ...] + inner lengths [B, S]
# ---------------------------------------------------------------------------
# General level-2 sequences (reference lod_tensor.h:58 nested LoD — e.g.
# paragraph -> sentence -> word) reduce to level-1 ops on the flattened
# sentence axis: the padded-nested layout [B, S, W, ...] with inner
# lengths [B, S] IS [B*S, W, ...] with lengths [B*S].  Ops that operate
# on the innermost level take the optional "SeqLen2" slot and run their
# level-1 lowering over the flattened view; pooling removes the inner
# level (out [B, S, ...], outer @LEN becomes the companion — the layer
# wires that).  Sentence slots past a sample's outer length have
# length 0 and pool to zeros, masked downstream by the outer lengths.

_NESTED_INNER_OPS = ("sequence_pool", "sequence_softmax", "sequence_reverse",
                     "sequence_first_step", "sequence_last_step",
                     "sequence_pad", "sequence_unpad")


def _nestable(fn):
    def wrapped(ctx, ins, attrs):
        if not ins.get("SeqLen2"):
            return fn(ctx, ins, attrs)
        x = ins["X"][0]
        B, S = x.shape[0], x.shape[1]
        lens2 = ins["SeqLen2"][0].reshape(-1)
        sub = {k: v for k, v in ins.items() if k != "SeqLen2"}
        sub["X"] = [x.reshape((B * S,) + x.shape[2:])]
        sub["SeqLen"] = [lens2]
        out = fn(ctx, sub, attrs)
        o = out["Out"][0]
        out["Out"] = [o.reshape((B, S) + o.shape[1:])]
        for slot in ("Length", "OutLen"):
            if slot in out:
                out[slot] = [out[slot][0].reshape(B, S)]
        return out
    return wrapped


def _enable_nested():
    from ..core.registry import _REGISTRY

    for t in _NESTED_INNER_OPS:
        opdef = _REGISTRY[t]
        opdef.lower = _nestable(opdef.lower)
        opdef.no_grad_slots.add("SeqLen2")


_enable_nested()
