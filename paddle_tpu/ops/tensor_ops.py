"""Tensor manipulation / creation op lowerings.

Reference coverage: ``reshape_op.cc``, ``transpose_op.cc``, ``concat_op.cc``,
``split_op.cc``, ``stack_op``, ``slice_op.cc``, ``expand_op.cc``,
``gather_op.cc``, ``scatter_op.cc``, ``lookup_table_op.cc``,
``fill_constant_op.cc``, ``uniform_random_op.cc``, ``gaussian_random_op.cc``,
``assign_op.cc``, ``shape_op.cc``, ``one_hot_op.cc``, ``top_k_op.cc``,
``arg_max_op``, ``cast_op``, ``pad_op.cc``, ``squeeze/unsqueeze``,
``fill_constant_batch_size_like_op.cc``, ``increment_op``, ``dropout_op.cc``.
Random ops consume PRNG keys threaded through the block (ctx.prng()), the
functional replacement for the reference's per-op seed attrs + cuRAND.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, register_grad
from ..core.types import np_dtype


@register("reshape")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # reference semantics: 0 means copy input dim; -1 infers
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": [x.reshape(shape)]}


register("reshape2")(_reshape)  # alias; reference reshape2 also outputs XShape


@register("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


register("transpose2")(_transpose)


@register("squeeze")
def _squeeze(ctx, ins, attrs):
    axes = attrs.get("axes", [])
    x = ins["X"][0]
    return {"Out": [jnp.squeeze(x, axis=tuple(axes) if axes else None)]}


@register("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    for ax in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, ax)
    return {"Out": [x]}


@register("concat")
def _concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    sections = attrs.get("sections", [])
    num = attrs.get("num", 0)
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register("stack")
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register("slice")
def _slice(ctx, ins, attrs):
    x = ins["Input"][0]
    axes, starts, ends = attrs["axes"], attrs["starts"], attrs["ends"]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        dim = x.shape[ax]
        st = max(st + dim, 0) if st < 0 else min(st, dim)
        en = max(en + dim, 0) if en < 0 else min(en, dim)
        idx[ax] = slice(st, en)
    return {"Out": [x[tuple(idx)]]}


@register("expand")
def _expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register("pad")
def _pad(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]  # flat [before0, after0, before1, after1, ...]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))]}


@register("gather", no_grad_slots=("Index",))
def _gather(ctx, ins, attrs):
    x, index = ins["X"][0], ins["Index"][0]
    return {"Out": [jnp.take(x, index, axis=attrs.get("axis", 0))]}


@register("scatter", no_grad_slots=("Ids",))
def _scatter(ctx, ins, attrs):
    x, ids, updates = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(updates)]}
    return {"Out": [x.at[ids].add(updates)]}


@register("lookup_table", no_grad_slots=("Ids",))
def _lookup_table(ctx, ins, attrs):
    """Embedding gather (lookup_table_op.cc).  Ids may carry a trailing
    [..., 1] dim like the reference; padding_idx rows produce zeros.
    On TPU this is a plain XLA gather; the distributed/sharded-table path
    lives in the transpiler + pserver layers, not here."""
    w, ids = ins["W"][0], ins["Ids"][0]
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.squeeze(-1)
    out = jnp.take(w, ids, axis=0)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        mask = (ids != pad)[..., None].astype(out.dtype)
        out = out * mask
    return {"Out": [out]}


@register_grad("lookup_table")
def _lookup_table_grad(ctx, ins, attrs):
    """W-grad of the embedding gather.  With ``is_sparse`` the gradient is a
    SelectedRows {flattened ids, cotangent rows} pair — the [height, D]
    dense gradient is never materialised (reference sparse path:
    lookup_table_op.cc grad → SelectedRows, selected_rows.h:32)."""
    from ..core.selected_rows import SelectedRows

    ids = ins["Ids"][0]
    gout = ins["Out@GRAD"][0]
    if gout is None:
        return {}
    # W may be absent: the DistributeTranspiler strips the table var from
    # the trainer (only its prefetched rows exist there) and supplies
    # height/dtype as attrs instead
    w = ins["W"][0] if ins.get("W") else None
    height = int(attrs["height"]) if w is None else w.shape[0]
    wdtype = np_dtype(attrs["w_dtype"]) if w is None else w.dtype
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        gout = gout * (ids != pad)[..., None].astype(gout.dtype)
    rows = ids.reshape(-1)
    vals = gout.reshape((-1,) + gout.shape[ids.ndim:]).astype(wdtype)
    if attrs.get("is_sparse", False):
        return {"W@GRAD": [SelectedRows(rows, vals, height)]}
    if w is None:
        dense = jnp.zeros((height,) + vals.shape[1:], wdtype)
        return {"W@GRAD": [dense.at[rows].add(vals)]}
    return {"W@GRAD": [jnp.zeros_like(w).at[rows].add(vals)]}


@register("sparse_decay", no_grad_slots=("Param", "Grad"))
def _sparse_decay(ctx, ins, attrs):
    """Weight-decay contribution for a SelectedRows gradient: decay only the
    touched rows (reference regularizer.py SelectedRows branch: extract_rows
    + row gather + scale).  Rows are merged first so duplicated lookups decay
    once, matching the dense-grad semantics."""
    from ..core.selected_rows import SelectedRows, gather_rows, merge_rows

    p, g = ins["Param"][0], ins["Grad"][0]
    m = merge_rows(g)
    pr = gather_rows(p, m.rows).astype(m.dtype)
    coeff = attrs.get("coeff", 0.0)
    vals = coeff * (jnp.sign(pr) if attrs.get("mode", "l2") == "l1" else pr)
    return {"Out": [SelectedRows(m.rows, vals, m.height, merged=True)]}


@register("one_hot", no_grad_slots=("X",))
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    return {"Out": [jax.nn.one_hot(x, attrs["depth"], dtype=np_dtype(attrs.get("dtype", "float32")))]}


@register("shape", no_grad_slots=("Input",))
def _shape(ctx, ins, attrs):
    return {"Out": [jnp.asarray(ins["Input"][0].shape, dtype=jnp.int32)]}


@register("assign")
def _assign(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register("increment")
def _increment(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


@register("fill_constant")
def _fill_constant(ctx, ins, attrs):
    dt = np_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(tuple(attrs["shape"]), attrs["value"], dtype=dt)]}


@register("fill_constant_batch_size_like", no_grad_slots=("Input",))
def _fill_cbsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dt = np_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(tuple(shape), attrs["value"], dtype=dt)]}


@register("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register("uniform_random", stateful=True)
def _uniform_random(ctx, ins, attrs):
    dt = np_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    key = _seed_key(ctx, attrs)
    return {"Out": [jax.random.uniform(key, shape, dtype=jnp.float32,
                                       minval=attrs.get("min", -1.0),
                                       maxval=attrs.get("max", 1.0)).astype(dt)]}


@register("gaussian_random", stateful=True)
def _gaussian_random(ctx, ins, attrs):
    dt = np_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    key = _seed_key(ctx, attrs)
    x = jax.random.normal(key, shape, dtype=jnp.float32)
    return {"Out": [(x * attrs.get("std", 1.0) + attrs.get("mean", 0.0)).astype(dt)]}


@register("truncated_gaussian_random", stateful=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    dt = np_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    key = _seed_key(ctx, attrs)
    x = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=jnp.float32)
    return {"Out": [(x * attrs.get("std", 1.0) + attrs.get("mean", 0.0)).astype(dt)]}


def _seed_key(ctx, attrs):
    seed = attrs.get("seed", 0)
    name = attrs.get("seed_name")
    if name:
        # initializer ops: key by var name → order/partition-independent
        return ctx.named_prng(name, seed)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.prng()


@register("dropout", stateful=True)
def _dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or not ctx.training
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        if impl == "upscale_in_train":
            return {"Out": [x], "Mask": [jnp.ones_like(x)]}
        return {"Out": [x * jnp.asarray(1.0 - p, x.dtype)],
                "Mask": [jnp.ones_like(x)]}
    keep = jax.random.bernoulli(_seed_key(ctx, attrs), 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        scale = jnp.asarray(1.0 / max(1.0 - p, 1e-8), x.dtype)
        mask = keep.astype(x.dtype) * scale
    else:
        mask = keep.astype(x.dtype)
    return {"Out": [x * mask], "Mask": [mask]}


@register_grad("dropout")
def _dropout_grad(ctx, ins, attrs):
    return {"X@GRAD": [ins["Out@GRAD"][0] * ins["Mask"][0]]}


@register("top_k", no_grad_slots=("X",))
def _top_k(ctx, ins, attrs):
    vals, idx = lax.top_k(ins["X"][0], attrs["k"])
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register("arg_max", no_grad_slots=("X",))
def _arg_max(ctx, ins, attrs):
    return {"Out": [jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int64)]}


@register("arg_min", no_grad_slots=("X",))
def _arg_min(ctx, ins, attrs):
    return {"Out": [jnp.argmin(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int64)]}


@register("range", no_grad_slots=("Start", "End", "Step"))
def _range(ctx, ins, attrs):
    if "Start" in ins:
        st, en, sp = ins["Start"][0], ins["End"][0], ins["Step"][0]
        # XLA needs static sizes; range via attrs preferred
        raise NotImplementedError("dynamic range not supported under XLA; use attrs")
    dt = np_dtype(attrs.get("dtype", "int64"))
    return {"Out": [jnp.arange(attrs["start"], attrs["end"], attrs["step"], dtype=dt)]}


@register("where", no_grad_slots=("Condition",))
def _where(ctx, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register("print")
def _print(ctx, ins, attrs):
    x = ins["In"][0]
    jax.debug.print(attrs.get("message", "") + " {}", x)
    return {"Out": [x]}


@register("assign_value")
def _assign_value(ctx, ins, attrs):
    import numpy as _np
    arr = _np.asarray(attrs["values"], dtype=np_dtype(attrs.get("dtype", "float32")))
    return {"Out": [jnp.asarray(arr.reshape(attrs["shape"]))]}


register("split_byref")(_split)  # split_byref_op.cc: same math, by-ref out


@register("fill")
def _fill(ctx, ins, attrs):
    """fill_op.cc: fill Out with the literal data in attrs (row-major)."""
    import numpy as np
    dt = np_dtype(attrs.get("dtype", "float32"))
    data = np.asarray(attrs["value"], dt).reshape(attrs["shape"])
    return {"Out": [jnp.asarray(data)]}


@register("extract_rows", no_grad_slots=("X",))
def _extract_rows(ctx, ins, attrs):
    """extract_rows_op.cc: the row-id vector of a SelectedRows value."""
    from ..core.selected_rows import SelectedRows
    x = ins["X"][0]
    if not isinstance(x, SelectedRows):
        raise TypeError("extract_rows expects a SelectedRows input")
    return {"Out": [x.rows.reshape(-1, 1)]}
