"""Fake-quantization ops for quantization-aware training.

Reference: ``paddle/fluid/operators/fake_quantize_op.cc``
(abs_max / moving_average_abs_max / channel_wise variants) and
``fake_dequantize_op.cc``.  Quantize-dequantize in the forward, straight-
through estimator in the backward (the reference grad kernels pass the
gradient through unchanged) — registered as explicit grad rules since
round() has zero derivative.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register, register_grad


def _qdq(x, scale, bits):
    r = float((1 << (bits - 1)) - 1)
    scale = jnp.maximum(scale.astype(jnp.float32), 1e-8)
    q = jnp.round(x.astype(jnp.float32) / scale * r)
    q = jnp.clip(q, -r, r)
    return (q * scale / r).astype(x.dtype)


@register("fake_quantize_abs_max")
def _fake_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return {"Out": [_qdq(x, scale, bits)], "OutScale": [scale]}


@register_grad("fake_quantize_abs_max")
def _fake_quantize_abs_max_grad(ctx, ins, attrs):
    return {"X@GRAD": [ins["Out@GRAD"][0]]}  # straight-through


@register("fake_channel_wise_quantize_abs_max")
def _fake_cw_quantize(ctx, ins, attrs):
    """Per-output-channel (dim 0) scales — conv filter quantization."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    flat = jnp.abs(x.astype(jnp.float32)).reshape(x.shape[0], -1)
    scale = jnp.max(flat, axis=1)
    shaped = scale.reshape((-1,) + (1,) * (x.ndim - 1))
    return {"Out": [_qdq(x, shaped, bits)], "OutScale": [scale]}


@register_grad("fake_channel_wise_quantize_abs_max")
def _fake_cw_quantize_grad(ctx, ins, attrs):
    return {"X@GRAD": [ins["Out@GRAD"][0]]}


@register("fake_quantize_moving_average_abs_max",
          no_grad_slots=("InScale", "InAccum", "InState"))
def _fake_quantize_mavg(ctx, ins, attrs):
    """Running abs-max scale (fake_quantize_op.cc moving_average path):
    state = rate·state + 1; accum = rate·accum + max|x|;
    scale = accum/state.  State vars are persistable in/outs."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    cur = jnp.max(jnp.abs(x.astype(jnp.float32)))
    accum = (ins["InAccum"][0].reshape(()) if ins.get("InAccum")
             else jnp.zeros((), jnp.float32))
    state = (ins["InState"][0].reshape(()) if ins.get("InState")
             else jnp.zeros((), jnp.float32))
    if ctx.training and not attrs.get("is_test", False):
        state = rate * state + 1.0
        accum = rate * accum + cur
        scale = accum / jnp.maximum(state, 1e-8)
    else:
        scale = (ins["InScale"][0].reshape(()) if ins.get("InScale") else cur)
    return {"Out": [_qdq(x, scale, bits)],
            "OutScale": [scale.reshape((1,))],
            "OutAccum": [accum.reshape((1,))],
            "OutState": [state.reshape((1,))]}


@register_grad("fake_quantize_moving_average_abs_max")
def _fake_quantize_mavg_grad(ctx, ins, attrs):
    return {"X@GRAD": [ins["Out@GRAD"][0]]}


@register("fake_dequantize_max_abs", no_grad_slots=("Scale",))
def _fake_dequantize(ctx, ins, attrs):
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(()).astype(jnp.float32)
    r = float(attrs.get("max_range", 127))
    return {"Out": [(x.astype(jnp.float32) * scale / r).astype(x.dtype)]}
