"""Neural-net op lowerings: conv, pool, normalization, losses, recurrent
cells.

Reference coverage: ``conv_op.cc``/``conv_cudnn_op.cu.cc``, ``pool_op.cc``,
``batch_norm_op.cc``, ``layer_norm_op.cc``, ``cross_entropy_op.cc``,
``softmax_with_cross_entropy_op.cc``, ``accuracy_op.cc``, ``lstm_op.cc`` +
``math/lstm_compute``, ``gru_op.cc``, ``conv2d_transpose``, ``norm_op.cc``,
``huber_loss``/``square_error_cost``-style losses.

TPU mapping: convs/matmuls go through lax.conv_general_dilated / jnp.matmul
(MXU); recurrences are ``lax.scan`` over padded [B,T,...] tensors with a
length mask — the static-shape replacement for the reference's LoDTensor
batch⇄sequence machinery (``math/sequence2batch.h``).  Gradients come from
the vjp default rule (scan differentiates to reverse-scan, the functional
equivalent of the reference's recurrent grad machinery).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register, register_grad


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _stat_dtype(x):
    """Statistics dtype: at least f32 (bf16 inputs promote), keep f64."""
    return jnp.promote_types(x.dtype, jnp.float32)


# ---------------------------------------------------------------------------
# conv / pool
# ---------------------------------------------------------------------------

@register("conv2d", no_grad_slots=())
def _conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    layout = attrs.get("data_layout", "NCHW")
    # Filter params are always OIHW (the reference's storage layout) so
    # checkpoints stay layout-independent; for NHWC activations the spec
    # string retargets the conv and XLA folds the constant-strided filter
    # view into its im2col read.
    if (layout == "NHWC" and x.shape[-1] <= 4 and strides == (2, 2)
            and pads == (3, 3) and w.shape[2:] == (7, 7)
            and dil == (1, 1) and groups == 1
            and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0):
        # Space-to-depth stem (the MLPerf ResNet trick, exact): a 7x7/s2/p3
        # conv on <=4 input channels runs at ~2% MXU utilization (3 lanes of
        # 128).  Fold 2x2 pixel blocks into channels (12 lanes), zero-pad
        # the kernel to 8x8 and rearrange to 4x4 in block space — identical
        # math (the zero taps contribute nothing and their grads are
        # discarded by pad's vjp), 4x the lane occupancy.
        b, h, wd, c = x.shape
        o = w.shape[0]
        xs = x.reshape(b, h // 2, 2, wd // 2, 2, c)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, wd // 2, 4 * c)
        wp = jnp.pad(w.transpose(2, 3, 1, 0), ((1, 0), (1, 0), (0, 0), (0, 0)))
        ws = wp.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
        ws = ws.reshape(4, 4, 4 * c, o)
        out = lax.conv_general_dilated(
            xs, ws, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return {"Output": [out]}
    # NOTE(perf A/B, r4): lowering 1x1 convs as reshape->dot (so XLA could
    # fuse the BN stats reductions into the dot epilogue, which its conv
    # emitter cannot take) was tried and REVERTED: whole-model resnet50
    # measured 2,547 img/s (bf16 dot) / 1,395 (f32-accum dot) vs 2,626
    # with lax.conv — the reshape barriers break more producer/consumer
    # fusion than the epilogue recovers.  See PERF.md par.2 round-4 note.
    dn = ("NHWC", "OIHW", "NHWC") if layout == "NHWC" else ("NCHW", "OIHW", "NCHW")
    out = lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=dn,
    )
    return {"Output": [out]}


register("depthwise_conv2d")(
    lambda ctx, ins, attrs: _conv2d(
        ctx, ins, {**attrs, "groups": ins["Input"][0].shape[1]}
    )
)


@register("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    # conv2d_transpose is defined as the input-gradient of a forward conv2d
    # (reference conv_transpose_op semantics: out = (in-1)*s - 2p + d*(k-1)+1,
    # weight layout [C_in, C_out/g, kh, kw] ≡ OIHW of the y→x conv).
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    n, _, h, wd = x.shape
    _, cout_pg, kh, kw = w.shape
    cout = cout_pg * groups
    hout = (h - 1) * strides[0] - 2 * pads[0] + dil[0] * (kh - 1) + 1
    wout = (wd - 1) * strides[1] - 2 * pads[1] + dil[1] * (kw - 1) + 1

    def fwd(y):
        return lax.conv_general_dilated(
            y, w,
            window_strides=strides,
            padding=[(pads[0], pads[0]), (pads[1], pads[1])],
            rhs_dilation=dil,
            feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )

    _, vjp_fn = jax.vjp(fwd, jnp.zeros((n, cout, hout, wout), x.dtype))
    (out,) = vjp_fn(x)
    return {"Output": [out]}


@register("pool2d")
def _pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    layout = attrs.get("data_layout", "NCHW")
    sp = (1, 2) if layout == "NHWC" else (2, 3)
    if attrs.get("global_pooling", False):
        ks = tuple(x.shape[d] for d in sp)
        strides, pads = ks, (0, 0)
    else:
        ks = _pair(attrs["ksize"])
        strides = _pair(attrs.get("strides", [1, 1]))
        pads = _pair(attrs.get("paddings", [0, 0]))
    window = [1, 1, 1, 1]
    strides_full = [1, 1, 1, 1]
    padding = [(0, 0)] * 4
    for i, d in enumerate(sp):
        window[d] = ks[i]
        strides_full[d] = strides[i]
        padding[d] = (pads[i], pads[i])
    window, strides_full = tuple(window), tuple(strides_full)
    padding = tuple(padding)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides_full, padding)
    else:
        summed = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, window, strides_full, padding)
        if attrs.get("exclusive", True) and (pads[0] or pads[1]):
            ones = jnp.ones(tuple(x.shape[d] for d in sp), jnp.float32)
            ones = ones[None, :, :, None] if layout == "NHWC" else ones[None, None]
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides_full, padding)
            out = summed / counts
        else:
            out = summed / float(np.prod(ks))
        out = out.astype(x.dtype)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@register("batch_norm", no_grad_slots=("Mean", "Variance"))
def _batch_norm(ctx, ins, attrs):
    """batch_norm_op.cc semantics: training mode uses batch statistics and
    exponentially updates the running Mean/Variance (persistable state — the
    executor writes MeanOut/VarianceOut back to the same scope vars);
    is_test uses the running stats."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or not ctx.training
    layout = attrs.get("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim) if i != (1 if layout == "NCHW" else x.ndim - 1))
    bshape = [1] * x.ndim
    bshape[1 if layout == "NCHW" else x.ndim - 1] = -1

    sdt = _stat_dtype(x)
    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_inv_std = lax.rsqrt(var + eps)
    else:
        # One-pass statistics (E[x], E[x^2]) so XLA reads the activation a
        # single time for both moments — on TPU the two-pass mean/var form
        # costs an extra full HBM sweep of the conv output, which dominates
        # BN time for bandwidth-bound image models.
        xf = x.astype(sdt)
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.maximum(
            jnp.mean(xf * xf, axis=axes) - use_mean * use_mean, 0.0)
        mean_out = mean * momentum + use_mean * (1.0 - momentum)
        var_out = var * momentum + use_var * (1.0 - momentum)
        saved_mean = use_mean
        saved_inv_std = lax.rsqrt(use_var + eps)

    # Folded affine: y = x*(inv*scale) + (bias - mean*inv*scale).  The
    # per-channel factors are computed in fp32 then cast to x.dtype, so the
    # per-element work stays in the activation dtype (bf16 on the MXU path)
    # instead of materializing an fp32 copy of the activation.
    inv = lax.rsqrt(use_var.astype(sdt) + eps)
    inv_s = inv * scale.astype(sdt)
    shift = bias.astype(sdt) - use_mean.astype(sdt) * inv_s
    y = x * inv_s.reshape(bshape).astype(x.dtype) \
        + shift.reshape(bshape).astype(x.dtype)
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_inv_std],
    }


@register("layer_norm")
def _layer_norm(ctx, ins, attrs):
    """layer_norm_op.cc: normalize over dims [begin_norm_axis:], affine with
    flattened Scale/Bias.  Stats in fp32 for bf16 inputs (TPU numeric
    policy)."""
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    bna = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(bna, x.ndim))
    xf = x.astype(_stat_dtype(x))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    bshape = [1] * bna + list(x.shape[bna:])
    if "Scale" in ins and ins["Scale"]:
        y = y * ins["Scale"][0].reshape(bshape)
    if "Bias" in ins and ins["Bias"]:
        y = y + ins["Bias"][0].reshape(bshape)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [mean.reshape(x.shape[:bna])],
        "Variance": [var.reshape(x.shape[:bna])],
    }


@register("norm")
def _norm(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def _squeeze_label(label):
    if label.ndim >= 2 and label.shape[-1] == 1:
        return label.squeeze(-1)
    return label


@register("cross_entropy", no_grad_slots=("Label",))
def _cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-8
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        li = _squeeze_label(label)
        p = jnp.take_along_axis(x, li[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(p + eps)
    return {"Y": [loss]}


@register("softmax_with_cross_entropy", no_grad_slots=("Label",))
def _softmax_xent(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    sdt = _stat_dtype(logits)
    lse = jax.nn.logsumexp(logits.astype(sdt), axis=-1, keepdims=True)
    log_softmax = logits.astype(sdt) - lse
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * log_softmax, axis=-1, keepdims=True)
    else:
        li = _squeeze_label(label).astype(jnp.int32)
        picked = jnp.take_along_axis(log_softmax, li[..., None], axis=-1)
        if attrs.get("ignore_index", -100) != -100:
            mask = (li[..., None] != attrs["ignore_index"]).astype(log_softmax.dtype)
            picked = picked * mask
        loss = -picked
    return {"Softmax": [jnp.exp(log_softmax).astype(logits.dtype)],
            "Loss": [loss.astype(logits.dtype)]}


@register("square_error_cost")
def _square_error_cost(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.square(x - y)]}


@register("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = attrs.get("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d))
    return {"Out": [loss], "Residual": [r]}


@register("sigmoid_cross_entropy_with_logits", no_grad_slots=("Label",))
def _sce_logits(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


@register("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    """smooth_l1_loss_op.h: diff = (x-y)*inside_weight; per-element error
    scaled by outside_weight; row-summed loss."""
    x, y = ins["X"][0], ins["Y"][0]
    sigma2 = attrs.get("sigma", 1.0) ** 2
    d = x - y
    if "InsideWeight" in ins and ins["InsideWeight"]:
        d = d * ins["InsideWeight"][0]
    a = jnp.abs(d)
    loss = jnp.where(a < 1.0 / sigma2, 0.5 * d * d * sigma2, a - 0.5 / sigma2)
    if "OutsideWeight" in ins and ins["OutsideWeight"]:
        loss = loss * ins["OutsideWeight"][0]
    return {"Out": [jnp.sum(loss, axis=tuple(range(1, x.ndim)), keepdims=False)[..., None]],
            "Diff": [d]}


# ---------------------------------------------------------------------------
# metrics (accuracy_op.cc; used by fluid.layers.accuracy)
# ---------------------------------------------------------------------------

@register("accuracy", no_grad_slots=("Out", "Indices", "Label"))
def _accuracy(ctx, ins, attrs):
    idx = ins["Indices"][0]
    label = _squeeze_label(ins["Label"][0])
    correct = jnp.any(idx == label[..., None], axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = jnp.asarray(idx.shape[0], jnp.int32)
    acc = num_correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": [acc], "Correct": [num_correct], "Total": [total]}


# ---------------------------------------------------------------------------
# recurrent cells — scan over padded [B,T,*] + length mask.
# Gate order: i, f, c(candidate), o — documented contract for Weight layout.
# ---------------------------------------------------------------------------

def _length_mask(seq_len, B, T, dtype):
    if seq_len is None:
        return jnp.ones((B, T), dtype)
    t = jnp.arange(T)[None, :]
    return (t < seq_len[:, None]).astype(dtype)


def _rnn_pallas_eligible(ctx, B, T, H, dtype, attrs, supported_fn):
    """Shared Pallas-cell dispatch policy (lstm + gru): explicit attr
    wins; otherwise TPU backend + top-level block (control-flow
    sub-blocks differentiate via jax.vjp, which cannot see through a
    pallas_call) + MXU/VMEM-compatible shapes."""
    force = attrs.get("use_pallas_kernel", None)
    if force is not None:
        return bool(force)
    top_level = ctx.block is None or getattr(ctx.block, "idx", 0) == 0
    return (jax.default_backend() == "tpu" and top_level
            and supported_fn(B, T, H, dtype))


def _lstm_pallas_eligible(ctx, B, T, H, dtype, attrs):
    from ..kernels import rnn as _rnn
    return _rnn_pallas_eligible(ctx, B, T, H, dtype, attrs,
                                _rnn.lstm_supported)


@register("lstm", no_grad_slots=("SeqLen",))
def _lstm(ctx, ins, attrs):
    """Fused LSTM over a padded batch (lstm_op.cc + math/lstm_compute
    re-designed for XLA: lax.scan with [B,4H] gate matmuls per step — the
    recurrent matmul rides the MXU, elementwise gates fuse on the VPU).

    Inputs: Input [B,T,4H] (x·Wx + b precomputed by the layer), Weight
    [H,4H] recurrent weights, optional H0/C0 [B,H], optional SeqLen [B].
    Outputs: Hidden [B,T,H], Cell [B,T,H], LastH, LastC [B,H].
    """
    xproj = ins["Input"][0]
    w = ins["Weight"][0]
    B, T, H4 = xproj.shape
    H = H4 // 4
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, H), xproj.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, H), xproj.dtype)
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    mask = _length_mask(seq_len, B, T, xproj.dtype)
    reverse = attrs.get("is_reverse", False)

    # Fused Pallas cell (jit_kernel_rnn.cc analogue): whole scan in one
    # kernel, recurrent weights VMEM-resident.  TPU + MXU-aligned shapes
    # + top-level block only (control-flow sub-blocks differentiate via
    # jax.vjp, which cannot see through a pallas_call — they keep the XLA
    # scan); attr use_pallas_kernel forces it (interpret) for kernel tests.
    use_pallas = _lstm_pallas_eligible(ctx, B, T, H, xproj.dtype, attrs)
    from ..kernels import rnn as _rnn
    if use_pallas:
        xp, mk = (jnp.flip(xproj, 1), jnp.flip(mask, 1)) if reverse \
            else (xproj, mask)
        hs_bt, cs_bt = _rnn.lstm_fused(
            xp, w, h0.astype(xproj.dtype), c0.astype(xproj.dtype),
            mk.astype(jnp.float32))
        h_last, c_last = hs_bt[:, -1], cs_bt[:, -1]
        if reverse:
            hs_bt, cs_bt = jnp.flip(hs_bt, 1), jnp.flip(cs_bt, 1)
        return {"Hidden": [hs_bt], "Cell": [cs_bt],
                "LastH": [h_last], "LastC": [c_last]}

    xs = jnp.swapaxes(xproj, 0, 1)  # [T,B,4H]
    ms = jnp.swapaxes(mask, 0, 1)[..., None]  # [T,B,1]
    if reverse:
        xs, ms = jnp.flip(xs, 0), jnp.flip(ms, 0)

    def step(carry, inp):
        h, c = carry
        x_t, m_t = inp
        gates = x_t + jnp.matmul(h, w)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        c_new = m_t * c_new + (1 - m_t) * c
        h_new = m_t * h_new + (1 - m_t) * h
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = lax.scan(step, (h0, c0), (xs, ms))
    if reverse:
        hs, cs = jnp.flip(hs, 0), jnp.flip(cs, 0)
    return {
        "Hidden": [jnp.swapaxes(hs, 0, 1)],
        "Cell": [jnp.swapaxes(cs, 0, 1)],
        "LastH": [h_last],
        "LastC": [c_last],
    }


@register_grad("lstm")
def _lstm_grad(ctx, ins, attrs):
    """Explicit lstm backward: the Pallas path calls the fused backward
    kernel (gates recomputed in-kernel); other shapes fall back to
    jax.vjp of the XLA scan lowering.  Registered explicitly because the
    axon plugin miscompiles custom_vjp closures under lax.scan (see
    kernels/rnn.py module docstring)."""
    from ..core import registry as _registry
    from ..kernels import rnn as _rnn

    xproj = ins["Input"][0]
    B, T, H4 = xproj.shape
    H = H4 // 4
    if not _lstm_pallas_eligible(ctx, B, T, H, xproj.dtype, attrs):
        fwd_attrs = {**attrs, "use_pallas_kernel": False}
        return _registry.vjp_grad(_registry.get("lstm"), ctx, ins, fwd_attrs)

    w = ins["Weight"][0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, H), xproj.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, H), xproj.dtype)
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    mask = _length_mask(seq_len, B, T, jnp.float32)
    reverse = attrs.get("is_reverse", False)
    hs, cs = ins["Hidden"][0], ins["Cell"][0]

    def grad_or_zeros(slot, shape):
        g = ins.get(slot)
        if g and g[0] is not None:
            return g[0].astype(jnp.float32)
        return jnp.zeros(shape, jnp.float32)

    dhs = grad_or_zeros("Hidden@GRAD", (B, T, H))
    dcs = grad_or_zeros("Cell@GRAD", (B, T, H))
    # move everything into the (possibly flipped) scan domain; LastH/LastC
    # are the scan-domain step T-1 states, so their cotangents fold there
    if reverse:
        xp, mk = jnp.flip(xproj, 1), jnp.flip(mask, 1)
        hs_f, cs_f = jnp.flip(hs, 1), jnp.flip(cs, 1)
        dhs_f, dcs_f = jnp.flip(dhs, 1), jnp.flip(dcs, 1)
    else:
        xp, mk, hs_f, cs_f, dhs_f, dcs_f = xproj, mask, hs, cs, dhs, dcs
    g = ins.get("LastH@GRAD")
    if g and g[0] is not None:
        dhs_f = dhs_f.at[:, -1].add(g[0].astype(jnp.float32))
    g = ins.get("LastC@GRAD")
    if g and g[0] is not None:
        dcs_f = dcs_f.at[:, -1].add(g[0].astype(jnp.float32))

    dxs, dw, dh0, dc0 = _rnn.lstm_fused_grad(
        xp, w, h0.astype(xproj.dtype), c0.astype(xproj.dtype), mk,
        hs_f, cs_f, dhs_f, dcs_f)
    if reverse:
        dxs = jnp.flip(dxs, 1)
    outs = {"Input@GRAD": [dxs], "Weight@GRAD": [dw]}
    if ins.get("H0"):
        outs["H0@GRAD"] = [dh0]
    if ins.get("C0"):
        outs["C0@GRAD"] = [dc0]
    return outs


@register("attention_lstm", no_grad_slots=("SeqLen",))
def _attention_lstm(ctx, ins, attrs):
    """attention_lstm_op.cc: per decode step, a 1-unit additive attention
    over the WHOLE input sequence conditioned on the previous cell state,
    sum-pooled into the LSTM's x input.  Padded redesign: X [B,T,M] with a
    length mask; per step the attention softmax masks padding positions;
    finished rows pass h/c through (same contract as the lstm op).

    Weights: AttentionWeight [(M+D),1] (+AttentionBias [1,1], optional
    AttentionScalar/AttentionScalarBias [1,1]), LSTMWeight [(D+M),4D]
    with the reference's [forget|input|output|candidate] gate order,
    LSTMBias [1,4D]."""
    x = ins["X"][0]                                   # [B,T,M]
    B, T, M = x.shape
    lstm_w = ins["LSTMWeight"][0]                     # [(D+M),4D]
    D = lstm_w.shape[1] // 4
    lstm_b = ins["LSTMBias"][0].reshape(-1)           # [4D]
    atten_w = ins["AttentionWeight"][0]               # [(M+D),1]
    atten_b = (ins["AttentionBias"][0].reshape(())
               if ins.get("AttentionBias") else None)
    atten_s = (ins["AttentionScalar"][0].reshape(())
               if ins.get("AttentionScalar") else None)
    atten_sb = (ins["AttentionScalarBias"][0].reshape(())
                if ins.get("AttentionScalarBias") else None)
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, D), x.dtype)
    c0 = ins["C0"][0]                                 # required (attention)
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    # the whole scan runs in f32 (attention logits + cell state) and the
    # outputs cast back — bf16 carries would both break lax.scan's carry
    # dtype invariant under mixed masking and underflow the -1e30 fill
    cdt = jnp.float32
    mask = _length_mask(seq_len, B, T, cdt)           # [B,T]

    w_x, w_c = (atten_w[:M, 0].astype(cdt),
                atten_w[M:, 0].astype(cdt))           # [M], [D]
    w_h, w_in = lstm_w[:D].astype(cdt), lstm_w[D:].astype(cdt)
    xf = x.astype(cdt)
    atted_x = jnp.einsum("btm,m->bt", xf, w_x)        # [B,T]
    if atten_b is not None:
        atted_x = atted_x + atten_b

    def step(carry, t):
        h, c = carry                                  # [B,D] f32
        e = atted_x + (c * w_c[None, :]).sum(-1, keepdims=True)
        e = jax.nn.relu(e)
        if atten_s is not None:
            e = e * atten_s
            e = jax.nn.relu(e + (atten_sb if atten_sb is not None else 0.0))
        e = jnp.where(mask > 0, e, -1e30)
        alpha = jax.nn.softmax(e, axis=-1)            # [B,T]
        lstm_x = jnp.einsum("bt,btm->bm", alpha, xf)  # [B,M]
        gates = lstm_x @ w_in + h @ w_h + lstm_b.astype(cdt)
        f = jax.nn.sigmoid(gates[:, :D])
        i = jax.nn.sigmoid(gates[:, D:2 * D])
        o = jax.nn.sigmoid(gates[:, 2 * D:3 * D])
        cand = jnp.tanh(gates[:, 3 * D:])
        c_new = f * c + i * cand
        h_new = jnp.tanh(c_new) * o
        m_t = mask[:, t][:, None]
        c_new = m_t * c_new + (1 - m_t) * c
        h_new = m_t * h_new + (1 - m_t) * h
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = lax.scan(
        step, (h0.astype(cdt), c0.astype(cdt)), jnp.arange(T))
    return {"Hidden": [jnp.swapaxes(hs, 0, 1).astype(x.dtype)],
            "Cell": [jnp.swapaxes(cs, 0, 1).astype(x.dtype)],
            "AttentionedX": [atted_x[..., None].astype(x.dtype)],
            # AttentionFCOut/LSTMX/LSTMOUT are per-step SCRATCH in the
            # reference kernel (overwritten every iteration, exposed only
            # because C++ kernels need declared workspaces); emitted as
            # shape-correct zero placeholders here
            "AttentionFCOut": [jnp.zeros((B, T, 1), x.dtype)],
            "LSTMX": [jnp.zeros((B, M), x.dtype)],
            "LSTMOUT": [jnp.zeros((B, 4 * D), x.dtype)]}


def _gru_pallas_eligible(ctx, B, T, H, dtype, attrs):
    from ..kernels import rnn as _rnn
    return _rnn_pallas_eligible(ctx, B, T, H, dtype, attrs,
                                _rnn.gru_supported)


@register("gru", no_grad_slots=("SeqLen",))
def _gru(ctx, ins, attrs):
    """Fused GRU over a padded batch (gru_op.cc + math/gru_compute).
    Input [B,T,3H] (x-projection), Weight [H,3H] as [update|reset|candidate].
    """
    xproj = ins["Input"][0]
    w = ins["Weight"][0]
    B, T, H3 = xproj.shape
    H = H3 // 3
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, H), xproj.dtype)
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    mask = _length_mask(seq_len, B, T, xproj.dtype)
    reverse = attrs.get("is_reverse", False)

    # Fused Pallas cell (same dispatch contract as lstm above)
    use_pallas = _gru_pallas_eligible(ctx, B, T, H, xproj.dtype, attrs)
    if use_pallas:
        from ..kernels import rnn as _rnn
        xp, mk = (jnp.flip(xproj, 1), jnp.flip(mask, 1)) if reverse \
            else (xproj, mask)
        hs_bt = _rnn.gru_fused(xp, w, h0.astype(xproj.dtype),
                               mk.astype(jnp.float32))
        h_last = hs_bt[:, -1]
        if reverse:
            hs_bt = jnp.flip(hs_bt, 1)
        return {"Hidden": [hs_bt], "LastH": [h_last]}

    w_uz = w[:, : 2 * H]
    w_c = w[:, 2 * H :]
    xs = jnp.swapaxes(xproj, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)[..., None]
    if reverse:
        xs, ms = jnp.flip(xs, 0), jnp.flip(ms, 0)

    def step(h, inp):
        x_t, m_t = inp
        x_uz, x_c = x_t[:, : 2 * H], x_t[:, 2 * H :]
        uz = jax.nn.sigmoid(x_uz + jnp.matmul(h, w_uz))
        u, r = uz[:, :H], uz[:, H:]
        c = jnp.tanh(x_c + jnp.matmul(r * h, w_c))
        h_new = u * h + (1 - u) * c
        h_new = m_t * h_new + (1 - m_t) * h
        return h_new, h_new

    h_last, hs = lax.scan(step, h0, (xs, ms))
    if reverse:
        hs = jnp.flip(hs, 0)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "LastH": [h_last]}


@register_grad("gru")
def _gru_grad(ctx, ins, attrs):
    """Explicit gru backward: Pallas path calls the fused backward kernel
    (gates recomputed in-kernel); other shapes fall back to jax.vjp of
    the XLA scan lowering (same rationale as _lstm_grad)."""
    from ..core import registry as _registry
    from ..kernels import rnn as _rnn

    xproj = ins["Input"][0]
    B, T, H3 = xproj.shape
    H = H3 // 3
    if not _gru_pallas_eligible(ctx, B, T, H, xproj.dtype, attrs):
        fwd_attrs = {**attrs, "use_pallas_kernel": False}
        return _registry.vjp_grad(_registry.get("gru"), ctx, ins, fwd_attrs)

    w = ins["Weight"][0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, H), xproj.dtype)
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    mask = _length_mask(seq_len, B, T, jnp.float32)
    reverse = attrs.get("is_reverse", False)
    hs = ins["Hidden"][0]

    g = ins.get("Hidden@GRAD")
    dhs = (g[0].astype(jnp.float32) if g and g[0] is not None
           else jnp.zeros((B, T, H), jnp.float32))
    if reverse:
        xp, mk = jnp.flip(xproj, 1), jnp.flip(mask, 1)
        hs_f, dhs_f = jnp.flip(hs, 1), jnp.flip(dhs, 1)
    else:
        xp, mk, hs_f, dhs_f = xproj, mask, hs, dhs
    g = ins.get("LastH@GRAD")
    if g and g[0] is not None:
        dhs_f = dhs_f.at[:, -1].add(g[0].astype(jnp.float32))

    dxs, dw, dh0 = _rnn.gru_fused_grad(
        xp, w, h0.astype(xproj.dtype), mk, hs_f, dhs_f)
    if reverse:
        dxs = jnp.flip(dxs, 1)
    outs = {"Input@GRAD": [dxs], "Weight@GRAD": [dw]}
    if ins.get("H0"):
        outs["H0@GRAD"] = [dh0]
    return outs


@register("fused_fc")
def _fused_fc(ctx, ins, attrs):
    """Fused mul + bias + activation, emitted by the inference fc fuser
    (framework/ir/fc_fuse_pass.cc analogue; see inference/passes.py).
    Delegates to the registered mul/elementwise_add/act lowerings so the
    fused op is semantics-identical to the chain it replaced."""
    from ..core import registry as _registry

    out = _registry.get("mul").lower(
        ctx, {"X": ins["X"], "Y": ins["W"]},
        {"x_num_col_dims": attrs.get("x_num_col_dims", 1),
         "y_num_col_dims": attrs.get("y_num_col_dims", 1)})["Out"][0]
    if ins.get("Bias"):
        out = _registry.get("elementwise_add").lower(
            ctx, {"X": [out], "Y": ins["Bias"]},
            {"axis": attrs.get("axis", -1)})["Out"][0]
    act = attrs.get("act") or ""
    if act:
        out = _registry.get(act).lower(
            ctx, {"X": [out]}, dict(attrs.get("act_attrs") or {}))["Out"][0]
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# 3-D conv / pool family (conv3d_op, pool3d, conv3d_transpose — NCDHW)
# ---------------------------------------------------------------------------

def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


@register("conv3d")
def _conv3d(ctx, ins, attrs):
    """conv_op.cc 3-D branch: NCDHW activations, OIDHW filters."""
    x, w = ins["Input"][0], ins["Filter"][0]
    st = _triple(attrs.get("strides", [1, 1, 1]))
    pd = _triple(attrs.get("paddings", [0, 0, 0]))
    dl = _triple(attrs.get("dilations", [1, 1, 1]))
    out = lax.conv_general_dilated(
        x, w, window_strides=st,
        padding=[(pd[0], pd[0]), (pd[1], pd[1]), (pd[2], pd[2])],
        rhs_dilation=dl,
        feature_group_count=attrs.get("groups", 1) or 1,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    return {"Output": [out]}


@register("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    """conv_transpose_op 3-D branch: input-gradient of a forward conv3d."""
    x, w = ins["Input"][0], ins["Filter"][0]
    st = _triple(attrs.get("strides", [1, 1, 1]))
    pd = _triple(attrs.get("paddings", [0, 0, 0]))
    dl = _triple(attrs.get("dilations", [1, 1, 1]))
    groups = attrs.get("groups", 1) or 1
    n = x.shape[0]
    _, cout_pg, kd, kh, kw = w.shape
    cout = cout_pg * groups
    dims = [(x.shape[2 + i] - 1) * st[i] - 2 * pd[i]
            + dl[i] * (w.shape[2 + i] - 1) + 1 for i in range(3)]

    def fwd(y):
        return lax.conv_general_dilated(
            y, w, window_strides=st,
            padding=[(pd[0], pd[0]), (pd[1], pd[1]), (pd[2], pd[2])],
            rhs_dilation=dl,
            feature_group_count=groups,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )

    _, vjp_fn = jax.vjp(fwd, jnp.zeros((n, cout) + tuple(dims), x.dtype))
    (out,) = vjp_fn(x)
    return {"Output": [out]}


@register("pool3d")
def _pool3d(ctx, ins, attrs):
    """pool_op.cc 3-D branch (NCDHW max/avg)."""
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        ks = x.shape[2:5]
        st, pd = ks, (0, 0, 0)
    else:
        ks = _triple(attrs["ksize"])
        st = _triple(attrs.get("strides", [1, 1, 1]))
        pd = _triple(attrs.get("paddings", [0, 0, 0]))
    window = (1, 1) + tuple(ks)
    strides = (1, 1) + tuple(st)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides, padding)
    else:
        summed = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add,
                                   window, strides, padding)
        if attrs.get("exclusive", True) and any(pd):
            ones = jnp.ones((1, 1) + x.shape[2:5], jnp.float32)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                       padding)
            out = (summed / counts).astype(x.dtype)
        else:
            out = (summed / float(np.prod(ks))).astype(x.dtype)
    return {"Out": [out]}


@register("lstmp", no_grad_slots=("SeqLen",))
def _lstmp(ctx, ins, attrs):
    """lstmp_op.cc: LSTM with recurrent projection (Sak et al. 2014).
    Input [B,T,4H] (x-projection), Weight [P,4H] recurrent weights over the
    projected state, ProjWeight [H,P].  Outputs Projection [B,T,P] and
    Cell [B,T,H]."""
    xproj = ins["Input"][0]
    w = ins["Weight"][0]
    wproj = ins["ProjWeight"][0]
    B, T, H4 = xproj.shape
    H = H4 // 4
    P = wproj.shape[1]
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, H), xproj.dtype)
    r0 = ins["H0"][0] @ wproj if ins.get("H0") \
        else jnp.zeros((B, P), xproj.dtype)
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    mask = _length_mask(seq_len, B, T, xproj.dtype)
    reverse = attrs.get("is_reverse", False)
    proj_act = attrs.get("proj_activation", "identity")

    xs = jnp.swapaxes(xproj, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)[..., None]
    if reverse:
        xs, ms = jnp.flip(xs, 0), jnp.flip(ms, 0)

    def step(carry, inp):
        r, c = carry
        x_t, m_t = inp
        gates = x_t + jnp.matmul(r, w)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        r_new = jnp.matmul(h_new, wproj)
        if proj_act == "tanh":
            r_new = jnp.tanh(r_new)
        elif proj_act == "relu":
            r_new = jax.nn.relu(r_new)
        c_new = m_t * c_new + (1 - m_t) * c
        r_new = m_t * r_new + (1 - m_t) * r
        return (r_new, c_new), (r_new, c_new)

    (r_last, c_last), (rs, cs) = lax.scan(step, (r0, c0), (xs, ms))
    if reverse:
        rs, cs = jnp.flip(rs, 0), jnp.flip(cs, 0)
    return {
        "Projection": [jnp.swapaxes(rs, 0, 1)],
        "Cell": [jnp.swapaxes(cs, 0, 1)],
        "LastH": [r_last],
        "LastC": [c_last],
    }


def _fused_lstm_tail(ctx, op_name, xproj, ins, attrs):
    """Shared tail of the fused-LSTM family: bias add on the x-projection,
    carry slots forwarded, the lstm scan, {Hidden, Cell, XX} packaging."""
    if attrs.get("use_peepholes", False):
        raise NotImplementedError(
            f"{op_name}: use_peepholes=True (the [1, 7D] bias layout) is "
            "not ported; the in-scope models run peephole-free")
    if ins.get("Bias"):
        xproj = xproj + ins["Bias"][0].reshape(1, 1, -1)
    sub = {"Input": [xproj], "Weight": [ins["WeightH"][0]]}
    for slot in ("H0", "C0", "SeqLen"):
        if ins.get(slot):
            sub[slot] = ins[slot]
    # training: XLA scan only — the fused family's backward is vjp_grad
    # through this lowering and jax.vjp cannot see through a pallas_call.
    # Inference (ctx.training False, e.g. the Predictor after the
    # fuse_fc_lstm pass) keeps the Pallas cell dispatch.
    if ctx.training:
        attrs = {**attrs, "use_pallas_kernel": False}
    out = _lstm(ctx, sub, attrs)
    return {"Hidden": out["Hidden"], "Cell": out["Cell"], "XX": [xproj]}


@register("fusion_lstm", no_grad_slots=("SeqLen",))
def _fusion_lstm(ctx, ins, attrs):
    """fusion_lstm_op.cc: fc(x) + LSTM in one op (the CPU jit_kernel
    fusion; on TPU one XLA region anyway).  X [B,T,M], WeightX [M,4D],
    WeightH [D,4D], Bias [1,4D]; reuses the lstm scan lowering."""
    xproj = jnp.einsum("btm,mf->btf", ins["X"][0], ins["WeightX"][0])
    return _fused_lstm_tail(ctx, "fusion_lstm", xproj, ins, attrs)


@register("fusion_gru", no_grad_slots=("SeqLen",))
def _fusion_gru(ctx, ins, attrs):
    """fusion_gru_op.cc: fc(x) + GRU in one op; reuses the gru scan."""
    x = ins["X"][0]
    wx = ins["WeightX"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    xproj = jnp.einsum("btm,mf->btf", x, wx)
    if bias is not None:
        xproj = xproj + bias.reshape(1, 1, -1)
    sub = {"Input": [xproj], "Weight": [ins["WeightH"][0]]}
    for slot in ("H0", "SeqLen"):
        if ins.get(slot):
            sub[slot] = ins[slot]
    # training: XLA scan only (vjp cannot see through the Pallas cell);
    # inference keeps the Pallas dispatch (see _fused_lstm_tail)
    if ctx.training:
        attrs = {**attrs, "use_pallas_kernel": False}
    out = _gru(ctx, sub, attrs)
    return {"Hidden": out["Hidden"], "XX": [xproj]}


@register("fused_elemwise_activation")
def _fused_elemwise_activation(ctx, ins, attrs):
    """fused_elemwise_activation_op.cc: functor_list pairs like
    ["elementwise_add", "relu"] / ["relu", "elementwise_add"] — binary op
    and unary activation composed in one op (XLA fuses either way; the
    op exists for graph parity with the reference's fusion passes)."""
    x, y = ins["X"][0], ins["Y"][0]
    functors = [f.lower() for f in attrs["functor_list"]]
    axis = attrs.get("axis", -1)

    def binary(name, a, b):
        if b.ndim < a.ndim and axis != -1:
            b = b.reshape(b.shape + (1,) * (a.ndim - b.ndim - axis))
        return {"elementwise_add": a + b, "elementwise_sub": a - b,
                "elementwise_mul": a * b}[name]

    def unary(name, a):
        return {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
                "tanh": jnp.tanh, "scale": lambda v: v * attrs.get(
                    "scale", 1.0)}[name](a)

    if functors[0].startswith("elementwise"):
        inter = binary(functors[0], x, y)
        out = unary(functors[1], inter)
    else:
        inter = unary(functors[0], y)
        out = binary(functors[1], x, inter)
    return {"Out": [out], "IntermediateOut": [inter]}


@register("fused_embedding_fc_lstm", no_grad_slots=("Ids", "SeqLen"))
def _fused_embedding_fc_lstm(ctx, ins, attrs):
    """fused_embedding_fc_lstm_op.cc: the embedding table IS the
    pre-multiplied x-projection (Embeddings [V, 4D] = emb @ Wx fused
    offline), so a lookup replaces the fc; then the LSTM scan."""
    ids = ins["Ids"][0]
    table = ins["Embeddings"][0]
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    xproj = table[ids.astype(jnp.int32)]          # [B, T, 4D]
    return _fused_lstm_tail(ctx, "fused_embedding_fc_lstm", xproj, ins,
                            attrs)


@register("fusion_seqexpand_concat_fc", no_grad_slots=("SeqLen",))
def _fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """fusion_seqexpand_concat_fc_op.cc: X[0] is a [B,T,D0] sequence, the
    rest are per-batch [B,Di] rows broadcast over T; concat features,
    fc + activation in one op."""
    xs = ins["X"]
    seq = xs[0]
    B, T = seq.shape[0], seq.shape[1]
    parts = [seq]
    for x in xs[1:]:
        parts.append(jnp.broadcast_to(x[:, None, :], (B, T, x.shape[-1])))
    cat = jnp.concatenate(parts, axis=-1)
    w = ins["FCWeight"][0]
    out = jnp.einsum("btm,mf->btf", cat, w)
    if ins.get("FCBias"):
        out = out + ins["FCBias"][0].reshape(1, 1, -1)
    act = attrs.get("fc_activation", "identity")
    acts = {"identity": lambda v: v, "relu": jax.nn.relu,
            "tanh": jnp.tanh, "sigmoid": jax.nn.sigmoid}
    if act not in acts:
        raise ValueError(
            f"fusion_seqexpand_concat_fc: unknown fc_activation {act!r} "
            f"(supported: {sorted(acts)})")
    return {"Out": [acts[act](out)]}
