"""Optimizer op lowerings — per-parameter device-side updates.

Reference coverage: ``sgd_op``, ``momentum_op``, ``adam_op``, ``adagrad_op``,
``adamax_op``, ``adadelta_op``, ``rmsprop_op``, ``ftrl_op``,
``decayed_adagrad_op``, ``lars_momentum`` (paddle/fluid/operators/*.cc).

These ops write to persistable vars (ParamOut aliases Param etc.); the
executor detects the writes and returns updated state — functional in-place
updates with donated buffers, so XLA reuses the parameter's HBM allocation.
Accumulator math runs in the accumulator's own dtype (keep fp32 accumulators
under bf16 params — the standard TPU mixed-precision recipe).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.registry import register
from ..core.selected_rows import (
    SelectedRows, dense_grad_and_mask, gather_rows, merge_rows,
    prefer_dense_update, scatter_set_rows)
from ..kernels import sparse as sparse_kernels


def _lr(ins, dtype=None):
    lr = ins["LearningRate"][0]
    lr = lr.reshape(()) if hasattr(lr, "reshape") else lr
    return lr.astype(dtype) if dtype is not None else lr


def _is_sparse(g):
    return isinstance(g, SelectedRows)


def _dense_only(g, op):
    if isinstance(g, SelectedRows):
        raise NotImplementedError(
            f"optimizer op {op!r} has no sparse (SelectedRows) update path; "
            "use sgd/momentum/adam/adagrad for is_sparse embeddings")
    return g


@register("sgd")
def _sgd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins, p.dtype)
    if _is_sparse(g):
        # sparse path (sgd_op.h:47-52): scatter-add touches only the looked-up
        # rows; duplicates accumulate, which is exact for plain SGD
        return {"ParamOut": [
            p.at[g.rows].add(-lr * g.values.astype(p.dtype), mode="drop")]}
    return {"ParamOut": [p - lr * g.astype(p.dtype)]}


@register("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = jnp.asarray(attrs.get("mu", 0.9), v.dtype)
    lr = _lr(ins, v.dtype)
    if _is_sparse(g):
        if sparse_kernels.enabled_for(ctx):
            # g stays in its own dtype: the sorted reference merges
            # duplicates BEFORE casting, so only f32-valued grads are
            # fused (others fall back inside, counted)
            fused = sparse_kernels.fused_momentum(
                p, v, g, lr, attrs.get("mu", 0.9),
                attrs.get("use_nesterov", False))
            if fused is not None:
                ctx.sparse_fused_used = True
                p_new, v_new = fused
                return {"ParamOut": [p_new], "VelocityOut": [v_new]}
        if prefer_dense_update(g):
            gd, t = dense_grad_and_mask(g, v.dtype)
            v_new = jnp.where(t, mu * v + gd, v)
            pf = p.astype(v.dtype)
            if attrs.get("use_nesterov", False):
                p_new = jnp.where(t, pf - (gd + mu * v_new) * lr, pf)
            else:
                p_new = jnp.where(t, pf - lr * v_new, pf)
            return {"ParamOut": [p_new.astype(p.dtype)],
                    "VelocityOut": [v_new]}
        m = merge_rows(g)
        rows, gf = m.rows, m.values.astype(v.dtype)
        vr = gather_rows(v, rows)
        pr = gather_rows(p, rows).astype(v.dtype)
        v_new_r = mu * vr + gf
        if attrs.get("use_nesterov", False):
            p_new_r = pr - (gf + mu * v_new_r) * lr
        else:
            p_new_r = pr - lr * v_new_r
        return {"ParamOut": [scatter_set_rows(p, rows, p_new_r)],
                "VelocityOut": [scatter_set_rows(v, rows, v_new_r)]}
    g = g.astype(v.dtype)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new).astype(p.dtype) * lr.astype(p.dtype)
    else:
        p_new = p - (lr * v_new).astype(p.dtype)
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register("adam")
def _adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    beta1 = jnp.asarray(attrs.get("beta1", 0.9), m1.dtype)
    beta2 = jnp.asarray(attrs.get("beta2", 0.999), m2.dtype)
    eps = jnp.asarray(attrs.get("epsilon", 1e-8), m1.dtype)
    if _is_sparse(g):
        # sparse (lazy) adam: update moments and param for touched rows only
        # (reference adam_op.h SelectedRows path)
        lr = (_lr(ins, m1.dtype)
              * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(())))
        if sparse_kernels.enabled_for(ctx):
            fused = sparse_kernels.fused_adam(
                p, m1, m2, g, lr, attrs.get("beta1", 0.9),
                attrs.get("beta2", 0.999), attrs.get("epsilon", 1e-8))
            if fused is not None:
                ctx.sparse_fused_used = True
                p_new, m1n, m2n = fused
                return {
                    "ParamOut": [p_new],
                    "Moment1Out": [m1n],
                    "Moment2Out": [m2n],
                    "Beta1PowOut": [b1p * beta1],
                    "Beta2PowOut": [b2p * beta2],
                }
        if prefer_dense_update(g):
            gd, t = dense_grad_and_mask(g, m1.dtype)
            m1n = jnp.where(t, beta1 * m1 + (1 - beta1) * gd, m1)
            m2n = jnp.where(t, beta2 * m2 + (1 - beta2) * gd * gd, m2)
            step = lr * m1n / (jnp.sqrt(m2n) + eps)
            pf = p.astype(m1.dtype)
            return {
                "ParamOut": [jnp.where(t, pf - step, pf).astype(p.dtype)],
                "Moment1Out": [m1n],
                "Moment2Out": [m2n],
                "Beta1PowOut": [b1p * beta1],
                "Beta2PowOut": [b2p * beta2],
            }
        m = merge_rows(g)
        rows, gf = m.rows, m.values.astype(m1.dtype)
        m1r, m2r = gather_rows(m1, rows), gather_rows(m2, rows)
        pr = gather_rows(p, rows).astype(m1.dtype)
        m1n = beta1 * m1r + (1 - beta1) * gf
        m2n = beta2 * m2r + (1 - beta2) * gf * gf
        step = lr * m1n / (jnp.sqrt(m2n) + eps)
        return {
            "ParamOut": [scatter_set_rows(p, rows, pr - step)],
            "Moment1Out": [scatter_set_rows(m1, rows, m1n)],
            "Moment2Out": [scatter_set_rows(m2, rows, m2n)],
            "Beta1PowOut": [b1p * beta1],
            "Beta2PowOut": [b2p * beta2],
        }
    gf = g.astype(m1.dtype)
    m1n = beta1 * m1 + (1 - beta1) * gf
    m2n = beta2 * m2 + (1 - beta2) * gf * gf
    lr = _lr(ins, m1.dtype) * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    step = lr * m1n / (jnp.sqrt(m2n) + eps)
    return {
        "ParamOut": [(p.astype(m1.dtype) - step).astype(p.dtype)],
        "Moment1Out": [m1n],
        "Moment2Out": [m2n],
        "Beta1PowOut": [b1p * beta1],
        "Beta2PowOut": [b2p * beta2],
    }


@register("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = jnp.asarray(attrs.get("epsilon", 1e-6), mom.dtype)
    if _is_sparse(g):
        if sparse_kernels.enabled_for(ctx):
            fused = sparse_kernels.fused_adagrad(
                p, mom, g, _lr(ins, mom.dtype), attrs.get("epsilon", 1e-6))
            if fused is not None:
                ctx.sparse_fused_used = True
                p_new, mom_new = fused
                return {"ParamOut": [p_new], "MomentOut": [mom_new]}
        if prefer_dense_update(g):
            gd, t = dense_grad_and_mask(g, mom.dtype)
            mom_new = jnp.where(t, mom + gd * gd, mom)
            pf = p.astype(mom.dtype)
            step = _lr(ins, mom.dtype) * gd / (jnp.sqrt(mom_new) + eps)
            return {"ParamOut": [jnp.where(t, pf - step, pf).astype(p.dtype)],
                    "MomentOut": [mom_new]}
        m = merge_rows(g)
        rows, gf = m.rows, m.values.astype(mom.dtype)
        momr = gather_rows(mom, rows)
        pr = gather_rows(p, rows).astype(mom.dtype)
        mom_new_r = momr + gf * gf
        p_new_r = pr - _lr(ins, mom.dtype) * gf / (jnp.sqrt(mom_new_r) + eps)
        return {"ParamOut": [scatter_set_rows(p, rows, p_new_r)],
                "MomentOut": [scatter_set_rows(mom, rows, mom_new_r)]}
    gf = g.astype(mom.dtype)
    mom_new = mom + gf * gf
    p_new = p - (_lr(ins, mom.dtype) * gf / (jnp.sqrt(mom_new) + eps)).astype(p.dtype)
    return {"ParamOut": [p_new], "MomentOut": [mom_new]}


@register("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    ins = {**ins, "Grad": [_dense_only(ins["Grad"][0], "decayed_adagrad")]}
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    decay = jnp.asarray(attrs.get("decay", 0.95), mom.dtype)
    eps = jnp.asarray(attrs.get("epsilon", 1e-6), mom.dtype)
    gf = g.astype(mom.dtype)
    mom_new = decay * mom + (1 - decay) * gf * gf
    p_new = p - (_lr(ins, mom.dtype) * gf / (jnp.sqrt(mom_new) + eps)).astype(p.dtype)
    return {"ParamOut": [p_new], "MomentOut": [mom_new]}


@register("adamax")
def _adamax(ctx, ins, attrs):
    ins = {**ins, "Grad": [_dense_only(ins["Grad"][0], "adamax")]}
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    beta1 = jnp.asarray(attrs.get("beta1", 0.9), m.dtype)
    beta2 = jnp.asarray(attrs.get("beta2", 0.999), m.dtype)
    eps = jnp.asarray(attrs.get("epsilon", 1e-8), m.dtype)
    gf = g.astype(m.dtype)
    m_new = beta1 * m + (1 - beta1) * gf
    inf_new = jnp.maximum(beta2 * inf, jnp.abs(gf))
    lr = _lr(ins, m.dtype) / (1 - b1p.reshape(()))
    p_new = p - (lr * m_new / (inf_new + eps)).astype(p.dtype)
    return {"ParamOut": [p_new], "MomentOut": [m_new], "InfNormOut": [inf_new],
            "Beta1PowOut": [b1p * beta1]}


@register("adadelta")
def _adadelta(ctx, ins, attrs):
    ins = {**ins, "Grad": [_dense_only(ins["Grad"][0], "adadelta")]}
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = jnp.asarray(attrs.get("rho", 0.95), avg_sq_g.dtype)
    eps = jnp.asarray(attrs.get("epsilon", 1e-6), avg_sq_g.dtype)
    gf = g.astype(avg_sq_g.dtype)
    asg_new = rho * avg_sq_g + (1 - rho) * gf * gf
    update = -jnp.sqrt((avg_sq_u + eps) / (asg_new + eps)) * gf
    asu_new = rho * avg_sq_u + (1 - rho) * update * update
    return {"ParamOut": [(p.astype(gf.dtype) + update).astype(p.dtype)],
            "AvgSquaredGradOut": [asg_new], "AvgSquaredUpdateOut": [asu_new]}


@register("rmsprop")
def _rmsprop(ctx, ins, attrs):
    ins = {**ins, "Grad": [_dense_only(ins["Grad"][0], "rmsprop")]}
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    rho = jnp.asarray(attrs.get("decay", 0.95), ms.dtype)
    eps = jnp.asarray(attrs.get("epsilon", 1e-6), ms.dtype)
    momentum = jnp.asarray(attrs.get("momentum", 0.0), ms.dtype)
    gf = g.astype(ms.dtype)
    ms_new = rho * ms + (1 - rho) * gf * gf
    if attrs.get("centered", False):
        mg = ins["MeanGrad"][0]
        mg_new = rho * mg + (1 - rho) * gf
        denom = ms_new - mg_new * mg_new + eps
    else:
        mg_new = None
        denom = ms_new + eps
    mom_new = momentum * mom + _lr(ins, ms.dtype) * gf * lax.rsqrt(denom)
    out = {"ParamOut": [(p.astype(gf.dtype) - mom_new).astype(p.dtype)],
           "MeanSquareOut": [ms_new], "MomentOut": [mom_new]}
    if mg_new is not None:
        out["MeanGradOut"] = [mg_new]
    return out


@register("ftrl")
def _ftrl(ctx, ins, attrs):
    ins = {**ins, "Grad": [_dense_only(ins["Grad"][0], "ftrl")]}
    p, g = ins["Param"][0], ins["Grad"][0]
    sq_acc, lin_acc = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = jnp.asarray(attrs.get("l1", 0.0), sq_acc.dtype)
    l2 = jnp.asarray(attrs.get("l2", 0.0), sq_acc.dtype)
    lr_power = jnp.asarray(attrs.get("lr_power", -0.5), sq_acc.dtype)
    lr = _lr(ins, sq_acc.dtype)
    gf = g.astype(sq_acc.dtype)
    new_sq = sq_acc + gf * gf
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq_acc, -lr_power)) / lr
    lin_new = lin_acc + gf - sigma * p.astype(sq_acc.dtype)
    x = jnp.clip(lin_new, -l1, l1) - lin_new
    y = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    p_new = (x / y).astype(p.dtype)
    return {"ParamOut": [p_new], "SquaredAccumOut": [new_sq], "LinearAccumOut": [lin_new]}


@register("lars_momentum")
def _lars_momentum(ctx, ins, attrs):
    ins = {**ins, "Grad": [_dense_only(ins["Grad"][0], "lars_momentum")]}
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = jnp.asarray(attrs.get("mu", 0.9), v.dtype)
    lars_coeff = attrs.get("lars_coeff", 1e-3)
    lars_wd = attrs.get("lars_weight_decay", 5e-4)
    lr = _lr(ins, v.dtype)
    gf = g.astype(v.dtype)
    pf = p.astype(v.dtype)
    p_norm = jnp.sqrt(jnp.sum(pf * pf))
    g_norm = jnp.sqrt(jnp.sum(gf * gf))
    local_lr = lr * lars_coeff * p_norm / (g_norm + lars_wd * p_norm + 1e-12)
    v_new = mu * v + local_lr * (gf + lars_wd * pf)
    return {"ParamOut": [(pf - v_new).astype(p.dtype)], "VelocityOut": [v_new]}


@register("average_accumulates",
          no_grad_slots=("param", "in_sum_1", "in_sum_2", "in_sum_3",
                         "in_num_accumulates", "in_old_num_accumulates",
                         "in_num_updates"))
def _average_accumulates(ctx, ins, attrs):
    """average_accumulates_op.h: sliding-window parameter sums for
    ModelAverage.  sum_1 accumulates every step; every 16384 updates it
    rolls into sum_2 (precision); when the window closes (num_accumulates
    >= min(max_window, num_updates*window_rate)) everything rolls into
    sum_3 and the window restarts."""
    k_max = 16384
    param = ins["param"][0]
    s1, s2, s3 = ins["in_sum_1"][0], ins["in_sum_2"][0], ins["in_sum_3"][0]
    num_acc = ins["in_num_accumulates"][0].reshape(()).astype(jnp.int64)
    old_acc = ins["in_old_num_accumulates"][0].reshape(()).astype(jnp.int64)
    num_upd = ins["in_num_updates"][0].reshape(()).astype(jnp.int64)
    window = float(attrs.get("average_window", 0.0))
    max_w = int(attrs.get("max_average_window", 2 ** 62))
    min_w = int(attrs.get("min_average_window", 10000))

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + param.astype(s1.dtype)

    roll_precision = (num_upd % k_max) == 0
    s2 = jnp.where(roll_precision, s2 + s1, s2)
    s1 = jnp.where(roll_precision, 0.0, s1)

    close = (num_acc >= min_w) & (
        num_acc >= jnp.minimum(
            jnp.asarray(max_w, jnp.int64),
            (num_upd.astype(jnp.float32) * window).astype(jnp.int64)))
    s3 = jnp.where(close, s1 + s2 + s3 * 0, s3)
    s1 = jnp.where(close, 0.0, s1)
    s2 = jnp.where(close, 0.0, s2)
    old_acc = jnp.where(close, num_acc, old_acc)
    num_acc = jnp.where(close, 0, num_acc)

    return {"out_sum_1": [s1], "out_sum_2": [s2], "out_sum_3": [s3],
            "out_num_accumulates": [num_acc.reshape(1)],
            "out_old_num_accumulates": [old_acc.reshape(1)],
            "out_num_updates": [num_upd.reshape(1)]}


def _prox(prox_param, lr, l1, l2):
    """Proximal step (proximal_gd_op.cc): soft-threshold by lr*l1 then
    shrink by 1/(1+lr*l2)."""
    return (jnp.sign(prox_param)
            * jnp.maximum(jnp.abs(prox_param) - lr * l1, 0.0)
            / (1.0 + lr * l2))


@register("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    _dense_only(g, "proximal_gd")
    lr = _lr(ins, jnp.float32)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p.astype(jnp.float32) - lr * g.astype(jnp.float32)
    return {"ParamOut": [_prox(prox, lr, l1, l2).astype(p.dtype)]}


@register("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    _dense_only(g, "proximal_adagrad")
    mom = ins["Moment"][0]
    lr = _lr(ins, jnp.float32)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    gf = g.astype(mom.dtype)
    mom_out = mom + gf * gf
    eff_lr = lr / jnp.sqrt(mom_out + 1e-12)
    prox = p.astype(jnp.float32) - eff_lr * gf
    return {"ParamOut": [_prox(prox, eff_lr, l1, l2).astype(p.dtype)],
            "MomentOut": [mom_out]}
