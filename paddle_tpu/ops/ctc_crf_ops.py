"""CTC and linear-chain CRF: the structured sequence losses.

Reference: ``paddle/fluid/operators/warpctc_op.cc`` (wrapping the warpctc
CUDA/CPU library), ``ctc_align_op.cc`` (greedy decode cleanup),
``linear_chain_crf_op.cc`` and ``crf_decoding_op.cc``.

TPU-native redesign: both dynamic programs run as ``lax.scan`` over time
in log space — fully differentiable by reverse-scan autodiff, so there is
no hand-written gradient kernel (warpctc's grad output becomes plain
jax.vjp through the DP).  Batched over padded sequences with explicit
per-row logit/label lengths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, register_grad

NEG = -1e30


def _log_softmax_time(logits):
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)


@register("warpctc", no_grad_slots=("Label", "LogitsLength", "LabelLength"))
def _warpctc(ctx, ins, attrs):
    """CTC loss (warpctc_op.cc capability).  Logits [B, T, C] (padded,
    ``LogitsLength`` [B]), Label [B, L] (padded, ``LabelLength`` [B]),
    attr ``blank``.  Returns per-sequence negative log-likelihood [B, 1].
    norm_by_times divides by the logit length."""
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[..., 0]
    B, T, C = logits.shape
    L = label.shape[1]
    blank = int(attrs.get("blank", 0))
    logit_len = (ins["LogitsLength"][0].reshape(-1).astype(jnp.int32)
                 if ins.get("LogitsLength")
                 else jnp.full((B,), T, jnp.int32))
    label_len = (ins["LabelLength"][0].reshape(-1).astype(jnp.int32)
                 if ins.get("LabelLength")
                 else jnp.full((B,), L, jnp.int32))

    logp = _log_softmax_time(logits)                     # [B,T,C]
    S = 2 * L + 1
    # extended label: blank, l0, blank, l1, …, blank
    ext = jnp.full((B, S), blank, label.dtype)
    ext = ext.at[:, 1::2].set(label)
    s_idx = jnp.arange(S)[None, :]
    is_label = (s_idx % 2) == 1
    # skip transition s-2→s allowed when ext[s] is a label differing from
    # ext[s-2]
    prev2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)), constant_values=blank)
    can_skip = is_label & (ext != prev2)
    valid_s = s_idx < (2 * label_len[:, None] + 1)

    def emit(t):
        return jnp.take_along_axis(logp[:, t], ext.astype(jnp.int32), axis=1)

    a0 = jnp.full((B, S), NEG, jnp.float32)
    a0 = a0.at[:, 0].set(emit(0)[:, 0])
    a0 = a0.at[:, 1].set(jnp.where(label_len > 0, emit(0)[:, 1], NEG))
    a0 = jnp.where(valid_s, a0, NEG)

    def step(alpha, t):
        sh1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=NEG)
        sh2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=NEG)
        sh2 = jnp.where(can_skip, sh2, NEG)
        prev = jnp.logaddexp(jnp.logaddexp(alpha, sh1), sh2)
        new = prev + emit(t)
        new = jnp.where(valid_s, new, NEG)
        active = (t < logit_len)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, T))
    # final: logsumexp over positions 2*label_len (last blank) and
    # 2*label_len-1 (last label)
    last = jnp.take_along_axis(alpha, (2 * label_len[:, None]), axis=1)[:, 0]
    seclast = jnp.take_along_axis(
        alpha, jnp.maximum(2 * label_len[:, None] - 1, 0), axis=1)[:, 0]
    seclast = jnp.where(label_len > 0, seclast, NEG)
    loss = -jnp.logaddexp(last, seclast)
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(logit_len.astype(jnp.float32), 1)
    return {"Loss": [loss[:, None].astype(logits.dtype)]}


@register("ctc_align", no_grad_slots=("Input", "InputLength"))
def _ctc_align(ctx, ins, attrs):
    """Greedy CTC decode cleanup (ctc_align_op.cc): merge repeats, drop
    blanks, left-compact.  Input [B, T] argmax ids; outputs compacted ids
    + lengths."""
    x = ins["Input"][0]
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    ids = x[..., 0] if squeeze else x
    B, T = ids.shape
    blank = int(attrs.get("blank", 0))
    lens = (ins["InputLength"][0].reshape(-1).astype(jnp.int32)
            if ins.get("InputLength") else jnp.full((B,), T, jnp.int32))
    valid = jnp.arange(T)[None, :] < lens[:, None]
    prev = jnp.pad(ids[:, :-1], ((0, 0), (1, 0)), constant_values=blank)
    keep = valid & (ids != blank) & (ids != prev)
    from .sequence_ops import left_compact
    compacted, new_len = left_compact(ids, keep)
    out = jnp.where(jnp.arange(T)[None, :] < new_len[:, None], compacted,
                    jnp.asarray(blank, ids.dtype))
    if squeeze:
        out = out[..., None]
    return {"Output": [out], "OutputLength": [new_len]}


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------
# Transition layout (linear_chain_crf_op.cc): [C+2, C] — row 0: start→tag,
# row 1: tag→stop, rows 2+c: from tag c → to tag.

def _crf_parts(transition):
    start = transition[0].astype(jnp.float32)
    stop = transition[1].astype(jnp.float32)
    trans = transition[2:].astype(jnp.float32)
    return start, stop, trans


@register("linear_chain_crf",
          no_grad_slots=("Label", "Length"))
def _linear_chain_crf(ctx, ins, attrs):
    """Per-sequence log-likelihood of the gold path
    (linear_chain_crf_op.cc): gold score − log partition, both masked by
    per-row lengths.  Emission [B,T,C], Label [B,T], Transition [C+2,C]."""
    emission = ins["Emission"][0].astype(jnp.float32)
    transition = ins["Transition"][0]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[..., 0]
    B, T, C = emission.shape
    lens = (ins["Length"][0].reshape(-1).astype(jnp.int32)
            if ins.get("Length") else jnp.full((B,), T, jnp.int32))
    start, stop, trans = _crf_parts(transition)
    lab32 = label.astype(jnp.int32)

    # gold path score
    e_scores = jnp.take_along_axis(emission, lab32[..., None], axis=2)[..., 0]
    t_idx = jnp.arange(T)[None, :]
    valid = t_idx < lens[:, None]
    gold = jnp.sum(jnp.where(valid, e_scores, 0.0), axis=1)
    pair_valid = (t_idx[:, 1:] < lens[:, None])
    pair = trans[lab32[:, :-1], lab32[:, 1:]]
    gold = gold + jnp.sum(jnp.where(pair_valid, pair, 0.0), axis=1)
    gold = gold + start[lab32[:, 0]]
    last = jnp.take_along_axis(lab32, jnp.maximum(lens - 1, 0)[:, None],
                               axis=1)[:, 0]
    gold = gold + stop[last]

    # log partition by forward scan
    a0 = start[None, :] + emission[:, 0]

    def step(alpha, t):
        scores = alpha[:, :, None] + trans[None, :, :] + emission[:, t][:, None, :]
        new = jax.nn.logsumexp(scores, axis=1)
        active = (t < lens)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, T))
    logz = jax.nn.logsumexp(alpha + stop[None, :], axis=1)
    ll = gold - logz
    return {"LogLikelihood": [ll[:, None]]}


@register("crf_decoding", no_grad_slots=("Emission", "Transition", "Label",
                                         "Length"))
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode (crf_decoding_op.cc): max-product forward with
    argmax backpointers, reverse backtrack; padded tail emits 0."""
    emission = ins["Emission"][0].astype(jnp.float32)
    transition = ins["Transition"][0]
    B, T, C = emission.shape
    lens = (ins["Length"][0].reshape(-1).astype(jnp.int32)
            if ins.get("Length") else jnp.full((B,), T, jnp.int32))
    start, stop, trans = _crf_parts(transition)

    a0 = start[None, :] + emission[:, 0]

    def fwd(alpha, t):
        scores = alpha[:, :, None] + trans[None, :, :]    # [B, C_from, C_to]
        best = jnp.max(scores, axis=1) + emission[:, t]
        ptr = jnp.argmax(scores, axis=1).astype(jnp.int32)
        active = (t < lens)[:, None]
        new = jnp.where(active, best, alpha)
        ptr = jnp.where(active, ptr,
                        jnp.arange(C, dtype=jnp.int32)[None, :])
        return new, ptr

    alpha, ptrs = lax.scan(fwd, a0, jnp.arange(1, T))     # ptrs [T-1,B,C]
    last_tag = jnp.argmax(alpha + stop[None, :], axis=1).astype(jnp.int32)

    def back(cur, ptr_t):
        nxt = jnp.take_along_axis(ptr_t, cur[:, None], axis=1)[:, 0]
        return nxt, cur

    tag0, tags_rev = lax.scan(back, last_tag, ptrs[::-1])
    # emitted: tag_{T-1}..tag_1; final carry: tag_0
    path = jnp.concatenate([tag0[:, None], tags_rev[::-1].T], axis=1)  # [B,T]
    # frozen steps carry identity pointers, so path[0:len] is already the
    # per-row Viterbi path; zero the padded tail
    t_idx = jnp.arange(T)[None, :]
    out = jnp.where(t_idx < lens[:, None], path, 0)
    return {"ViterbiPath": [out.astype(jnp.int64)]}
