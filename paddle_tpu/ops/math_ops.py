"""Math op lowerings: elementwise (with axis-broadcast), activations,
reductions, matmul family, misc scalar math.

Reference coverage: ``paddle/fluid/operators/elementwise_*.cc``,
``activation_op.cc`` (30+ activations), ``reduce_*.cc``, ``mul_op.cc``,
``matmul_op.cc``, ``scale_op.cc``, ``sum_op.cc``, ``clip_op.cc``,
``cast_op.cc``, ``mean_op.cc``.  Each lowers to jnp/lax ops that XLA fuses
into surrounding computations (no per-op kernels needed on TPU); matmuls hit
the MXU via ``jnp.matmul`` with preferred_element_type left to the input
dtype policy.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register, register_grad


# ---------------------------------------------------------------------------
# elementwise family with the reference's axis-broadcast semantics
# (elementwise_op_function.h: Y broadcasts to X along a contiguous dim span
# starting at `axis`; axis=-1 aligns trailing dims)
# ---------------------------------------------------------------------------

def broadcast_y(x, y, axis: int):
    if x.shape == y.shape:
        return y
    if axis == -1 or axis is None:
        return y  # trailing alignment == numpy broadcasting
    # align y's dims at `axis` within x's rank, pad 1s on the right
    new_shape = [1] * x.ndim
    for i, s in enumerate(y.shape):
        new_shape[axis + i] = s
    return y.reshape(new_shape)


def _ew(fn, sparse_scalar_ok=False):
    def lower(ctx, ins, attrs):
        from ..core.selected_rows import SelectedRows

        x, y = ins["X"][0], ins["Y"][0]
        if isinstance(x, SelectedRows):
            # only f with f(0, y) == 0 (mul/div) may skip the absent zero
            # rows; anything else would silently diverge from dense semantics
            if sparse_scalar_ok and jnp.ndim(y) == 0:
                return {"Out": [SelectedRows(x.rows, fn(x.values, y), x.height,
                                             merged=x.merged)]}
            raise NotImplementedError(
                f"elementwise op {fn.__name__!r} over a SelectedRows operand")
        return {"Out": [fn(x, broadcast_y(x, y, attrs.get("axis", -1)))]}
    return lower


register("elementwise_add")(_ew(jnp.add))
register("elementwise_sub")(_ew(jnp.subtract))
register("elementwise_mul")(_ew(jnp.multiply, sparse_scalar_ok=True))
register("elementwise_div")(_ew(jnp.divide, sparse_scalar_ok=True))
register("elementwise_max")(_ew(jnp.maximum))
register("elementwise_min")(_ew(jnp.minimum))
register("elementwise_pow")(_ew(jnp.power))
register("elementwise_mod")(_ew(jnp.mod))
register("elementwise_floordiv")(_ew(jnp.floor_divide))


# ---------------------------------------------------------------------------
# activations (activation_op.cc / activation_op.h functor zoo)
# ---------------------------------------------------------------------------

def _act(fn, needs_attrs=False):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        return {"Out": [fn(x, attrs) if needs_attrs else fn(x)]}
    return lower


register("relu")(_act(jax.nn.relu))
register("sigmoid")(_act(jax.nn.sigmoid))
register("logsigmoid")(_act(jax.nn.log_sigmoid))
register("tanh")(_act(jnp.tanh))
register("tanh_shrink")(_act(lambda x: x - jnp.tanh(x)))
register("exp")(_act(jnp.exp))
register("log")(_act(jnp.log))
register("square")(_act(jnp.square))
register("sqrt")(_act(jnp.sqrt))
register("rsqrt")(_act(lax.rsqrt))
register("abs")(_act(jnp.abs))
register("ceil")(_act(jnp.ceil))
register("floor")(_act(jnp.floor))
register("round")(_act(jnp.round))
register("reciprocal")(_act(jnp.reciprocal))
register("sin")(_act(jnp.sin))
register("cos")(_act(jnp.cos))
register("softplus")(_act(jax.nn.softplus))
register("softsign")(_act(jax.nn.soft_sign))
register("softshrink")(
    _act(lambda x, a: jnp.where(x > a["lambda"], x - a["lambda"],
                                jnp.where(x < -a["lambda"], x + a["lambda"], 0.0)),
         needs_attrs=True)
)
register("relu6")(_act(lambda x: jnp.clip(x, 0.0, 6.0)))
register("leaky_relu")(_act(lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 0.02) * x), needs_attrs=True))
register("elu")(_act(lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)), needs_attrs=True))
register("gelu")(_act(lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", True)), needs_attrs=True))
register("swish")(_act(lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x), needs_attrs=True))
register("hard_sigmoid")(
    _act(lambda x, a: jnp.clip(a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0), needs_attrs=True)
)
register("brelu")(_act(lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)), needs_attrs=True))
register("pow")(_act(lambda x, a: jnp.power(x, a.get("factor", 1.0)), needs_attrs=True))
register("stanh")(
    _act(lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 0.67) * x), needs_attrs=True)
)
register("hard_shrink")(
    _act(lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0), needs_attrs=True)
)
register("thresholded_relu")(
    _act(lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0), needs_attrs=True)
)
register("maxout")(_act(
    lambda x, a: x.reshape(x.shape[0], a["groups"], x.shape[1] // a["groups"], *x.shape[2:]).max(axis=1),
    needs_attrs=True,
))


@register("softmax")
def _softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


@register("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


# ---------------------------------------------------------------------------
# reductions (reduce_op.h: dim / keep_dim / reduce_all attrs)
# ---------------------------------------------------------------------------

def _reduce(fn):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        if attrs.get("reduce_all", False):
            axes = None
        else:
            dim = attrs.get("dim", [0])
            axes = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        return {"Out": [fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))]}
    return lower


register("reduce_sum")(_reduce(jnp.sum))
register("reduce_mean")(_reduce(jnp.mean))
register("reduce_max")(_reduce(jnp.max))
register("reduce_min")(_reduce(jnp.min))
register("reduce_prod")(_reduce(jnp.prod))


@register("mean")
def _mean(ctx, ins, attrs):
    return {"Out": [jnp.mean(ins["X"][0])]}


# ---------------------------------------------------------------------------
# matmul family — the MXU path.  `mul` is the reference's FC core
# (mul_op.cc:181: flattens X to 2-D by x_num_col_dims).
# ---------------------------------------------------------------------------

def flatten_to_2d(x, num_col_dims: int):
    lead = 1
    for s in x.shape[:num_col_dims]:
        lead *= s
    rest = 1
    for s in x.shape[num_col_dims:]:
        rest *= s
    return x.reshape(lead, rest)


@register("mul")
def _mul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    x2 = flatten_to_2d(x, xnc)
    y2 = flatten_to_2d(y, ync)
    out = jnp.matmul(x2, y2)
    out_shape = x.shape[:xnc] + y.shape[ync:]
    return {"Out": [out.reshape(out_shape)]}


@register("matmul")
def _matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

@register("scale")
def _scale(ctx, ins, attrs):
    x = ins["X"][0]
    scale = jnp.asarray(attrs.get("scale", 1.0), x.dtype)
    bias = jnp.asarray(attrs.get("bias", 0.0), x.dtype)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * scale + bias]}
    return {"Out": [(x + bias) * scale]}


@register("sum")
def _sum(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows

    xs = ins["X"]
    sparse = [x for x in xs if isinstance(x, SelectedRows)]
    if sparse:
        if len(sparse) == len(xs):
            # all-sparse sum = row concatenation (reference sum_op over
            # SelectedRows; duplicates are merged later by the consumer)
            out = SelectedRows(
                jnp.concatenate([s.rows for s in sparse]),
                jnp.concatenate([s.values for s in sparse]),
                sparse[0].height)
            return {"Out": [out]}
        xs = [x.to_dense() if isinstance(x, SelectedRows) else x for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register("cast", no_grad_slots=())
def _cast(ctx, ins, attrs):
    from ..core.types import np_dtype
    return {"Out": [ins["X"][0].astype(np_dtype(attrs["out_dtype"]))]}


@register_grad("cast")
def _cast_grad(ctx, ins, attrs):
    g = ins["Out@GRAD"][0]
    x = ins["X"][0]
    return {"X@GRAD": [g.astype(x.dtype)]}


@register("clip")
def _clip(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows, merge_rows

    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        m = merge_rows(x)  # merge first so duplicates clip like the dense grad
        return {"Out": [SelectedRows(
            m.rows, jnp.clip(m.values, attrs.get("min"), attrs.get("max")),
            m.height, merged=True)]}
    return {"Out": [jnp.clip(x, attrs.get("min"), attrs.get("max"))]}


@register("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows, merge_rows

    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    if isinstance(x, SelectedRows):
        m = merge_rows(x)
        norm = jnp.sqrt(jnp.sum(jnp.square(m.values)))
        factor = jnp.where(norm > max_norm,
                           max_norm / jnp.maximum(norm, 1e-12), 1.0)
        return {"Out": [SelectedRows(
            m.rows, m.values * factor.astype(m.dtype), m.height,
            merged=True)]}
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    factor = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [x * factor.astype(x.dtype)]}


@register("isfinite")
def _isfinite(ctx, ins, attrs):
    # reference isfinite_op: reduces all inputs to one bool-ish scalar
    ok = jnp.asarray(True)
    for x in ins["X"]:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return {"Out": [ok]}


@register("has_inf")
def _has_inf(ctx, ins, attrs):
    # reference overflow ops (isfinite_op.cc InfinityFunctor family)
    return {"Out": [jnp.any(jnp.isinf(ins["X"][0]))]}


@register("has_nan")
def _has_nan(ctx, ins, attrs):
    return {"Out": [jnp.any(jnp.isnan(ins["X"][0]))]}


@register("soft_relu")
def _soft_relu(ctx, ins, attrs):
    # activation_op.cc SoftReluFunctor: log(1 + exp(clip(x, -t, t)))
    t = attrs.get("threshold", 40.0)
    x = ins["X"][0]
    return {"Out": [jnp.log1p(jnp.exp(jnp.clip(x, -t, t)))]}


@register("sign")
def _sign(ctx, ins, attrs):
    return {"Out": [jnp.sign(ins["X"][0])]}


@register("cumsum")
def _cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get("exclusive", False):
            out = out - x
    return {"Out": [out]}


# logical / comparison (compare_op.cc, logical_op.cc)
def _cmp(fn):
    def lower(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        return {"Out": [fn(x, broadcast_y(x, y, attrs.get("axis", -1)))]}
    return lower


register("less_than", no_grad_slots=("X", "Y"))(_cmp(jnp.less))
register("less_equal", no_grad_slots=("X", "Y"))(_cmp(jnp.less_equal))
register("greater_than", no_grad_slots=("X", "Y"))(_cmp(jnp.greater))
register("greater_equal", no_grad_slots=("X", "Y"))(_cmp(jnp.greater_equal))
register("equal", no_grad_slots=("X", "Y"))(_cmp(jnp.equal))
register("not_equal", no_grad_slots=("X", "Y"))(_cmp(jnp.not_equal))
register("logical_and", no_grad_slots=("X", "Y"))(_cmp(jnp.logical_and))
register("logical_or", no_grad_slots=("X", "Y"))(_cmp(jnp.logical_or))
register("logical_xor", no_grad_slots=("X", "Y"))(_cmp(jnp.logical_xor))


@register("logical_not", no_grad_slots=("X",))
def _logical_not(ctx, ins, attrs):
    return {"Out": [jnp.logical_not(ins["X"][0])]}


# helpers for GradientClipByGlobalNorm (clip.py)
@register("__global_norm_sq__", no_grad_slots=("X",))
def _global_norm_sq(ctx, ins, attrs):
    from ..core.selected_rows import SelectedRows, merge_rows

    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        x = merge_rows(x).values  # duplicates must sum before squaring
    return {"Out": [jnp.sum(jnp.square(x.astype(jnp.float32)))]}


@register("__global_norm_factor__", no_grad_slots=("X",))
def _global_norm_factor(ctx, ins, attrs):
    total_sq = ins["X"][0]
    clip_norm = attrs["clip_norm"]
    norm = jnp.sqrt(total_sq)
    return {"Out": [clip_norm / jnp.maximum(norm, clip_norm)]}
