"""In-graph metric ops: auc, precision_recall stats, edit_distance.

Reference: ``paddle/fluid/operators/auc_op.cc`` (threshold-bucketed
TP/FP histograms accumulated across batches as in/out state tensors),
``precision_recall_op.cc`` and ``edit_distance_op.cc`` (per-pair
Levenshtein).  The python-side accumulators in ``paddle_tpu/metrics.py``
wrap these (reference ``python/paddle/fluid/metrics.py``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.host_ops import register_host_op
from ..core.registry import register


@register("auc", no_grad_slots=("Predict", "Label", "StatPos", "StatNeg"))
def _auc(ctx, ins, attrs):
    """ROC-AUC over accumulated threshold buckets (auc_op.cc).

    Predict [N, 2] (P(neg), P(pos)) or [N, 1]/[N] positive scores;
    Label [N, 1] {0,1}; StatPos/StatNeg [T+1] running histograms.
    Outputs AUC scalar + updated stats (write them back to the same
    persistable vars to accumulate across batches).
    """
    num_t = int(attrs.get("num_thresholds", 4095))
    pred = ins["Predict"][0]
    if pred.ndim == 2 and pred.shape[1] == 2:
        pos_score = pred[:, 1]
    else:
        pos_score = pred.reshape(-1)
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]

    bucket = jnp.clip((pos_score * num_t).astype(jnp.int32), 0, num_t)
    one = jnp.ones_like(bucket, dtype=stat_pos.dtype)
    new_pos = stat_pos.at[bucket].add(jnp.where(label == 1, one, 0))
    new_neg = stat_neg.at[bucket].add(jnp.where(label == 0, one, 0))

    # trapezoid rule over buckets scanned from the highest threshold
    pos_r = new_pos[::-1]
    neg_r = new_neg[::-1]
    tp = jnp.cumsum(pos_r)
    fp = jnp.cumsum(neg_r)
    tp_prev = jnp.concatenate([jnp.zeros((1,), tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros((1,), fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    total = tp[-1] * fp[-1]
    auc = jnp.where(total > 0, area / jnp.maximum(total, 1), 0.0)
    return {"AUC": [auc.astype(jnp.float32)],
            "StatPosOut": [new_pos], "StatNegOut": [new_neg]}


@register("precision_recall",
          no_grad_slots=("MaxProbs", "Indices", "Labels", "StatesInfo"))
def _precision_recall(ctx, ins, attrs):
    """Multi-class precision/recall stats (precision_recall_op.cc).

    Indices [N,1] predicted class, Labels [N,1]; StatesInfo [C,4] running
    (TP, FP, TN, FN) per class.  Outputs BatchMetrics/AccumMetrics
    [6] = (macro-P, macro-R, macro-F1, micro-P, micro-R, micro-F1) and
    the updated StatesInfo.
    """
    num_classes = int(attrs["class_number"])
    idx = ins["Indices"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    states = ins["StatesInfo"][0]  # [C, 4]

    onehot_pred = jax.nn.one_hot(idx, num_classes, dtype=states.dtype)
    onehot_lbl = jax.nn.one_hot(label, num_classes, dtype=states.dtype)
    tp = jnp.sum(onehot_pred * onehot_lbl, axis=0)
    fp = jnp.sum(onehot_pred * (1 - onehot_lbl), axis=0)
    fn = jnp.sum((1 - onehot_pred) * onehot_lbl, axis=0)
    n = idx.shape[0]
    tn = jnp.full_like(tp, n) - tp - fp - fn

    def metrics(tp, fp, tn, fn):
        prec = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1), 0.0)
        rec = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1), 0.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0)
        macro = (jnp.mean(prec), jnp.mean(rec), jnp.mean(f1))
        stp, sfp, sfn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
        mp = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1), 0.0)
        mr = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr, 1e-12),
                       0.0)
        return jnp.stack(macro + (mp, mr, mf)).astype(jnp.float32)

    batch = metrics(tp, fp, tn, fn)
    new_states = states + jnp.stack([tp, fp, tn, fn], axis=1)
    accum = metrics(new_states[:, 0], new_states[:, 1], new_states[:, 2],
                    new_states[:, 3])
    return {"BatchMetrics": [batch], "AccumMetrics": [accum],
            "AccumStatesInfo": [new_states]}


@register("edit_distance", no_grad_slots=("Hyps", "Refs", "HypsLen", "RefsLen"))
def _edit_distance(ctx, ins, attrs):
    """Batched Levenshtein distance over padded id sequences
    (edit_distance_op.cc).  Hyps [B, Th], Refs [B, Tr] + length vectors;
    ``normalized`` divides by the reference length."""
    hyps = ins["Hyps"][0].astype(jnp.int32)
    refs = ins["Refs"][0].astype(jnp.int32)
    b, th = hyps.shape
    tr = refs.shape[1]
    hyp_len = (ins["HypsLen"][0].reshape(-1).astype(jnp.int32)
               if ins.get("HypsLen") else jnp.full((b,), th, jnp.int32))
    ref_len = (ins["RefsLen"][0].reshape(-1).astype(jnp.int32)
               if ins.get("RefsLen") else jnp.full((b,), tr, jnp.int32))

    # DP rows: carry [B, Tr+1]; row_i[j] = dist(hyp[:i], ref[:j]).
    # Positions beyond a sequence's length are frozen by masking.
    init = jnp.broadcast_to(
        jnp.minimum(jnp.arange(tr + 1), ref_len[:, None]).astype(jnp.float32),
        (b, tr + 1))

    def step(row, ti):
        h_t = hyps[:, ti]                                     # [B]
        sub_cost = (refs != h_t[:, None]).astype(jnp.float32)  # [B, Tr]
        active = (ti < hyp_len).astype(jnp.float32)[:, None]

        def inner(left, j):
            up = row[:, j + 1] + 1.0
            diag = row[:, j] + sub_cost[:, j]
            val = jnp.minimum(jnp.minimum(left + 1.0, up), diag)
            # columns beyond ref_len freeze at the ref_len column value
            val = jnp.where(j + 1 <= ref_len, val, left)
            return val, val

        first = row[:, 0] + 1.0
        _, cols = lax.scan(inner, first, jnp.arange(tr))
        new_row = jnp.concatenate([first[None, :], cols], axis=0).T  # [B,Tr+1]
        row = active * new_row + (1.0 - active) * row
        return row, None

    final, _ = lax.scan(step, init, jnp.arange(th))
    dist = jnp.take_along_axis(final, ref_len[:, None].astype(jnp.int32),
                               axis=1)                        # [B,1]
    if attrs.get("normalized", True):
        dist = dist / jnp.maximum(ref_len[:, None].astype(jnp.float32), 1.0)
    return {"Out": [dist.astype(jnp.float32)],
            "SequenceNum": [jnp.asarray(b, jnp.int64)]}


@register("mean_iou", no_grad_slots=("Predictions", "Labels"))
def _mean_iou(ctx, ins, attrs):
    """mean_iou_op.cc: mean intersection-over-union over classes present
    in either predictions or labels (union > 0)."""
    num_classes = attrs["num_classes"]
    pred = ins["Predictions"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    one = jnp.ones_like(pred, jnp.float32)
    inter = jnp.zeros((num_classes,), jnp.float32).at[
        jnp.where(pred == label, pred, num_classes - 1)
    ].add(jnp.where(pred == label, one, 0.0))
    pred_cnt = jnp.zeros((num_classes,), jnp.float32).at[pred].add(one)
    label_cnt = jnp.zeros((num_classes,), jnp.float32).at[label].add(one)
    wrong = pred_cnt + label_cnt - 2 * inter
    # streaming accumulators (mean_iou_op.cc InWrongs/InCorrects lists)
    for prev in ins.get("InWrongs", []):
        wrong = wrong + prev.astype(jnp.float32)
    for prev in ins.get("InCorrects", []):
        inter = inter + prev.astype(jnp.float32)
    union = 2 * inter + wrong
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(inter + wrong, 1.0), 0.0)
    mean = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    for prev in ins.get("InMeanIou", []):
        mean = mean + prev.reshape(()).astype(jnp.float32)
    return {"OutMeanIou": [mean],
            "OutWrong": [wrong.astype(jnp.int32)],
            "OutCorrect": [inter.astype(jnp.int32)]}


# ---------------------------------------------------------------------------
# host-side metrics (data-dependent chunk/pair extraction; eval-time only)
# ---------------------------------------------------------------------------

_CHUNK_SCHEMES = {
    # scheme: (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _extract_chunks(tags, scheme, num_chunk_types, excluded):
    """Segment extraction per chunk_eval_op.h GetSegments (fresh numpy
    port of the IOB/IOE/IOBES/plain transition rules)."""
    num_tag, t_begin, t_inside, t_end, t_single = _CHUNK_SCHEMES[scheme]
    other = num_chunk_types

    def is_end(ptag, ptype, tag, typ):
        if ptype == other:
            return False
        if typ == other or typ != ptype:
            return True
        if ptag in (t_begin, t_inside):
            return tag in (t_begin, t_single)
        return ptag in (t_end, t_single)

    def is_begin(ptag, ptype, tag, typ):
        if ptype == other:
            return typ != other
        if typ == other:
            return False
        if typ != ptype:
            return True
        if tag == t_begin or tag == t_single:
            return True
        if tag in (t_inside, t_end):
            return ptag in (t_end, t_single)
        return False

    chunks = set()
    start, in_chunk = 0, False
    ptag, ptype = -1, other
    for i, lab in enumerate(tags):
        tag = int(lab) % num_tag
        typ = int(lab) // num_tag
        if in_chunk and is_end(ptag, ptype, tag, typ):
            if ptype not in excluded:
                chunks.add((start, i - 1, ptype))
            in_chunk = False
        if is_begin(ptag, ptype, tag, typ):
            start, in_chunk = i, True
        ptag, ptype = tag, typ
    if in_chunk and ptype not in excluded:
        chunks.add((start, len(tags) - 1, ptype))
    return chunks


@register_host_op("chunk_eval")
def _chunk_eval(exe, program, op, scope):
    """chunk_eval_op.cc: batch chunk precision/recall/F1 from padded
    [B, T] tag tensors + @LEN lengths."""
    import numpy as np

    inf = np.asarray(scope.find_var(op.input("Inference")[0]))
    lab = np.asarray(scope.find_var(op.input("Label")[0]))
    lens = None
    if op.input("SeqLen"):
        lens = np.asarray(scope.find_var(op.input("SeqLen")[0]))
    scheme = op.attr("chunk_scheme", "IOB")
    num_chunk_types = op.attr("num_chunk_types")
    excluded = set(op.attr("excluded_chunk_types", []) or [])
    if inf.ndim == 1:
        inf, lab = inf[None, :], lab[None, :]
    B = inf.shape[0]
    n_inf = n_lab = n_correct = 0
    for i in range(B):
        L = int(lens[i]) if lens is not None else inf.shape[1]
        ci = _extract_chunks(inf[i, :L].reshape(-1), scheme,
                             num_chunk_types, excluded)
        cl = _extract_chunks(lab[i, :L].reshape(-1), scheme,
                             num_chunk_types, excluded)
        n_inf += len(ci)
        n_lab += len(cl)
        n_correct += len(ci & cl)
    p = n_correct / n_inf if n_inf else 0.0
    r = n_correct / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    scope.set_var(op.output("Precision")[0], np.asarray([p], np.float32))
    scope.set_var(op.output("Recall")[0], np.asarray([r], np.float32))
    scope.set_var(op.output("F1-Score")[0], np.asarray([f1], np.float32))
    scope.set_var(op.output("NumInferChunks")[0],
                  np.asarray([n_inf], np.int64))
    scope.set_var(op.output("NumLabelChunks")[0],
                  np.asarray([n_lab], np.int64))
    scope.set_var(op.output("NumCorrectChunks")[0],
                  np.asarray([n_correct], np.int64))


@register_host_op("positive_negative_pair")
def _positive_negative_pair(exe, program, op, scope):
    """positive_negative_pair_op.cc: per-query counts of correctly ordered
    (positive), mis-ordered (negative) and tied (neutral) score pairs,
    accumulated into the running totals when Accumulate* inputs exist."""
    import numpy as np

    score = np.asarray(scope.find_var(op.input("Score")[0]))
    label = np.asarray(scope.find_var(op.input("Label")[0])).reshape(-1)
    qid = np.asarray(scope.find_var(op.input("QueryID")[0])).reshape(-1)
    col = op.attr("column", -1)
    score = score.reshape(len(qid), -1)[:, col]
    weight = None
    if op.input("Weight"):
        weight = np.asarray(scope.find_var(op.input("Weight")[0])).reshape(-1)
    pos = neg = neu = 0.0
    for q in np.unique(qid):
        idx = np.where(qid == q)[0]
        s, l = score[idx], label[idx]
        # vectorized upper-triangle pair comparison per query.  Reference
        # quirks kept: pair weight = mean of the two row weights; a tied
        # score counts as neutral AND still falls through to pos/neg.
        a, b = np.triu_indices(len(idx), k=1)
        diff = l[a] != l[b]
        a, b = a[diff], b[diff]
        w = (0.5 * (weight[idx][a] + weight[idx][b]) if weight is not None
             else np.ones(len(a)))
        tied = s[a] == s[b]
        neu += float(w[tied].sum())
        ordered = (s[a] - s[b]) * (l[a] - l[b]) > 0
        pos += float(w[ordered].sum())
        neg += float(w[~ordered].sum())
    if op.input("AccumulatePositivePair"):
        pos += float(np.asarray(
            scope.find_var(op.input("AccumulatePositivePair")[0])))
        neg += float(np.asarray(
            scope.find_var(op.input("AccumulateNegativePair")[0])))
        neu += float(np.asarray(
            scope.find_var(op.input("AccumulateNeutralPair")[0])))
    scope.set_var(op.output("PositivePair")[0], np.asarray([pos], np.float32))
    scope.set_var(op.output("NegativePair")[0], np.asarray([neg], np.float32))
    scope.set_var(op.output("NeutralPair")[0], np.asarray([neu], np.float32))
