"""In-graph metric ops: auc, precision_recall stats, edit_distance.

Reference: ``paddle/fluid/operators/auc_op.cc`` (threshold-bucketed
TP/FP histograms accumulated across batches as in/out state tensors),
``precision_recall_op.cc`` and ``edit_distance_op.cc`` (per-pair
Levenshtein).  The python-side accumulators in ``paddle_tpu/metrics.py``
wrap these (reference ``python/paddle/fluid/metrics.py``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


@register("auc", no_grad_slots=("Predict", "Label", "StatPos", "StatNeg"))
def _auc(ctx, ins, attrs):
    """ROC-AUC over accumulated threshold buckets (auc_op.cc).

    Predict [N, 2] (P(neg), P(pos)) or [N, 1]/[N] positive scores;
    Label [N, 1] {0,1}; StatPos/StatNeg [T+1] running histograms.
    Outputs AUC scalar + updated stats (write them back to the same
    persistable vars to accumulate across batches).
    """
    num_t = int(attrs.get("num_thresholds", 4095))
    pred = ins["Predict"][0]
    if pred.ndim == 2 and pred.shape[1] == 2:
        pos_score = pred[:, 1]
    else:
        pos_score = pred.reshape(-1)
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]

    bucket = jnp.clip((pos_score * num_t).astype(jnp.int32), 0, num_t)
    one = jnp.ones_like(bucket, dtype=stat_pos.dtype)
    new_pos = stat_pos.at[bucket].add(jnp.where(label == 1, one, 0))
    new_neg = stat_neg.at[bucket].add(jnp.where(label == 0, one, 0))

    # trapezoid rule over buckets scanned from the highest threshold
    pos_r = new_pos[::-1]
    neg_r = new_neg[::-1]
    tp = jnp.cumsum(pos_r)
    fp = jnp.cumsum(neg_r)
    tp_prev = jnp.concatenate([jnp.zeros((1,), tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros((1,), fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    total = tp[-1] * fp[-1]
    auc = jnp.where(total > 0, area / jnp.maximum(total, 1), 0.0)
    return {"AUC": [auc.astype(jnp.float32)],
            "StatPosOut": [new_pos], "StatNegOut": [new_neg]}


@register("precision_recall",
          no_grad_slots=("MaxProbs", "Indices", "Labels", "StatesInfo"))
def _precision_recall(ctx, ins, attrs):
    """Multi-class precision/recall stats (precision_recall_op.cc).

    Indices [N,1] predicted class, Labels [N,1]; StatesInfo [C,4] running
    (TP, FP, TN, FN) per class.  Outputs BatchMetrics/AccumMetrics
    [6] = (macro-P, macro-R, macro-F1, micro-P, micro-R, micro-F1) and
    the updated StatesInfo.
    """
    num_classes = int(attrs["class_number"])
    idx = ins["Indices"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    states = ins["StatesInfo"][0]  # [C, 4]

    onehot_pred = jax.nn.one_hot(idx, num_classes, dtype=states.dtype)
    onehot_lbl = jax.nn.one_hot(label, num_classes, dtype=states.dtype)
    tp = jnp.sum(onehot_pred * onehot_lbl, axis=0)
    fp = jnp.sum(onehot_pred * (1 - onehot_lbl), axis=0)
    fn = jnp.sum((1 - onehot_pred) * onehot_lbl, axis=0)
    n = idx.shape[0]
    tn = jnp.full_like(tp, n) - tp - fp - fn

    def metrics(tp, fp, tn, fn):
        prec = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1), 0.0)
        rec = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1), 0.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0)
        macro = (jnp.mean(prec), jnp.mean(rec), jnp.mean(f1))
        stp, sfp, sfn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
        mp = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1), 0.0)
        mr = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr, 1e-12),
                       0.0)
        return jnp.stack(macro + (mp, mr, mf)).astype(jnp.float32)

    batch = metrics(tp, fp, tn, fn)
    new_states = states + jnp.stack([tp, fp, tn, fn], axis=1)
    accum = metrics(new_states[:, 0], new_states[:, 1], new_states[:, 2],
                    new_states[:, 3])
    return {"BatchMetrics": [batch], "AccumMetrics": [accum],
            "AccumStatesInfo": [new_states]}


@register("edit_distance", no_grad_slots=("Hyps", "Refs", "HypsLen", "RefsLen"))
def _edit_distance(ctx, ins, attrs):
    """Batched Levenshtein distance over padded id sequences
    (edit_distance_op.cc).  Hyps [B, Th], Refs [B, Tr] + length vectors;
    ``normalized`` divides by the reference length."""
    hyps = ins["Hyps"][0].astype(jnp.int32)
    refs = ins["Refs"][0].astype(jnp.int32)
    b, th = hyps.shape
    tr = refs.shape[1]
    hyp_len = (ins["HypsLen"][0].reshape(-1).astype(jnp.int32)
               if ins.get("HypsLen") else jnp.full((b,), th, jnp.int32))
    ref_len = (ins["RefsLen"][0].reshape(-1).astype(jnp.int32)
               if ins.get("RefsLen") else jnp.full((b,), tr, jnp.int32))

    # DP rows: carry [B, Tr+1]; row_i[j] = dist(hyp[:i], ref[:j]).
    # Positions beyond a sequence's length are frozen by masking.
    init = jnp.broadcast_to(
        jnp.minimum(jnp.arange(tr + 1), ref_len[:, None]).astype(jnp.float32),
        (b, tr + 1))

    def step(row, ti):
        h_t = hyps[:, ti]                                     # [B]
        sub_cost = (refs != h_t[:, None]).astype(jnp.float32)  # [B, Tr]
        active = (ti < hyp_len).astype(jnp.float32)[:, None]

        def inner(left, j):
            up = row[:, j + 1] + 1.0
            diag = row[:, j] + sub_cost[:, j]
            val = jnp.minimum(jnp.minimum(left + 1.0, up), diag)
            # columns beyond ref_len freeze at the ref_len column value
            val = jnp.where(j + 1 <= ref_len, val, left)
            return val, val

        first = row[:, 0] + 1.0
        _, cols = lax.scan(inner, first, jnp.arange(tr))
        new_row = jnp.concatenate([first[None, :], cols], axis=0).T  # [B,Tr+1]
        row = active * new_row + (1.0 - active) * row
        return row, None

    final, _ = lax.scan(step, init, jnp.arange(th))
    dist = jnp.take_along_axis(final, ref_len[:, None].astype(jnp.int32),
                               axis=1)                        # [B,1]
    if attrs.get("normalized", True):
        dist = dist / jnp.maximum(ref_len[:, None].astype(jnp.float32), 1.0)
    return {"Out": [dist.astype(jnp.float32)],
            "SequenceNum": [jnp.asarray(b, jnp.int64)]}
