"""fused_attention op: one IR node for the whole attention block.

The program-level counterpart of the reference's fused ops
(``fused_elemwise_activation_op``, ``fusion_lstm_op`` — one op standing for
a subgraph, dispatched to a tuned kernel).  Impl selection via attr:

- ``auto``  : XLA fused attention below seq 2048 (faster on v5e), pallas
              flash kernel beyond (O(block) memory wins at long context)
- ``xla``   : jnp einsum/softmax chain
- ``pallas``: force the flash kernel (interpret mode off-TPU)
- ``ring``  : sequence-parallel ring attention over mesh axis ``sp_axis``
              (wraps shard_map; requires lowering under a ParallelExecutor
              mesh that has that axis)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.registry import register, register_grad
from ..kernels import attention as A


@register("fused_attention", no_grad_slots=("KvMask", "Seed"))
def _fused_attention(ctx, ins, attrs):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    # None flows through every impl and lets the pallas kernels compile
    # out the mask load + per-tile where entirely (ring materializes ones
    # below because shard_map must shard a real array)
    kv_mask = ins["KvMask"][0] if ins.get("KvMask") else None
    causal = attrs.get("causal", False)
    scale = attrs.get("scale", None)
    impl = attrs.get("impl", "auto")
    # attention-prob dropout is seeded by an explicit program input (drawn
    # per step by the layer), so the grad op re-lowers the identical
    # computation on either path — in-kernel tile hashes on pallas,
    # deterministic bernoulli keys on xla/ring; no stored mask, no stale rng
    rate = float(attrs.get("dropout_rate", 0.0) or 0.0)
    if not ctx.training or attrs.get("is_test", False):
        rate = 0.0
    seed = ins["Seed"][0] if ins.get("Seed") else None
    if impl == "auto":
        # measured on v5e: XLA's fused attention beats the pallas kernel
        # through seq 1024 in-model (105k vs 76k tok/s at 256; 49k vs 37k
        # at 1024, Transformer-base); the flash kernel's win is O(block)
        # memory, so auto switches only where the O(T^2) scores would
        # dominate HBM (long-context training).  The crossover is
        # head_dim-aware (PERF.md §1 round 4): at D >= 128 the kernel
        # runs 44-64 TFLOPs and wins from 2048; at D < 128 every MXU dot
        # is half-filled by construction (~23-25 TFLOPs ceiling, packing
        # remedies measured equal) while the XLA ratio narrows only
        # slowly (1.8x at 256 -> 1.3x at 1024), so D=64 geometries stay
        # on XLA until 4096, where the score materialization cost
        # dominates either way.
        threshold = 2048 if q.shape[-1] >= 128 else 4096
        impl = "pallas" if (jax.default_backend() == "tpu"
                            and k.shape[2] >= threshold) else "xla"

    if impl == "xla":
        out = A.mha_xla(q, k, v, kv_mask, causal, scale,
                        dropout_rate=rate, dropout_seed=seed)
    elif impl == "pallas":
        out = A.flash_attention(q, k, v, kv_mask, causal, scale,
                                dropout_rate=rate, dropout_seed=seed)
    elif impl == "ring":
        mesh = ctx.mesh
        sp = attrs.get("sp_axis", "sp")
        if mesh is None or sp not in mesh.axis_names:
            out = A.mha_xla(q, k, v, kv_mask, causal, scale,
                            dropout_rate=rate, dropout_seed=seed)
        else:
            if kv_mask is None:
                kv_mask = jnp.ones((q.shape[0], k.shape[2]), jnp.float32)
            dp = "dp" if "dp" in mesh.axis_names else None
            qspec = P(dp, None, sp, None)
            mspec = P(dp, sp)
            sspec = P()

            def ring(q, k, v, m, s):
                return A.ring_attention(q, k, v, m, sp, causal, scale,
                                        dropout_rate=rate, dropout_seed=s)

            seed_in = (seed if seed is not None
                       else jnp.zeros((1,), jnp.int32))
            # jax.shard_map is the modern spelling; older jax only has
            # the experimental location
            shard_map = getattr(jax, "shard_map", None)
            if shard_map is None:
                from jax.experimental.shard_map import shard_map
            out = shard_map(
                ring, mesh=mesh,
                in_specs=(qspec, qspec, qspec, mspec, sspec),
                out_specs=qspec)(q, k, v, kv_mask, seed_in)
    else:
        raise ValueError(f"unknown attention impl {impl!r}")
    return {"Out": [out]}


@register_grad("fused_attention")
def _fused_attention_grad(ctx, ins, attrs):
    """Backward: differentiate the forward lowering (flash recompute /
    ring ppermute-transpose handled by jax)."""
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    g = ins["Out@GRAD"][0]
    extra = {"KvMask": ins["KvMask"]} if ins.get("KvMask") else {}
    if ins.get("Seed"):
        extra["Seed"] = ins["Seed"]  # same seed → identical dropout bits

    def f(q, k, v):
        return _fused_attention(ctx, {"Q": [q], "K": [k], "V": [v],
                                      **extra}, attrs)["Out"][0]

    _, vjp_fn = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp_fn(g)
    return {"Q@GRAD": [dq], "K@GRAD": [dk], "V@GRAD": [dv]}
