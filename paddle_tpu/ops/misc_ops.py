"""Operator-tail lowerings: pairwise/ranking losses, image ops, RNN unit
cells, interpolation, channel-affine ops, and batch-size-like random fills.

Reference coverage: ``hinge_loss_op.cc``, ``log_loss_op.cc``,
``rank_loss_op.cc``, ``margin_rank_loss_op.cc``, ``modified_huber_loss_op.h``,
``squared_l2_distance_op.cc``, ``squared_l2_norm_op.cc``, ``l1_norm_op.cc``,
``cos_sim_op.cc``, ``bilinear_tensor_product_op.cc``, ``minus_op.cc``,
``label_smooth_op.h``, ``flatten_op.cc``, ``reverse_op.cc``, ``unstack_op.cc``,
``crop_op.cc``, ``pad2d_op.cc``, ``pad_constant_like_op.cc``,
``multiplex_op.cc``, ``argsort_op.cc``, ``prelu_op.cc``,
``affine_channel_op.cc``, ``lrn_op.cc``, ``maxout_op.cc``,
``pool_with_index_op.cc``, ``unpool_op.cc``, ``spp_op.cc``,
``bilinear_interp_op.h``, ``roi_pool_op.cc``, ``gru_unit_op.h``,
``lstm_unit_op.cc``, ``conv_shift_op.cc``, ``sampling_id_op.cc``,
``uniform_random_batch_size_like_op.cc``,
``gaussian_random_batch_size_like_op.cc``, ``is_empty_op.cc``,
``random_crop_op.cc``.

TPU mapping notes: everything here is shape-static XLA; data-dependent
gather/scatter (unpool, roi_pool) uses one-hot matmuls or ``.at[]`` scatter
(lowered to XLA scatter); random ops consume PRNG keys threaded through the
block (functional replacement for cuRAND + per-op seed attrs).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register, register_grad
from ..core.types import np_dtype
from .tensor_ops import _seed_key


# ---------------------------------------------------------------------------
# pairwise / ranking / regression losses
# ---------------------------------------------------------------------------

@register("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    """hinge_loss_op.cc: loss = max(0, 1 - (2y-1) * pred), y in {0,1}."""
    pred, label = ins["Logits"][0], ins["Labels"][0]
    signs = 2.0 * label.astype(pred.dtype) - 1.0
    return {"Loss": [jnp.maximum(0.0, 1.0 - signs * pred).astype(pred.dtype)]}


@register("log_loss")
def _log_loss(ctx, ins, attrs):
    """log_loss_op.cc: -y*log(p+eps) - (1-y)*log(1-p+eps)."""
    p, y = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    return {"Loss": [loss.astype(p.dtype)]}


@register("rank_loss")
def _rank_loss(ctx, ins, attrs):
    """rank_loss_op.cc (RankNet): C = -P*(o_l-o_r) + log(1+exp(o_l-o_r))."""
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    o = left - right
    loss = jnp.logaddexp(0.0, o).astype(o.dtype) - label * o
    return {"Out": [loss]}


@register("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    """margin_rank_loss_op.cc: out = max(0, -label*(x1-x2) + margin);
    Activated saves the >0 mask for the grad."""
    label, x1, x2 = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    raw = -label * (x1 - x2) + margin
    act = (raw > 0).astype(x1.dtype)
    return {"Out": [jnp.maximum(raw, 0.0).astype(x1.dtype)],
            "Activated": [act]}


@register("modified_huber_loss")
def _modified_huber_loss(ctx, ins, attrs):
    """modified_huber_loss_op.h: z = x*(2y-1); loss = -4z if z<-1,
    (1-z)^2 if -1<=z<1, else 0."""
    x, y = ins["X"][0], ins["Y"][0]
    z = x * (2.0 * y - 1.0)
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return {"IntermediateVal": [z.astype(x.dtype)],
            "Out": [loss.astype(x.dtype)]}


@register("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    """squared_l2_distance_op.cc: row-wise ||x - y||^2 (Y may broadcast
    along the batch dim)."""
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    out = jnp.sum(sub * sub, axis=tuple(range(1, sub.ndim)), keepdims=True)
    return {"sub_result": [sub], "Out": [out.reshape(x.shape[0], 1)]}


@register("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.sum(x * x).reshape(1)]}


@register("l1_norm")
def _l1_norm(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.sum(jnp.abs(x)).reshape(1)]}


@register("cos_sim")
def _cos_sim(ctx, ins, attrs):
    """cos_sim_op.cc: row-wise cosine similarity; Y may have batch 1
    (broadcast)."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    dot = jnp.sum(x * y, axis=-1, keepdims=True)
    return {"Out": [dot / (xn * yn + 1e-12)], "XNorm": [xn], "YNorm": [yn]}


@register("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    """bilinear_tensor_product_op.cc: out_k = x^T W_k y (+ bias_k);
    Weight [K, Dx, Dy]."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if "Bias" in ins and ins["Bias"]:
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": [out.astype(x.dtype)]}


@register("minus")
def _minus(ctx, ins, attrs):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register("label_smooth")
def _label_smooth(ctx, ins, attrs):
    """label_smooth_op.h: (1-eps)*x + eps*prior (uniform 1/C default)."""
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    if "PriorDist" in ins and ins["PriorDist"]:
        prior = ins["PriorDist"][0].reshape(1, -1)
        out = (1.0 - eps) * x + eps * prior
    else:
        out = (1.0 - eps) * x + eps / x.shape[-1]
    return {"Out": [out.astype(x.dtype)]}


# ---------------------------------------------------------------------------
# shape / indexing ops
# ---------------------------------------------------------------------------

@register("flatten")
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return {"Out": [x.reshape(lead, -1)]}


register("flatten2")(_flatten)  # reference flatten2 adds an XShape output


@register("reverse")
def _reverse(ctx, ins, attrs):
    axes = attrs.get("axis", [0])
    axes = [axes] if isinstance(axes, int) else list(axes)
    return {"Out": [jnp.flip(ins["X"][0], axis=tuple(axes))]}


@register("unstack")
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    parts = jnp.split(x, n, axis=axis)
    return {"Y": [jnp.squeeze(p, axis=axis) for p in parts]}


@register("crop")
def _crop(ctx, ins, attrs):
    """crop_op.cc: static offsets/shape crop (offsets attr; Y gives the
    target shape when present)."""
    x = ins["X"][0]
    if "Y" in ins and ins["Y"]:
        shape = ins["Y"][0].shape
    else:
        shape = attrs["shape"]
    offsets = attrs.get("offsets", [0] * x.ndim)
    return {"Out": [lax.dynamic_slice(x, [int(o) for o in offsets],
                                      [int(s) for s in shape])]}


@register("pad2d")
def _pad2d(ctx, ins, attrs):
    """pad2d_op.cc: constant/reflect/edge padding of the spatial dims."""
    x = ins["X"][0]
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    value = attrs.get("pad_value", 0.0)
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    pads = [(0, 0), (0, 0), (0, 0), (0, 0)]
    h, w = (1, 2) if nhwc else (2, 3)
    pads[h] = (p[0], p[1])
    pads[w] = (p[2], p[3])
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[mode]
    kw = {"constant_values": value} if mode == "constant" else {}
    return {"Out": [jnp.pad(x, pads, mode=jmode, **kw)]}


@register("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    """pad_constant_like_op.cc: pad Y up to X's shape with pad_value."""
    x, y = ins["X"][0], ins["Y"][0]
    value = attrs.get("pad_value", 0.0)
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pads, constant_values=value)]}


@register("multiplex", no_grad_slots=("Ids",))
def _multiplex(ctx, ins, attrs):
    """multiplex_op.cc: out[i] = X[ids[i]][i] (row-wise candidate select)."""
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(ins["X"], axis=0)  # [K, B, ...]
    rows = jnp.arange(stacked.shape[1])
    return {"Out": [stacked[ids, rows]]}


@register("argsort", no_grad_slots=("X",))
def _argsort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": [jnp.sort(x, axis=axis)], "Indices": [idx.astype(jnp.int64)]}


@register("is_empty", no_grad_slots=("X",))
def _is_empty(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.asarray([x.size == 0])]}


# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------

@register("prelu")
def _prelu(ctx, ins, attrs):
    """prelu_op.cc: max(0,x) + alpha*min(0,x); alpha shared per mode
    all/channel/element."""
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:  # element
        a = alpha.reshape((1,) + x.shape[1:])
    return {"Out": [jnp.maximum(x, 0) + a * jnp.minimum(x, 0)]}


@register("affine_channel")
def _affine_channel(ctx, ins, attrs):
    """affine_channel_op.cc: x*scale + bias per channel."""
    x, scale, bias = ins["X"][0], ins["Scale"][0], ins["Bias"][0]
    nhwc = attrs.get("data_layout", "NCHW") == "NHWC"
    shape = ((1,) * (x.ndim - 1) + (-1,)) if nhwc else \
        ((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": [x * scale.reshape(shape) + bias.reshape(shape)]}


@register("lrn")
def _lrn(ctx, ins, attrs):
    """lrn_op.cc: out = x * (k + alpha*sum_{window n} x^2)^(-beta) across
    channels (NCHW)."""
    x = ins["X"][0]
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = x * x
    # window sum over channel dim via padded cumulative trick
    half = n // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    mid = sum(padded[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * mid
    return {"MidOut": [mid], "Out": [x * mid ** (-beta)]}


@register("maxout")
def _maxout(ctx, ins, attrs):
    """maxout_op.cc: NCHW channels split into groups, max within group."""
    x = ins["X"][0]
    g = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": [x.reshape(n, c // g, g, h, w).max(axis=2)]}


@register("max_pool2d_with_index", no_grad_slots=("Mask",))
def _max_pool2d_with_index(ctx, ins, attrs):
    """pool_with_index_op.cc: max pool + flat h*W+w argmax index per
    window (index into the input feature map)."""
    x = ins["X"][0]
    ks = tuple(attrs["ksize"])
    st = tuple(attrs.get("strides", [1, 1]))
    pd = tuple(attrs.get("paddings", [0, 0]))
    if attrs.get("global_pooling", False):
        ks, st, pd = x.shape[2:4], (1, 1), (0, 0)
    n, c, h, w = x.shape
    # indices ride along as float32 (exact to 2^24 — any realistic H*W);
    # x.dtype would round them for bf16 maps beyond 16x16
    flat_idx = jnp.broadcast_to(
        (jnp.arange(h)[:, None] * w + jnp.arange(w)[None, :]), x.shape
    ).astype(jnp.float32)
    neg = jnp.finfo(x.dtype).min

    def select(acc, cur):
        av, ai = acc
        cv, ci = cur
        take = cv > av
        return jnp.where(take, cv, av), jnp.where(take, ci, ai)

    out, idx = lax.reduce_window(
        (x, flat_idx),
        (jnp.asarray(neg, x.dtype), jnp.asarray(-1.0, jnp.float32)),
        lambda a, b: select(a, b),
        (1, 1) + ks, (1, 1) + st,
        ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
    return {"Out": [out], "Mask": [idx.astype(jnp.int64)]}


@register_grad("max_pool2d_with_index")
def _max_pool2d_with_index_grad(ctx, ins, attrs):
    """Route dOut back through the saved argmax indices (scatter-add)."""
    x = ins["X"][0]
    mask = ins["Mask"][0].astype(jnp.int32)
    dout = ins["Out@GRAD"][0]
    n, c, h, w = x.shape
    flat = jnp.zeros((n, c, h * w), dout.dtype)
    flat = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        mask.reshape(n, c, -1),
    ].add(dout.reshape(n, c, -1))
    return {"X@GRAD": [flat.reshape(x.shape)]}


@register("unpool", no_grad_slots=("Indices",))
def _unpool(ctx, ins, attrs):
    """unpool_op.cc: scatter pooled values back to the argmax positions."""
    x, idx = ins["X"][0], ins["Indices"][0].astype(jnp.int32)
    n, c, h, w = x.shape
    oh, ow = attrs["unpooled_height"], attrs["unpooled_width"]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    flat = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1),
    ].add(x.reshape(n, c, -1))
    return {"Out": [flat.reshape(n, c, oh, ow)]}


@register("spp")
def _spp(ctx, ins, attrs):
    """spp_op.cc: spatial pyramid pooling — concat flattened pools at
    1x1, 2x2, ... 2^(h-1) bins."""
    x = ins["X"][0]
    levels = attrs.get("pyramid_height", 1)
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for lvl in range(levels):
        bins = 2 ** lvl
        kh, kw = -(-h // bins), -(-w // bins)  # ceil
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        if ptype == "max":
            init = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
            pooled = lax.reduce_window(
                x, init, lax.max, (1, 1, kh, kw), (1, 1, kh, kw),
                ((0, 0), (0, 0), (ph, kh * bins - h - ph),
                 (pw, kw * bins - w - pw)))
        else:
            pooled = lax.reduce_window(
                x.astype(jnp.float32), 0.0, lax.add, (1, 1, kh, kw),
                (1, 1, kh, kw),
                ((0, 0), (0, 0), (ph, kh * bins - h - ph),
                 (pw, kw * bins - w - pw))) / float(kh * kw)
            pooled = pooled.astype(x.dtype)
        outs.append(pooled.reshape(n, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    """bilinear_interp_op.h: NCHW bilinear resize with the reference's
    (in-1)/(out-1) corner-aligned ratio."""
    x = ins["X"][0]
    oh, ow = attrs["out_h"], attrs["out_w"]
    n, c, h, w = x.shape
    if (h, w) == (oh, ow):
        return {"Out": [x]}
    rh = (h - 1) / (oh - 1) if oh > 1 else 0.0
    rw = (w - 1) / (ow - 1) if ow > 1 else 0.0
    ys = jnp.arange(oh) * rh
    xs = jnp.arange(ow) * rw
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0).astype(x.dtype)[None, None, :, None]
    wx = (xs - x0).astype(x.dtype)[None, None, None, :]
    g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]
    out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
           + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
    return {"Out": [out.astype(x.dtype)]}


@register("roi_pool", no_grad_slots=("ROIs",))
def _roi_pool(ctx, ins, attrs):
    """roi_pool_op.cc: per-ROI max pooling to a fixed [ph, pw] grid.
    ROIs [R, 4] (x1, y1, x2, y2) with a batch-id column convention of
    RoisLod-free 2018 fluid: ROIs carries batch ids via lod; here the
    padded redesign takes ROIs [R, 5] = (batch_id, x1, y1, x2, y2) or
    [R, 4] with batch 0."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = attrs["pooled_height"]
    pw = attrs["pooled_width"]
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    if rois.shape[-1] == 5:
        batch_ids = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    else:
        batch_ids = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois

    def pool_one(bid, box):
        x1 = jnp.round(box[0] * scale).astype(jnp.int32)
        y1 = jnp.round(box[1] * scale).astype(jnp.int32)
        x2 = jnp.round(box[2] * scale).astype(jnp.int32)
        y2 = jnp.round(box[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        img = x[bid]  # [C, H, W]
        hh = jnp.arange(h)
        ww = jnp.arange(w)
        inside_y = (hh >= y1) & (hh <= y2)
        inside_x = (ww >= x1) & (ww <= x2)
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        masked = jnp.where(inside_y[None, :, None] & inside_x[None, None, :],
                           img, neg)
        # reference bin boundaries overlap: bin i spans
        # [floor(i*r/p), ceil((i+1)*r/p)) relative to the ROI start
        bins_h = jnp.arange(ph)
        bins_w = jnp.arange(pw)
        y_lo = y1 + jnp.floor(bins_h * rh / ph).astype(jnp.int32)
        y_hi = y1 + jnp.ceil((bins_h + 1) * rh / ph).astype(jnp.int32)
        x_lo = x1 + jnp.floor(bins_w * rw / pw).astype(jnp.int32)
        x_hi = x1 + jnp.ceil((bins_w + 1) * rw / pw).astype(jnp.int32)
        oh_y = ((hh[None, :] >= y_lo[:, None]) & (hh[None, :] < y_hi[:, None])
                & inside_y[None, :])  # [ph, H]
        oh_x = ((ww[None, :] >= x_lo[:, None]) & (ww[None, :] < x_hi[:, None])
                & inside_x[None, :])  # [pw, W]
        rowred = jnp.where(oh_y[None, :, :, None], masked[:, None, :, :],
                           neg).max(axis=2)  # [C, ph, W]
        binred = jnp.where(oh_x[None, None, :, :], rowred[:, :, None, :],
                           neg).max(axis=3)  # [C, ph, pw]
        return jnp.where(binred == neg, 0.0, binred).astype(x.dtype)

    out = jax.vmap(pool_one)(batch_ids, boxes)
    return {"Out": [out]}


@register("random_crop", stateful=True, no_grad_slots=("X", "Seed"))
def _random_crop(ctx, ins, attrs):
    """random_crop_op.cc: crop `shape` at a uniform random offset (the
    trailing dims); leading dims pass through."""
    x = ins["X"][0]
    shape = list(attrs["shape"])
    lead = x.ndim - len(shape)
    key = _seed_key(ctx, attrs)
    keys = jax.random.split(key, len(shape))
    starts = [0] * lead + [
        jax.random.randint(keys[i], (), 0, x.shape[lead + i] - shape[i] + 1)
        for i in range(len(shape))]
    out = lax.dynamic_slice(x, starts, list(x.shape[:lead]) + shape)
    return {"Out": [out], "SeedOut": [ins.get("Seed", [jnp.zeros(1)])[0]]}


# ---------------------------------------------------------------------------
# RNN unit cells
# ---------------------------------------------------------------------------

_GRU_ACTS = {0: lambda v: v, 1: jax.nn.sigmoid, 2: jnp.tanh,
             3: jax.nn.relu}


@register("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """gru_unit_op.h: gates = X + h_prev @ W[:, :2D] (u, r);
    c = act(xc + (r*h_prev) @ W[:, 2D:]); h = u*(c - h_prev) + h_prev."""
    x, hp, w = ins["Input"][0], ins["HiddenPrev"][0], ins["Weight"][0]
    d = hp.shape[-1]
    gact = _GRU_ACTS[attrs.get("gate_activation", 1)]
    cact = _GRU_ACTS[attrs.get("activation", 2)]
    gates = x
    if "Bias" in ins and ins["Bias"]:
        gates = gates + ins["Bias"][0].reshape(1, -1)
    ur = gates[:, :2 * d] + hp @ w[:, :2 * d]
    ur = gact(ur)
    u, r = ur[:, :d], ur[:, d:]
    rhp = r * hp
    c = cact(gates[:, 2 * d:] + rhp @ w[:, 2 * d:].reshape(d, d))
    h = u * (c - hp) + hp
    return {"Gate": [jnp.concatenate([ur, c], axis=1)],
            "ResetHiddenPrev": [rhp], "Hidden": [h]}


@register("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """lstm_unit_op.cc: i,f,o,j = split(X); C = C_prev*sig(f+fb) +
    sig(i)*tanh(j); H = C*sig(o)."""
    x, cp = ins["X"][0], ins["C_prev"][0]
    fb = attrs.get("forget_bias", 0.0)
    i, f, o, j = jnp.split(x, 4, axis=-1)
    c = cp * jax.nn.sigmoid(f + fb) + jax.nn.sigmoid(i) * jnp.tanh(j)
    h = c * jax.nn.sigmoid(o)
    return {"C": [c], "H": [h]}


@register("conv_shift")
def _conv_shift(ctx, ins, attrs):
    """conv_shift_op.cc: circular row convolution
    out[i] = sum_j x[(i+j) mod M] * y[j], j centered on 0 (NTM shift)."""
    x, y = ins["X"][0], ins["Y"][0]
    m, n = x.shape[1], y.shape[1]
    half = (n - 1) // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, n - half)[None, :]) % m
    # [B, M, N] gather then contract against y
    gathered = x[:, idx]  # [B, M, N]
    return {"Out": [jnp.einsum("bmn,bn->bm", gathered, y)]}


# ---------------------------------------------------------------------------
# sampling / random
# ---------------------------------------------------------------------------

@register("sampling_id", stateful=True, no_grad_slots=("X",))
def _sampling_id(ctx, ins, attrs):
    """sampling_id_op.cc: sample one category per row of a probability
    matrix."""
    x = ins["X"][0]
    key = _seed_key(ctx, attrs)
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)), axis=-1)
    return {"Out": [ids.astype(jnp.int64)]}


@register("uniform_random_batch_size_like", stateful=True,
          no_grad_slots=("Input",))
def _uniform_random_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ref.shape[attrs.get("input_dim_idx", 0)]
    dt = np_dtype(attrs.get("dtype", "float32"))
    u = jax.random.uniform(
        _seed_key(ctx, attrs), tuple(shape),
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0))
    return {"Out": [u.astype(dt)]}


@register("gaussian_random_batch_size_like", stateful=True,
          no_grad_slots=("Input",))
def _gaussian_random_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ref.shape[attrs.get("input_dim_idx", 0)]
    dt = np_dtype(attrs.get("dtype", "float32"))
    g = jax.random.normal(_seed_key(ctx, attrs), tuple(shape))
    return {"Out": [(g * attrs.get("std", 1.0)
                     + attrs.get("mean", 0.0)).astype(dt)]}


# ---------------------------------------------------------------------------
# candidate-sampling classifiers
# ---------------------------------------------------------------------------

@register("nce", stateful=True, no_grad_slots=("Label", "SampleWeight"))
def _nce(ctx, ins, attrs):
    """nce_op.h: noise-contrastive estimation with a uniform noise
    distribution.  o = sigmoid(logit), b = num_neg/V;
    cost = -log(o/(o+b)) for true classes, -log(b/(o+b)) for sampled."""
    x = ins["Input"][0]
    label = ins["Label"][0].astype(jnp.int32)
    w = ins["Weight"][0]
    V = attrs["num_total_classes"]
    k = attrs.get("num_neg_samples", 10)
    B = x.shape[0]
    num_true = label.shape[1] if label.ndim > 1 else 1
    label = label.reshape(B, num_true)
    neg = jax.random.randint(_seed_key(ctx, attrs), (B, k), 0, V)
    samples = jnp.concatenate([label, neg], axis=1)  # [B, num_true+k]
    logits = jnp.einsum("bd,bsd->bs", x, w[samples])
    if "Bias" in ins and ins["Bias"]:
        logits = logits + ins["Bias"][0].reshape(-1)[samples]
    o = jax.nn.sigmoid(logits)
    b = k / float(V)
    cost_true = -jnp.log(o[:, :num_true] / (o[:, :num_true] + b) + 1e-20)
    cost_neg = -jnp.log(b / (o[:, num_true:] + b) + 1e-20)
    cost = cost_true.sum(axis=1) + cost_neg.sum(axis=1)
    if "SampleWeight" in ins and ins["SampleWeight"]:
        cost = cost * ins["SampleWeight"][0].reshape(-1)
    return {"Cost": [cost.reshape(B, 1).astype(x.dtype)],
            "SampleLogits": [o], "SampleLabels": [samples.astype(jnp.int64)]}


@register("hierarchical_sigmoid", no_grad_slots=("Label",))
def _hierarchical_sigmoid(ctx, ins, attrs):
    """hierarchical_sigmoid_op.h + math/matrix_bit_code.h: complete binary
    tree over classes; per-sample loss sums sigmoid cross-entropies along
    the leaf's root path.  SimpleCode: c = label + num_classes,
    index(b) = (c >> (b+1)) - 1, bit(b) = (c >> b) & 1,
    length = floor(log2(c))."""
    x = ins["X"][0]
    w = ins["W"][0]  # [num_classes - 1, D]
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    V = attrs["num_classes"]
    L = max(int(np.ceil(np.log2(V))) + 1, 1)  # static max code length
    c = label + V  # [B]
    bits = jnp.arange(L)
    lengths = jnp.floor(jnp.log2(c.astype(jnp.float32))).astype(jnp.int32)
    valid = bits[None, :] < lengths[:, None]  # [B, L]
    idx = jnp.where(valid, (c[:, None] >> (bits[None, :] + 1)) - 1, 0)
    bit = jnp.where(valid, (c[:, None] >> bits[None, :]) & 1, 0)
    pre = jnp.einsum("bd,bld->bl", x, w[idx])
    if "Bias" in ins and ins["Bias"]:
        pre = pre + ins["Bias"][0].reshape(-1)[idx]
    # loss_b = softplus(pre) - bit*pre summed over valid path bits
    per_bit = jnp.logaddexp(0.0, pre) - bit.astype(pre.dtype) * pre
    loss = jnp.sum(jnp.where(valid, per_bit, 0.0), axis=1)
    return {"Out": [loss.reshape(-1, 1).astype(x.dtype)],
            "PreOut": [pre.astype(x.dtype)]}
