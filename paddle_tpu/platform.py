"""Platform layer: places, device contexts, device pool.

Reference: ``paddle/fluid/platform/place.h:25-80`` (tagged device
addresses), ``device_context.h:42-200`` (per-device handles + the
singleton ``DeviceContextPool``), ``init.cc:76-92`` (device discovery).

TPU-native shape: JAX/PJRT owns streams, allocators and kernels, so a
DeviceContext here wraps the ``jax.Device`` (exposing the PJRT client and
platform metadata) rather than cuBLAS/cuDNN handles; the pool is keyed by
Place exactly like the reference.  Everything compute-related still flows
through the Executor — this module is the device-addressing API surface
(who am I running on, how many chips, memory stats).
"""
from __future__ import annotations

from typing import Dict, List, Union

import jax

# jax version shim: ``jax.shard_map`` is the modern spelling; on older
# jax only ``jax.experimental.shard_map.shard_map`` exists.  Alias it so
# kernel code (and tests) can use one spelling across the supported
# range.
if not hasattr(jax, "shard_map"):  # pragma: no cover - version-dependent
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
        import functools as _functools

        @_functools.wraps(_shard_map)
        def _shard_map_compat(*args, **kwargs):
            # the old experimental shard_map has no replication rule for
            # pallas_call and rejects kernels under its check_rep=True
            # default; the modern jax.shard_map handles this via vma.
            # Default the check off so kernel-bearing bodies work the
            # same across versions (callers may still pass it).
            kwargs.setdefault("check_rep", False)
            return _shard_map(*args, **kwargs)

        jax.shard_map = _shard_map_compat
    except Exception:
        pass


class CPUPlace:
    """Host-device tag (place.h:36)."""

    def __eq__(self, other):
        return isinstance(other, CPUPlace)

    def __hash__(self):
        return hash("cpu")

    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    """TPU device tag (the CUDAPlace analogue; place.h:51)."""

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return isinstance(other, TPUPlace) and other.device_id == self.device_id

    def __hash__(self):
        return hash(("tpu", self.device_id))

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


class CUDAPinnedPlace:
    """Pinned-host tag (place.h:45).  On TPU, host staging buffers are
    managed by the runtime/PJRT, so this is a compat tag that behaves
    like CPUPlace for placement decisions."""

    def __eq__(self, other):
        return isinstance(other, CUDAPinnedPlace)

    def __hash__(self):
        return hash("pinned")

    def __repr__(self):
        return "CUDAPinnedPlace"


CUDAPlace = TPUPlace  # reference-compat alias
Place = Union[CPUPlace, TPUPlace, CUDAPinnedPlace]


def is_tpu_place(p) -> bool:
    return isinstance(p, TPUPlace)


class DeviceContext:
    """Per-device context (device_context.h:42): wraps the jax.Device and
    its PJRT platform metadata."""

    def __init__(self, place: Place):
        self.place = place
        devices = jax.devices()
        if isinstance(place, TPUPlace):
            if place.device_id >= len(devices):
                raise ValueError(
                    f"{place!r}: only {len(devices)} device(s) visible")
            self.device = devices[place.device_id]
        else:
            self.device = jax.devices("cpu")[0] if _has_cpu() else None

    @property
    def platform(self) -> str:
        return self.device.platform if self.device is not None else "cpu"

    def memory_stats(self) -> dict:
        """HBM stats from PJRT (gpu_info.cc capability)."""
        if self.device is None or not hasattr(self.device, "memory_stats"):
            return {}
        try:
            return dict(self.device.memory_stats() or {})
        except Exception:
            return {}

    def synchronize(self) -> None:
        """Wait for outstanding work (the stream Wait analogue)."""
        jax.effects_barrier()

    def __repr__(self):
        return f"DeviceContext({self.place!r}, {self.platform})"


def _has_cpu() -> bool:
    try:
        return bool(jax.devices("cpu"))
    except RuntimeError:
        return False


class DeviceContextPool:
    """Singleton Place→DeviceContext map (device_context.h:200)."""

    _instance: "DeviceContextPool" = None

    def __init__(self):
        self._ctxs: Dict[Place, DeviceContext] = {}

    @classmethod
    def instance(cls) -> "DeviceContextPool":
        if cls._instance is None:
            cls._instance = DeviceContextPool()
        return cls._instance

    def get(self, place: Place) -> DeviceContext:
        if place not in self._ctxs:
            self._ctxs[place] = DeviceContext(place)
        return self._ctxs[place]


# ---------------------------------------------------------------------------
# Platform peak table (observability/perf.py rooflines)
# ---------------------------------------------------------------------------
# device_kind substring (lowercased, spaces stripped) → (dense bf16 peak
# FLOP/s, HBM bandwidth bytes/s).  Vendor datasheet numbers for TPU
# generations; the "cpu" row is a NOMINAL host envelope (labeled
# nominal=True in platform_peaks) so rooflines still compute on the CPU
# backend dev loop — positions there are relative, not absolute.
PLATFORM_PEAKS: Dict[str, tuple] = {
    "v6": (918e12, 1640e9),       # Trillium
    "v5p": (459e12, 2765e9),
    "v5e": (197e12, 819e9),
    "v5lite": (197e12, 819e9),    # "TPU v5 lite" device_kind spelling
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "v2": (46e12, 700e9),
}
_CPU_NOMINAL_PEAKS = (0.5e12, 50e9)


def platform_peaks(device=None) -> dict:
    """Peak FLOP/s + HBM bytes/s for ``device`` (default: first local
    device) from :data:`PLATFORM_PEAKS`; ``{"flops": None, ...}`` when
    the device kind is unknown (rooflines then report intensity only)."""
    if device is None:
        devs = jax.local_devices()
        if not devs:
            return {"device_kind": "none", "platform": "none",
                    "flops": None, "hbm_bytes_per_s": None}
        device = devs[0]
    kind = str(getattr(device, "device_kind", "") or "")
    plat = str(getattr(device, "platform", "") or "")
    norm = kind.lower().replace(" ", "").replace("-", "")
    out = {"device_kind": kind, "platform": plat,
           "flops": None, "hbm_bytes_per_s": None, "nominal": False}
    for tag, (fl, bw) in PLATFORM_PEAKS.items():
        if tag in norm:
            out["flops"], out["hbm_bytes_per_s"] = fl, bw
            return out
    if plat == "cpu":
        out["flops"], out["hbm_bytes_per_s"] = _CPU_NOMINAL_PEAKS
        out["nominal"] = True
    return out


def device_inventory() -> dict:
    """Hardware card for /statusz: platform, device kind/count, and the
    per-device memory limit — so fleet dashboards can label perf series
    by what they ran on.  Never raises (an uninitializable backend
    reports as an error field)."""
    try:
        devs = jax.local_devices()
    except Exception as e:  # pragma: no cover - backend init failure
        return {"error": repr(e)[:200]}
    out = {"platform": devs[0].platform if devs else "none",
           "device_count": len(jax.devices()),
           "local_device_count": len(devs),
           "devices": []}
    for d in devs:
        rec = {"id": d.id, "kind": str(getattr(d, "device_kind", "")),
               "process_index": getattr(d, "process_index", 0)}
        try:
            ms = d.memory_stats() if hasattr(d, "memory_stats") else None
        except Exception:
            ms = None
        rec["memory_limit_bytes"] = (ms or {}).get("bytes_limit")
        out["devices"].append(rec)
    return out


def device_count() -> int:
    """Visible accelerator count (init.cc device discovery)."""
    return len(jax.devices())


def tpu_places(device_ids: List[int] = None) -> List[TPUPlace]:
    ids = device_ids if device_ids is not None else range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]
