"""Parameter attributes (reference: python/paddle/fluid/param_attr.py)."""
from __future__ import annotations

from typing import Optional


class ParamAttr:
    def __init__(
        self,
        name: Optional[str] = None,
        initializer=None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        gradient_clip=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, bool):
            a = ParamAttr()
            a.trainable = arg
            return a
        # an Initializer instance
        return ParamAttr(initializer=arg)


WeightNormParamAttr = ParamAttr  # placeholder parity alias
