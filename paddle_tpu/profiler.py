"""Profiler: host-side spans + device tracing + Chrome-trace export.

Reference: ``paddle/fluid/platform/profiler.h:73`` (RAII RecordEvent/
RecordBlock), ``profiler.py:221`` context managers, ``device_tracer.h``
(CUPTI device records), ``tools/timeline.py`` Chrome-trace conversion.

TPU mapping: host spans are recorded here (same report shape); device-side
tracing delegates to the XLA profiler (``jax.profiler.start_trace`` →
xplane/TensorBoard, the CUPTI analogue).  ``chrome_trace`` emits the
catapult JSON directly — no separate conversion step needed, though
tools/timeline.py exists for file-based workflows.
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

_state = {"enabled": False, "tracer_dir": None}
_events: List[dict] = []
_lock = threading.Lock()

# thread ident → small stable lane id.  ``threading.get_ident() % N``
# could alias two threads into one Chrome-trace lane (idents are reused
# addresses); lanes are assigned densely in first-seen order instead and
# remembered with the thread's name for ``thread_name`` metadata.  The
# OS recycles idents after a thread exits, so a recycled ident whose
# CURRENT thread name differs gets a fresh lane (otherwise short-lived
# workers would inherit a dead thread's lane and its stale label — the
# exact aliasing class the dense mapping exists to fix); both tables are
# bounded (telemetry only: a clear just re-derives lanes on next use).
_lanes: Dict[int, tuple] = {}      # ident -> (lane, thread name)
_lane_names: Dict[int, str] = {}   # lane -> name
_next_lane = 0
_LANE_BOUND = 1024


def is_profiler_enabled() -> bool:
    return _state["enabled"]


def _thread_lane_locked() -> int:
    global _next_lane
    ident = threading.get_ident()
    name = threading.current_thread().name or ""
    ent = _lanes.get(ident)
    if ent is not None and ent[1] == name:
        return ent[0]
    if len(_lane_names) > _LANE_BOUND:
        _lanes.clear()
        _lane_names.clear()
    lane = _next_lane
    _next_lane += 1
    _lanes[ident] = (lane, name)
    _lane_names[lane] = name or f"thread-{lane}"
    return lane


def thread_lane() -> int:
    """This thread's stable lane id (shared with the distributed-trace
    spans so both streams agree on ``tid``)."""
    with _lock:
        return _thread_lane_locked()


def lane_names() -> Dict[int, str]:
    """{lane id: thread name} for ``ph:"M"`` thread_name metadata."""
    with _lock:
        return dict(_lane_names)


def _emit(name: str, t0_ns: int, t1_ns: int, cat: str = "op") -> None:
    """Append one completed span to the event stream.  Internal: the
    runtime telemetry layer (observability/trace.py) reuses it to file
    ``runtime::`` spans alongside user spans."""
    with _lock:
        _events.append({
            "name": name,
            "cat": cat,
            "ts": t0_ns / 1000.0,
            "dur": (t1_ns - t0_ns) / 1000.0,
            "tid": _thread_lane_locked(),
        })


class RecordEvent:
    """RAII span (profiler.h:73).  Usable as context manager or decorator:

        with RecordEvent("step"): ...

        @RecordEvent("step")
        def step(...): ...

    The decorator opens a FRESH span per call (never the shared instance
    state), so decorated functions are re-entrant and thread-safe.
    """

    def __init__(self, name: str):
        self.name = name
        self._t0 = None

    def __enter__(self):
        if _state["enabled"]:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        if self._t0 is not None:
            _emit(self.name, self._t0, time.perf_counter_ns())
            self._t0 = None
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)
        return wrapper


record_event = RecordEvent  # snake_case alias


def start_profiler(state: str = "All", tracer_option=None) -> None:
    """state ∈ {CPU, GPU, All} kept for API parity; device tracing starts an
    XLA profiler session when a trace dir was configured."""
    _state["enabled"] = True
    if _state["tracer_dir"]:
        import jax
        jax.profiler.start_trace(_state["tracer_dir"])


def stop_profiler(sorted_key: Optional[str] = "total",
                  profile_path: Optional[str] = None) -> None:
    _state["enabled"] = False
    if _state["tracer_dir"]:
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _state["tracer_dir"] = None
    if profile_path:
        chrome_trace(profile_path)
    print_summary(sorted_key)


def enable_device_trace(logdir: str) -> None:
    """Arm XLA (xplane) device tracing for the next start_profiler."""
    _state["tracer_dir"] = logdir


def reset_profiler() -> None:
    with _lock:
        _events.clear()


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None):
    """with profiler.profiler(...): ... (reference profiler.py:221)."""
    reset_profiler()
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def events() -> List[dict]:
    with _lock:
        return list(_events)


def print_summary(sorted_key: str = "total") -> None:
    agg = defaultdict(lambda: {"calls": 0, "total": 0.0, "max": 0.0})
    with _lock:
        for e in _events:
            a = agg[e["name"]]
            a["calls"] += 1
            a["total"] += e["dur"]
            a["max"] = max(a["max"], e["dur"])
    if not agg:
        return
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["total"])
    if sorted_key == "calls":
        rows = sorted(agg.items(), key=lambda kv: -kv[1]["calls"])
    width = max(len(n) for n, _ in rows)
    print(f"{'Event':<{width}}  {'Calls':>8} {'Total(us)':>12} "
          f"{'Avg(us)':>12} {'Max(us)':>12}")
    for name, a in rows:
        print(f"{name:<{width}}  {a['calls']:>8} {a['total']:>12.1f} "
              f"{a['total'] / a['calls']:>12.1f} {a['max']:>12.1f}")


def chrome_trace(path: str) -> None:
    """Write catapult trace-event JSON (tools/timeline.py output format).

    Includes ``ph:"M"`` ``process_name``/``thread_name`` metadata so
    Perfetto labels the process row and every thread lane instead of
    showing bare numeric ids."""
    pid = os.getpid()
    with _lock:
        events = [
            {"name": e["name"], "cat": e.get("cat", "op"), "ph": "X",
             "pid": pid, "tid": e["tid"], "ts": e["ts"], "dur": e["dur"]}
            for e in _events
        ]
        names = dict(_lane_names)
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"paddle_tpu (pid {pid})"}}]
    for lane in sorted(names):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": lane, "args": {"name": names[lane]}})
    with open(path, "w") as f:
        json.dump({"traceEvents": meta + events}, f)


def cuda_profiler(*a, **kw):  # parity stub: no CUDA on this backend
    return contextlib.nullcontext()
