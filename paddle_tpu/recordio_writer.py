"""fluid.recordio_writer — convert Python readers to RecordIO files
(reference python/paddle/fluid/recordio_writer.py:20; the storage engine
is the native C RecordIO in ``native/paddle_tpu_native.cc``, format
magic/CRC/compressor compatible with the reference's recordio/ spec).

Each RECORD is one feed dict (a batch fed through the DataFeeder,
including any ``@LEN`` sequence-length companions), so
``data/recordio_utils.reader_creator`` round-trips what this writes.
"""
from __future__ import annotations

import os
import pickle

from .data.native import RecordIOWriter

__all__ = ["convert_reader_to_recordio_file",
           "convert_reader_to_recordio_files"]


def convert_reader_to_recordio_file(filename, reader_creator, feeder,
                                    compressor=1, max_num_records=1000,
                                    feed_order=None):
    """Feed every batch from ``reader_creator`` through ``feeder`` and
    append it as one record; returns the number of records written.
    ``compressor``: 0 = none, 1 = zlib (the snappy slot of the reference
    enum; zlib is the compressor the native library ships)."""
    if feed_order is None:
        feed_order = [v.name for v in feeder.feed_vars]
    counter = 0
    with RecordIOWriter(filename, compressor=compressor,
                    max_chunk_records=max_num_records) as w:
        for batch in reader_creator():
            w.write(pickle.dumps(_record(feeder, batch, feed_order),
                                 protocol=pickle.HIGHEST_PROTOCOL))
            counter += 1
    return counter


def _record(feeder, batch, feed_order):
    """One record = the feed dict restricted to feed_order PLUS any
    ``@LEN`` sequence-length companions the feeder produced — dropping
    them would turn zero-padding into real tokens on read-back."""
    fd = feeder.feed(batch)
    keep = list(feed_order) + [n + suf for n in feed_order
                               for suf in ("@LEN", "@LEN2")
                               if n + suf in fd]
    return {n: fd[n] for n in keep}


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder, compressor=1,
                                     max_num_records=1000, feed_order=None):
    """Same as :func:`convert_reader_to_recordio_file` but splits the
    stream into files of at most ``batch_per_file`` records each."""
    if feed_order is None:
        feed_order = [v.name for v in feeder.feed_vars]
    f_name, f_ext = os.path.splitext(filename)
    assert batch_per_file > 0
    counter = 0
    file_idx = 0
    w = None
    try:
        for batch in reader_creator():
            if w is None:
                w = RecordIOWriter(f"{f_name}-{file_idx:05d}{f_ext}",
                                   compressor=compressor,
                                   max_chunk_records=max_num_records)
                file_idx += 1
            w.write(pickle.dumps(_record(feeder, batch, feed_order),
                                 protocol=pickle.HIGHEST_PROTOCOL))
            counter += 1
            if counter % batch_per_file == 0:
                w.close()
                w = None
    finally:
        if w is not None:
            w.close()
    return counter
