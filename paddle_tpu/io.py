"""Checkpoint save/load + inference-model export.

Reference: ``python/paddle/fluid/io.py`` (save_vars:89, save_persistables:252,
load_persistables:464, save_inference_model:544, load_inference_model:669)
driven by save/load ops (``operators/save_op.cc``).

TPU-native storage: persistable vars are device arrays in the Scope; they are
staged to host and written as one ``.npz`` per save_combine (or one ``.npy``
per var for save_vars), with the pruned program serialized as ``__model__``
JSON — same layout contract as the reference's ``__model__`` + param files.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from .core.executor import Scope, global_scope
from .core.program import Program, Variable, default_main_program

MODEL_FILENAME = "__model__"
PARAMS_FILENAME = "__params__.npz"


def _persistable_vars(program: Program) -> List[Variable]:
    return [v for v in program.global_block.vars.values()
            if v.persistable and v.name != "@RNG_STATE@"]


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in program.global_block.vars.values()
                if predicate is None or predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    if filename is None:
        for v in vars:
            val = scope.find_var(v.name)
            if val is None:
                continue
            np.save(os.path.join(dirname, v.name.replace("/", "__")), np.asarray(val))
    else:
        arrays = {}
        for v in vars:
            val = scope.find_var(v.name)
            if val is not None:
                arrays[v.name] = np.asarray(val)
        np.savez(os.path.join(dirname, filename), **arrays)


def save_persistables(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    save_vars(executor, dirname, program, vars=_persistable_vars(program),
              filename=filename or PARAMS_FILENAME)


def save_params(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    params = [v for v in program.global_block.vars.values() if v.is_parameter]
    save_vars(executor, dirname, program, vars=params,
              filename=filename or PARAMS_FILENAME)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in program.global_block.vars.values()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is None:
        for v in vars:
            path = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if os.path.exists(path):
                scope.set_var(v.name, np.load(path))
    else:
        data = np.load(os.path.join(dirname, filename))
        for v in vars:
            if v.name in data:
                scope.set_var(v.name, data[v.name])


def load_persistables(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    load_vars(executor, dirname, program, vars=_persistable_vars(program),
              filename=filename or PARAMS_FILENAME)


def load_params(executor, dirname, main_program=None, filename=None):
    load_persistables(executor, dirname, main_program, filename)


def save_inference_model(dirname, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable], executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """Prune to the inference subgraph and save program + params
    (reference io.py:544)."""
    program = (main_program or default_main_program()).clone()
    pruned = program.prune([v.name for v in target_vars])
    os.makedirs(dirname, exist_ok=True)
    meta = pruned.to_dict()
    meta["feed_var_names"] = list(feeded_var_names)
    meta["fetch_var_names"] = [v.name for v in target_vars]
    import json
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, pruned,
                      filename=params_filename or PARAMS_FILENAME)
    return [v.name for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import json
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME)) as f:
        meta = json.load(f)
    program = Program.from_dict({"version": meta.get("version", 1),
                                 "blocks": meta["blocks"]})
    load_persistables(executor, dirname, program,
                      filename=params_filename or PARAMS_FILENAME)
    feed_names = meta.get("feed_var_names", [])
    fetch_vars = [program.global_block.var(n) for n in meta.get("fetch_var_names", [])]
    return program, feed_names, fetch_vars
