"""Checkpoint save/load + inference-model export.

Reference: ``python/paddle/fluid/io.py`` (save_vars:89, save_persistables:252,
load_persistables:464, save_inference_model:544, load_inference_model:669)
driven by save/load ops (``operators/save_op.cc``).

TPU-native storage: persistable vars are device arrays in the Scope; they are
staged to host and written as one ``.npz`` per save_combine (or one ``.npy``
per var for save_vars), with the pruned program serialized as ``__model__``
JSON — same layout contract as the reference's ``__model__`` + param files.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from .core.executor import Scope, global_scope
from .core.program import Program, Variable, default_main_program

MODEL_FILENAME = "__model__"
PARAMS_FILENAME = "__params__.npz"


def _atomic_np_write(path: str, save_fn) -> None:
    """Write a numpy file atomically: a crash mid-save can no longer
    leave a silently half-written checkpoint under the final name —
    the previous complete file, if any, stays intact.  One shared
    implementation with the sharded-checkpoint store (unique-tmp +
    fsync + ``os.replace`` + tmp reap)."""
    from .checkpoint.store import atomic_file_write
    atomic_file_write(path, save_fn)


def _load_npz(path: str):
    """np.load with errors that NAME the file: a missing or corrupt
    checkpoint must say which file, not surface a bare KeyError/
    zipfile traceback from deep inside numpy."""
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"checkpoint file {path!r} does not exist — nothing was "
            "saved there, or the directory is wrong")
    try:
        return np.load(path, allow_pickle=False)
    except Exception as e:
        raise RuntimeError(
            f"checkpoint file {path!r} is corrupt or not a checkpoint "
            f"({type(e).__name__}: {e}); a crash mid-save cannot "
            "produce this (saves are atomic) — look for disk faults or "
            "a foreign file") from e


def _persistable_vars(program: Program) -> List[Variable]:
    return [v for v in program.global_block.vars.values()
            if v.persistable and v.name != "@RNG_STATE@"]


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in program.global_block.vars.values()
                if predicate is None or predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    if filename is None:
        for v in vars:
            val = scope.find_var(v.name)
            if val is None:
                continue
            arr = np.asarray(val)
            path = os.path.join(dirname,
                                v.name.replace("/", "__") + ".npy")
            _atomic_np_write(path, lambda f, a=arr: np.save(f, a))
    else:
        arrays = {}
        for v in vars:
            val = scope.find_var(v.name)
            if val is not None:
                arrays[v.name] = np.asarray(val)
        path = os.path.join(dirname, filename)
        _atomic_np_write(path, lambda f: np.savez(f, **arrays))


def save_persistables(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    save_vars(executor, dirname, program, vars=_persistable_vars(program),
              filename=filename or PARAMS_FILENAME)


def save_params(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    params = [v for v in program.global_block.vars.values() if v.is_parameter]
    save_vars(executor, dirname, program, vars=params,
              filename=filename or PARAMS_FILENAME)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in program.global_block.vars.values()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is None:
        for v in vars:
            path = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if os.path.exists(path):
                try:
                    scope.set_var(v.name, np.load(path,
                                                  allow_pickle=False))
                except Exception as e:
                    raise RuntimeError(
                        f"checkpoint file {path!r} for variable "
                        f"{v.name!r} is corrupt "
                        f"({type(e).__name__}: {e})") from e
    else:
        data = _load_npz(os.path.join(dirname, filename))
        for v in vars:
            if v.name in data:
                scope.set_var(v.name, data[v.name])


def load_persistables(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    load_vars(executor, dirname, program, vars=_persistable_vars(program),
              filename=filename or PARAMS_FILENAME)


def load_params(executor, dirname, main_program=None, filename=None):
    load_persistables(executor, dirname, main_program, filename)


def save_inference_model(dirname, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable], executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """Prune to the inference subgraph and save program + params
    (reference io.py:544)."""
    program = (main_program or default_main_program()).clone()
    pruned = program.prune([v.name for v in target_vars])
    os.makedirs(dirname, exist_ok=True)
    meta = pruned.to_dict()
    meta["feed_var_names"] = list(feeded_var_names)
    meta["fetch_var_names"] = [v.name for v in target_vars]
    import json
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, pruned,
                      filename=params_filename or PARAMS_FILENAME)
    return [v.name for v in target_vars]


TRAIN_MODEL_FILENAME = "__train_model__"


def save_train_model(dirname, feeded_var_names: Sequence[str], loss,
                     executor, main_program=None, startup_program=None):
    """Save a TRAINABLE model: the full (unpruned) main program with its
    backward + optimizer ops, the startup program, and the current
    persistable state — everything a native (no-Python-authored) trainer
    needs to run train steps and checkpoints.  Role analogue of the
    reference's train-from-saved-ProgramDesc flow
    (paddle/fluid/train/demo/demo_trainer.cc:1 loads main/startup
    ProgramDescs; test_train_recognize_digits.cc trains from them)."""
    from .core.program import default_startup_program

    program = main_program or default_main_program()
    startup = startup_program or default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    meta = {
        "main": program.to_dict(),
        "startup": startup.to_dict(),
        # to_dict covers blocks only; the seed must survive the
        # round-trip or a resumed dropout stream diverges
        "random_seed": program.random_seed,
        "startup_random_seed": startup.random_seed,
        "feed_var_names": list(feeded_var_names),
        "loss_name": loss if isinstance(loss, str) else loss.name,
    }
    import json
    with open(os.path.join(dirname, TRAIN_MODEL_FILENAME), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, program)


def load_train_model(dirname, executor):
    """Load a save_train_model directory: returns (main_program,
    startup_program, feed_names, loss_name).  The caller runs the
    startup program and then load_persistables to restore state (the
    native trainer bridge does both)."""
    import json
    with open(os.path.join(dirname, TRAIN_MODEL_FILENAME)) as f:
        meta = json.load(f)
    main = Program.from_dict(meta["main"])
    startup = Program.from_dict(meta["startup"])
    main.random_seed = meta.get("random_seed", 0)
    startup.random_seed = meta.get("startup_random_seed", 0)
    return main, startup, meta["feed_var_names"], meta["loss_name"]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import json
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME)) as f:
        meta = json.load(f)
    program = Program.from_dict({"version": meta.get("version", 1),
                                 "blocks": meta["blocks"]})
    load_persistables(executor, dirname, program,
                      filename=params_filename or PARAMS_FILENAME)
    feed_names = meta.get("feed_var_names", [])
    fetch_vars = [program.global_block.var(n) for n in meta.get("fetch_var_names", [])]
    return program, feed_names, fetch_vars
