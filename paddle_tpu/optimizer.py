"""Optimizers: append_backward + per-parameter optimizer ops.

Reference: ``python/paddle/fluid/optimizer.py:39-1082`` — ``minimize`` =
append_backward → gradient clip → regularization → optimization pass that
emits one optimizer op per parameter plus accumulator vars and LR ops.
Structure is preserved; the emitted ops lower to fused XLA update
computations with donated buffers (see ops/optimizer_ops.py).
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional

from .core import unique_name
from .core.backward import append_backward
from .core.program import (
    OP_ROLE_ATTR,
    OP_ROLE_VAR_ATTR,
    OpRole,
    Variable,
    default_main_program,
    default_startup_program,
)
from .clip import append_gradient_clip_ops, error_clip_callback
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_map: Dict = {}
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self.helper: Optional[LayerHelper] = None
        self.type = getattr(self, "type", "sgd")

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program._uid] = self._learning_rate
            return
        if program._uid in self._learning_rate_map:
            return
        helper = LayerHelper("learning_rate")
        lr = helper.create_global_variable(
            shape=(), dtype="float32", persistable=True,
            name=unique_name.generate("learning_rate"))
        helper.set_variable_initializer(
            lr, ConstantInitializer(float(self._learning_rate)))
        self._learning_rate_map[program._uid] = lr

    def _global_learning_rate(self):
        return self._learning_rate_map[default_main_program()._uid]

    def _create_param_lr(self, param: Variable):
        mult = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        lr = self._global_learning_rate()
        if mult == 1.0:
            return lr
        helper = LayerHelper("param_lr")
        out = helper.create_variable_for_type_inference("float32", shape=())
        helper.append_op("scale", {"X": [lr]}, {"Out": [out]},
                         {"scale": float(mult), OP_ROLE_ATTR: OpRole.Optimize})
        return out

    # -- accumulators (reference optimizer.py:148-200) ---------------------
    def _add_accumulator(self, name, param, dtype="float32", fill_value=0.0,
                         shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper(name)
        var = helper.create_global_variable(
            shape=shape if shape is not None else param.shape,
            dtype=dtype, persistable=True,
            name=unique_name.generate(f"{param.name}_{name}"))
        helper.set_variable_initializer(var, ConstantInitializer(fill_value))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks -------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # -- driver (reference minimize:245) -----------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def apply_gradients(self, params_grads):
        program = default_main_program()
        block = program.global_block
        with program.op_role_guard(OpRole.Optimize):
            params_grads = append_gradient_clip_ops(params_grads)
            params_grads = append_regularization_ops(params_grads, self.regularization)
            self._create_global_learning_rate()
            self._create_accumulators(block, [pg[0] for pg in params_grads])
            optimize_ops = []
            for param_and_grad in params_grads:
                if param_and_grad[1] is None or not param_and_grad[0].trainable:
                    continue
                with program.op_role_guard(
                        OpRole.Optimize,
                        [param_and_grad[0].name, param_and_grad[1].name]):
                    op = self._append_optimize_op(block, param_and_grad)
                    optimize_ops.append(op)
            self._finish_update(block, params_grads)
        return optimize_ops

    def _opt_op(self, block, type, inputs, outputs, attrs=None):
        program = block.program
        a = dict(attrs or {})
        a[OP_ROLE_ATTR] = OpRole.Optimize
        a[OP_ROLE_VAR_ATTR] = program.op_role_vars
        ins = {k: [v.name if isinstance(v, Variable) else v for v in vs]
               for k, vs in inputs.items()}
        outs = {k: [v.name if isinstance(v, Variable) else v for v in vs]
                for k, vs in outputs.items()}
        return block.append_op(type, ins, outs, a)


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return self._opt_op(
            block, "sgd",
            {"Param": [p], "Grad": [g], "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p]},
        )


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return self._opt_op(
            block, "momentum",
            {"Param": [p], "Grad": [g], "Velocity": [v],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p], "VelocityOut": [v]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(MomentumOptimizer):
    type = "lars_momentum"

    def __init__(self, learning_rate, momentum, lars_coeff=1e-3,
                 lars_weight_decay=5e-4, **kw):
        super().__init__(learning_rate, momentum, **kw)
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return self._opt_op(
            block, "lars_momentum",
            {"Param": [p], "Grad": [g], "Velocity": [v],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p], "VelocityOut": [v]},
            {"mu": self._momentum, "lars_coeff": self._lars_coeff,
             "lars_weight_decay": self._lars_weight_decay},
        )


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return self._opt_op(
            block, "adagrad",
            {"Param": [p], "Grad": [g], "Moment": [m],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p], "MomentOut": [m]},
            {"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=())
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=())

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return self._opt_op(
            block, "adam",
            {"Param": [p], "Grad": [g],
             "Moment1": [self._get_accumulator("moment1", p)],
             "Moment2": [self._get_accumulator("moment2", p)],
             "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
             "Beta2Pow": [self._get_accumulator("beta2_pow_acc", p)],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p],
             "Moment1Out": [self._get_accumulator("moment1", p)],
             "Moment2Out": [self._get_accumulator("moment2", p)],
             "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", p)],
             "Beta2PowOut": [self._get_accumulator("beta2_pow_acc", p)]},
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )


class AdamaxOptimizer(Optimizer):
    type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=())

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return self._opt_op(
            block, "adamax",
            {"Param": [p], "Grad": [g],
             "Moment": [self._get_accumulator("moment", p)],
             "InfNorm": [self._get_accumulator("inf_norm", p)],
             "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p],
             "MomentOut": [self._get_accumulator("moment", p)],
             "InfNormOut": [self._get_accumulator("inf_norm", p)],
             "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", p)]},
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )


class DecayedAdagradOptimizer(Optimizer):
    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return self._opt_op(
            block, "decayed_adagrad",
            {"Param": [p], "Grad": [g], "Moment": [m],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p], "MomentOut": [m]},
            {"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    type = "adadelta"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("avg_squared_grad", p)
        asu = self._get_accumulator("avg_squared_update", p)
        return self._opt_op(
            block, "adadelta",
            {"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg],
             "AvgSquaredUpdate": [asu],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p], "AvgSquaredGradOut": [asg],
             "AvgSquaredUpdateOut": [asu]},
            {"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator("momentum", p)
        ms = self._get_accumulator("mean_square", p)
        ins = {"Param": [p], "Grad": [g], "Moment": [mom], "MeanSquare": [ms],
               "LearningRate": [self._create_param_lr(p)]}
        outs = {"ParamOut": [p], "MomentOut": [mom], "MeanSquareOut": [ms]}
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            ins["MeanGrad"] = [mg]
            outs["MeanGradOut"] = [mg]
        return self._opt_op(
            block, "rmsprop", ins, outs,
            {"decay": self._rho, "epsilon": self._epsilon,
             "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return self._opt_op(
            block, "ftrl",
            {"Param": [p], "Grad": [g], "SquaredAccumulator": [sq],
             "LinearAccumulator": [lin],
             "LearningRate": [self._create_param_lr(p)]},
            {"ParamOut": [p], "SquaredAccumOut": [sq], "LinearAccumOut": [lin]},
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


# reference-compatible aliases (optimizer.py tail)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer


class ModelAverage(Optimizer):
    """Sliding-window parameter averaging (reference optimizer.py
    ModelAverage + average_accumulates_op.h).

    Appends an ``average_accumulates`` op per trainable parameter to the
    current main program; ``apply()`` swaps the averaged values in (with
    backup), ``restore()`` swaps them back::

        opt.minimize(loss)
        model_average = fluid.optimizer.ModelAverage(0.15)
        ...train...
        with model_average.apply(exe):
            evaluate()
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization=regularization, name=name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        program = default_main_program()
        block = program.global_block
        self.params_grads = [
            (p, p) for p in block.all_parameters() if p.trainable]
        with program.op_role_guard(OpRole.Optimize):
            for param, _ in self.params_grads:
                self._append_average_accumulate_op(block, param)
        self._build_apply_restore()

    def _append_average_accumulate_op(self, block, param):
        sum_1 = self._add_accumulator("sum_1", param)
        sum_2 = self._add_accumulator("sum_2", param)
        sum_3 = self._add_accumulator("sum_3", param)
        num_acc = self._add_accumulator("num_accumulates", param,
                                        dtype="int64", shape=[1])
        old_acc = self._add_accumulator("old_num_accumulates", param,
                                        dtype="int64", shape=[1])
        num_upd = self._add_accumulator("num_updates", param,
                                        dtype="int64", shape=[1])
        self._opt_op(
            block, "average_accumulates",
            {"param": [param], "in_sum_1": [sum_1], "in_sum_2": [sum_2],
             "in_sum_3": [sum_3], "in_num_accumulates": [num_acc],
             "in_old_num_accumulates": [old_acc],
             "in_num_updates": [num_upd]},
            {"out_sum_1": [sum_1], "out_sum_2": [sum_2],
             "out_sum_3": [sum_3], "out_num_accumulates": [num_acc],
             "out_old_num_accumulates": [old_acc],
             "out_num_updates": [num_upd]},
            {"average_window": self.average_window,
             "min_average_window": self.min_average_window,
             "max_average_window": self.max_average_window})

    def _build_apply_restore(self):
        from . import layers
        from .core.program import Program, program_guard

        def mirror(block, var):
            return block.create_var(
                name=var.name, shape=var.shape, dtype=var.dtype,
                persistable=True)

        self.apply_program = Program()
        self.restore_program = Program()
        with program_guard(self.apply_program, Program()):
            block = self.apply_program.global_block
            for param, _ in self.params_grads:
                p = mirror(block, param)
                backup = block.create_var(
                    name=param.name + "@BACKUP", shape=param.shape,
                    dtype=param.dtype, persistable=True)
                block.append_op("assign", {"X": [p.name]},
                                {"Out": [backup.name]}, {})
                accs = [mirror(block, self._get_accumulator(n, param))
                        for n in ("sum_1", "sum_2", "sum_3")]
                total = layers.sums([
                    mirror(block,
                           self._get_accumulator("num_accumulates", param)),
                    mirror(block, self._get_accumulator(
                        "old_num_accumulates", param))])
                cnt = layers.cast(total, param.dtype)
                ssum = layers.sums(accs)
                avg = layers.elementwise_div(
                    ssum, layers.elementwise_max(
                        cnt, layers.fill_constant([1], param.dtype, 1.0)))
                block.append_op("assign", {"X": [avg.name]},
                                {"Out": [p.name]}, {})
        with program_guard(self.restore_program, Program()):
            block = self.restore_program.global_block
            for param, _ in self.params_grads:
                p = mirror(block, param)
                backup = block.create_var(
                    name=param.name + "@BACKUP", shape=param.shape,
                    dtype=param.dtype, persistable=True)
                block.append_op("assign", {"X": [backup.name]},
                                {"Out": [p.name]}, {})

    @contextmanager
    def apply(self, executor, need_restore=True):
        executor.run(self.apply_program)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor):
        executor.run(self.restore_program)
