"""Python-side metric accumulators (reference
``python/paddle/fluid/metrics.py``): numpy state updated from fetched
batch outputs, queried with ``eval()``.  In-graph counterparts live in
``ops/metric_ops.py`` (auc / precision_recall / edit_distance) and
``layers.accuracy``/``layers.auc``.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall",
           "Accuracy", "ChunkEvaluator", "EditDistance", "Auc",
           "DetectionMAP"]


def _to_np(x):
    return np.asarray(x)


class MetricBase:
    """State-holder contract: update(...) per batch, eval() -> metric,
    reset() between passes (metrics.py:46 MetricBase)."""

    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}


class CompositeMetric(MetricBase):
    """Bundle of metrics updated together (metrics.py:141)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise TypeError("add_metric expects a MetricBase instance")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over {0,1} predictions (metrics.py:190)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).reshape(-1).astype(np.int64)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        return self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0


class Recall(MetricBase):
    """Binary recall (metrics.py:239)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).reshape(-1).astype(np.int64)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        return self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0


class Accuracy(MetricBase):
    """Weighted running mean of the in-graph accuracy op's batch values
    (metrics.py:286)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        if weight < 0:
            raise ValueError("weight must be non-negative")
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy has accumulated no batches")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunk-level precision/recall/F1 from per-batch chunk counts
    (metrics.py:336, fed by chunk counting — see ``extract_chunks`` for
    IOB-style tag decoding)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1

    @staticmethod
    def extract_chunks(tags, scheme="IOB", num_types=None):
        """Decode an IOB tag sequence (0=O; B=1+2t, I=2+2t for type t)
        into {(start, end, type)} — the chunk_eval_op.cc decoding."""
        chunks = set()
        start, ctype = None, None
        for i, tag in enumerate(list(tags) + [0]):
            tag = int(tag)
            if tag == 0:
                t, kind = None, "O"
            else:
                t, kind = (tag - 1) // 2, ("B" if (tag - 1) % 2 == 0 else "I")
            if start is not None and (kind in ("B", "O") or t != ctype):
                chunks.add((start, i - 1, ctype))
                start, ctype = None, None
            if kind == "B":
                start, ctype = i, t
            elif kind == "I" and start is None:
                start, ctype = i, t  # tolerant IOB: I after O starts a chunk
        return chunks

    def update_from_tags(self, infer_tags, label_tags, seq_lens=None):
        """Convenience: update from padded tag matrices [B, T]."""
        infer_tags = _to_np(infer_tags)
        label_tags = _to_np(label_tags)
        for b in range(infer_tags.shape[0]):
            ln = (int(seq_lens[b]) if seq_lens is not None
                  else infer_tags.shape[1])
            inf = self.extract_chunks(infer_tags[b, :ln])
            lab = self.extract_chunks(label_tags[b, :ln])
            self.update(len(inf), len(lab), len(inf & lab))


class EditDistance(MetricBase):
    """Average edit distance + instance error rate, fed by the
    edit_distance op's batch outputs (metrics.py:445)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num=None):
        d = _to_np(distances).reshape(-1)
        self.total_distance += float(np.sum(d))
        self.seq_num += int(seq_num) if seq_num is not None else d.size
        self.instance_error += int(np.sum(d > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance has accumulated no sequences")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """Streaming ROC-AUC with threshold buckets — the python twin of the
    auc op (metrics.py:524)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        preds = _to_np(preds)
        pos_score = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        bucket = np.clip((pos_score * self._num_thresholds).astype(np.int64),
                         0, self._num_thresholds)
        np.add.at(self._stat_pos, bucket[labels == 1], 1)
        np.add.at(self._stat_neg, bucket[labels == 0], 1)

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot = tp[-1] * fp[-1]
        if tot == 0:
            return 0.0
        tp_prev = np.concatenate([[0], tp[:-1]])
        fp_prev = np.concatenate([[0], fp[:-1]])
        area = np.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
        return float(area / tot)


class DetectionMAP(MetricBase):
    """Mean average precision over detection results (metrics.py:600
    capability; takes per-image lists of (class, score, matched) records
    accumulated against ground-truth counts)."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 ap_version="integral"):
        super().__init__(name)
        self.ap_version = ap_version
        self.overlap_threshold = overlap_threshold
        self.reset()

    def reset(self):
        self._records = {}   # class -> list of (score, is_tp)
        self._gt_counts = {}

    def update(self, detections, gt_counts):
        """detections: iterable of (class_id, score, is_true_positive);
        gt_counts: {class_id: #ground-truth boxes in this batch}."""
        for cls, score, is_tp in detections:
            self._records.setdefault(int(cls), []).append(
                (float(score), bool(is_tp)))
        for cls, cnt in gt_counts.items():
            self._gt_counts[int(cls)] = self._gt_counts.get(int(cls), 0) + int(cnt)

    def eval(self):
        aps = []
        for cls, gt in self._gt_counts.items():
            if gt == 0:
                continue
            recs = sorted(self._records.get(cls, []), reverse=True)
            tp_cum, ap_points = 0, []
            for i, (score, is_tp) in enumerate(recs):
                tp_cum += int(is_tp)
                ap_points.append((tp_cum / gt, tp_cum / (i + 1)))
            ap, prev_recall = 0.0, 0.0
            for recall, precision in ap_points:
                ap += (recall - prev_recall) * precision
                prev_recall = recall
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
