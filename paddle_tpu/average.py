"""fluid.average — pure-Python weighted averaging
(reference python/paddle/fluid/average.py:28; deprecated there in favor
of fluid.metrics, kept for API parity)."""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number(v):
    return isinstance(v, (int, float)) or (
        isinstance(v, np.ndarray) and v.ndim == 0)


def _is_number_or_matrix(v):
    return _is_number(v) or isinstance(v, np.ndarray)


class WeightedAverage:
    """sum(value * weight) / sum(weight), accumulated host-side."""

    def __init__(self):
        warnings.warn(
            f"The {self.__class__.__name__} is deprecated, please use "
            "fluid.metrics.Accuracy instead.", Warning)
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            # Accept anything exposing __array__ — notably the LazyFetch
            # objects Executor.run returns by default (reading one here
            # flushes the pending batch, same as any other consumption).
            value = np.asarray(value)
            if value.dtype.kind not in "biufc":
                raise ValueError(
                    "The 'value' must be a number(int, float), a numpy "
                    "ndarray, or expose __array__.")
        if not _is_number(weight):
            raise ValueError("The 'weight' must be a number(int, float).")
        if self.numerator is None or self.denominator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator is None:
            raise ValueError(
                "There is no data to be averaged in WeightedAverage.")
        return self.numerator / self.denominator
