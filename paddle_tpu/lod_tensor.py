"""LoDTensor compatibility shim (reference python/paddle/fluid/
lod_tensor.py:23 create_lod_tensor, core LoDTensor).

The TPU framework stores variable-length batches as padded ``[B, T, ...]``
arrays + a length vector (see layers/nn.py module docstring).  This shim
keeps the reference's feed-side API: a ``LoDTensor`` built from ragged
rows + ``recursive_seq_lens`` feeds straight into ``Executor.run`` —
the executor expands it to the padded array and the ``@LEN`` companion.
Level-1 only (nested LoD is intentionally unported)."""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "create_random_int_lodtensor"]


class LoDTensor:
    """Padded data + per-sequence lengths (level-1)."""

    def __init__(self, data: np.ndarray, seq_lens: Sequence[int]):
        self._data = np.asarray(data)
        self._lens = np.asarray(seq_lens, np.int64)
        if self._data.shape[0] != len(self._lens):
            raise ValueError(
                f"padded batch {self._data.shape[0]} != "
                f"{len(self._lens)} sequences")

    # reference API ------------------------------------------------------
    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [list(int(v) for v in self._lens)]

    def lod(self) -> List[List[int]]:
        offsets = [0]
        for v in self._lens:
            offsets.append(offsets[-1] + int(v))
        return [offsets]

    def shape(self):
        return tuple(self._data.shape)

    # padded-contract accessors ------------------------------------------
    @property
    def data(self) -> np.ndarray:
        return self._data

    @property
    def seq_lens(self) -> np.ndarray:
        return self._lens

    def __array__(self, dtype=None):
        return self._data.astype(dtype) if dtype else self._data


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """Build a LoDTensor from (a) a list of per-sequence row lists, (b) a
    packed ``[sum(lens), ...]`` array + lens, or (c) an existing
    LoDTensor (re-lod)."""
    if isinstance(data, LoDTensor):
        return create_lod_tensor(_unpad(data), recursive_seq_lens, place)
    if len(recursive_seq_lens) != 1:
        raise ValueError(
            "create_lod_tensor on TPU supports level-1 sequences only "
            "(nested LoD is intentionally unported; see README)")
    lens = [int(v) for v in recursive_seq_lens[0]]
    if isinstance(data, list):
        rows = [np.asarray(seq) for seq in data]
        if [len(r) for r in rows] != lens:
            raise ValueError(
                f"sequence lengths {[len(r) for r in rows]} do not match "
                f"recursive_seq_lens {lens}")
        packed = np.concatenate(rows) if rows else np.zeros((0, 1))
    else:
        packed = np.asarray(data)
        if packed.shape[0] != sum(lens):
            raise ValueError(
                f"packed rows {packed.shape[0]} != sum(lens) {sum(lens)}")
    # trailing base dims survive; bucket T like DataFeeder._pad so
    # per-batch max-length jitter does not recompile per distinct length
    # (the executor caches per feed-shape signature)
    from .data_feeder import _bucket

    B = len(lens)
    T = _bucket(max(lens)) if lens else 0
    padded = np.zeros((B, T) + packed.shape[1:], packed.dtype)
    off = 0
    for i, ln in enumerate(lens):
        padded[i, :ln] = packed[off:off + ln]
        off += ln
    return LoDTensor(padded, lens)


def _unpad(lt: LoDTensor) -> np.ndarray:
    return np.concatenate([lt.data[i, :ln]
                           for i, ln in enumerate(lt.seq_lens)])


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1) -> LoDTensor:
    """Reference lod_tensor.py create_random_int_lodtensor."""
    lens = [int(v) for v in recursive_seq_lens[0]]
    data = np.random.randint(low, high + 1,
                             (sum(lens),) + tuple(base_shape)).astype(
                                 np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)
