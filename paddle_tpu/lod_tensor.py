"""LoDTensor compatibility shim (reference python/paddle/fluid/
lod_tensor.py:23 create_lod_tensor, core LoDTensor).

The TPU framework stores variable-length batches as padded ``[B, T, ...]``
arrays + a length vector (see layers/nn.py module docstring).  This shim
keeps the reference's feed-side API: a ``LoDTensor`` built from ragged
rows + ``recursive_seq_lens`` feeds straight into ``Executor.run`` —
the executor expands it to the padded array and the ``@LEN`` companion.

Level-2 (nested, reference lod_tensor.h:58 — paragraph -> sentence ->
word): ``recursive_seq_lens = [outer, inner]`` builds a padded
``[B, S, W, ...]`` array + OUTER lengths [B] (``@LEN``) + INNER lengths
[B, S] (``@LEN2``); ``layers.data(lod_level=2)`` declares the same
companions and the nested sequence ops consume them
(ops/sequence_ops.py _nestable).  Deeper nesting is rejected loudly."""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "create_random_int_lodtensor"]


class LoDTensor:
    """Padded data + per-sequence lengths (level-1), or padded-nested
    data + outer/inner lengths (level-2)."""

    def __init__(self, data: np.ndarray, seq_lens: Sequence[int],
                 inner_lens=None):
        self._data = np.asarray(data)
        self._lens = np.asarray(seq_lens, np.int64)
        self._inner = (None if inner_lens is None
                       else np.asarray(inner_lens, np.int64))
        if self._data.shape[0] != len(self._lens):
            raise ValueError(
                f"padded batch {self._data.shape[0]} != "
                f"{len(self._lens)} sequences")
        if self._inner is not None and \
                self._inner.shape[:2] != self._data.shape[:2]:
            raise ValueError(
                f"inner lengths {self._inner.shape} do not match padded "
                f"nested batch {self._data.shape[:2]}")

    # reference API ------------------------------------------------------
    def recursive_sequence_lengths(self) -> List[List[int]]:
        if self._inner is None:
            return [list(int(v) for v in self._lens)]
        outer = [int(v) for v in self._lens]
        inner = [int(self._inner[b, s])
                 for b, n in enumerate(outer) for s in range(n)]
        return [outer, inner]

    def lod(self) -> List[List[int]]:
        levels = self.recursive_sequence_lengths()
        out = []
        for lens in levels:
            offsets = [0]
            for v in lens:
                offsets.append(offsets[-1] + int(v))
            out.append(offsets)
        return out

    def shape(self):
        return tuple(self._data.shape)

    # padded-contract accessors ------------------------------------------
    @property
    def data(self) -> np.ndarray:
        return self._data

    @property
    def seq_lens(self) -> np.ndarray:
        return self._lens

    @property
    def inner_lens(self):
        return self._inner

    def __array__(self, dtype=None):
        return self._data.astype(dtype) if dtype else self._data


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """Build a LoDTensor from (a) a list of per-sequence row lists, (b) a
    packed ``[sum(lens), ...]`` array + lens, or (c) an existing
    LoDTensor (re-lod)."""
    if isinstance(data, LoDTensor):
        return create_lod_tensor(_unpad(data), recursive_seq_lens, place)
    if len(recursive_seq_lens) == 2:
        return _create_nested(data, recursive_seq_lens)
    if len(recursive_seq_lens) != 1:
        raise ValueError(
            "create_lod_tensor supports level-1 and level-2 (nested) "
            "sequences; deeper LoD has no in-scope reference workload "
            "(lod_tensor.h:58 examples are all depth <= 2)")
    lens = [int(v) for v in recursive_seq_lens[0]]
    if isinstance(data, list):
        rows = [np.asarray(seq) for seq in data]
        if [len(r) for r in rows] != lens:
            raise ValueError(
                f"sequence lengths {[len(r) for r in rows]} do not match "
                f"recursive_seq_lens {lens}")
        packed = np.concatenate(rows) if rows else np.zeros((0, 1))
    else:
        packed = np.asarray(data)
        if packed.shape[0] != sum(lens):
            raise ValueError(
                f"packed rows {packed.shape[0]} != sum(lens) {sum(lens)}")
    # trailing base dims survive; bucket T like DataFeeder._pad so
    # per-batch max-length jitter does not recompile per distinct length
    # (the executor caches per feed-shape signature)
    from .data_feeder import _bucket

    B = len(lens)
    T = _bucket(max(lens)) if lens else 0
    padded = np.zeros((B, T) + packed.shape[1:], packed.dtype)
    off = 0
    for i, ln in enumerate(lens):
        padded[i, :ln] = packed[off:off + ln]
        off += ln
    return LoDTensor(padded, lens)


def _create_nested(data, recursive_seq_lens) -> LoDTensor:
    """Level-2: outer lens = sentences per sample, inner lens = words per
    sentence (flat, in sample-major order).  ``data`` is the packed
    [sum(inner), ...] word-row array (or nested lists)."""
    from .data_feeder import _bucket

    outer = [int(v) for v in recursive_seq_lens[0]]
    inner = [int(v) for v in recursive_seq_lens[1]]
    if sum(outer) != len(inner):
        raise ValueError(
            f"sum(outer)={sum(outer)} != number of inner sequences "
            f"{len(inner)}")
    if isinstance(data, list):
        packed = np.concatenate(
            [np.asarray(r) for r in data]) if data else np.zeros((0, 1))
    else:
        packed = np.asarray(data)
    if packed.shape[0] != sum(inner):
        raise ValueError(
            f"packed rows {packed.shape[0]} != sum(inner) {sum(inner)}")
    B = len(outer)
    S = _bucket(max(outer)) if outer else 0
    W = _bucket(max(inner)) if inner else 0
    padded = np.zeros((B, S, W) + packed.shape[1:], packed.dtype)
    inner_lens = np.zeros((B, S), np.int64)
    off = 0
    k = 0
    for b, n_sent in enumerate(outer):
        for sidx in range(n_sent):
            ln = inner[k]
            padded[b, sidx, :ln] = packed[off:off + ln]
            inner_lens[b, sidx] = ln
            off += ln
            k += 1
    return LoDTensor(padded, outer, inner_lens)


def _unpad(lt: LoDTensor) -> np.ndarray:
    if lt.inner_lens is not None:
        # nested: pack word rows sentence by sentence (skip all padding)
        rows = [lt.data[b, s, :int(lt.inner_lens[b, s])]
                for b, n in enumerate(lt.seq_lens) for s in range(int(n))]
        return (np.concatenate(rows) if rows
                else np.zeros((0,) + lt.data.shape[3:], lt.data.dtype))
    return np.concatenate([lt.data[i, :ln]
                           for i, ln in enumerate(lt.seq_lens)])


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1) -> LoDTensor:
    """Reference lod_tensor.py create_random_int_lodtensor."""
    lens = [int(v) for v in recursive_seq_lens[0]]
    data = np.random.randint(low, high + 1,
                             (sum(lens),) + tuple(base_shape)).astype(
                                 np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)
