"""Performance attribution plane: XLA cost/memory analysis + rooflines.

The perf twin of the tracing plane (PR 4): instead of guessing where
time goes from wall clocks, attribute it from the XLA side.  On every
executable build — fresh compile, AOT warm start, or compile-cache
hydrate — the executor harvests the compiled executable's
``cost_analysis()`` (flops, bytes accessed, transcendentals) and
``memory_analysis()`` (argument/output/temp/generated-code bytes) into
a bounded table of :class:`PerfRecord`\\ s keyed by the executable's
cache identity.  Each ``Executor.run`` then feeds its measured wall
time back into the record, so every executable carries a live roofline
position: achieved FLOP/s, achieved HBM bandwidth, arithmetic
intensity, and the fraction of the platform peak table
(``platform.PLATFORM_PEAKS``) it reaches — compute-bound vs
memory-bound is data, not folklore.

Alongside the per-executable records, :func:`sample_device_memory`
reads the live PJRT device-memory stats (``bytes_in_use``,
``peak_bytes_in_use``, ``bytes_limit`` per ``jax.local_devices()``
entry, plus host RSS) into ``device_mem.*`` gauges on the stats
registry — which means the fleet view comes for free over the existing
``STATS_PULL`` aggregation path, per-worker labeled like every other
gauge.

Served by the debug server as ``/profilez`` (records + rooflines) and
``/memz`` (live memory), JSON by default, ``?text=1`` for the human
rendering; ``tools/dump_metrics.py --profilez/--memz`` is the operator
CLI.

Strictly opt-in: with ``FLAGS_perf_attribution`` unset (default) the
executor never calls in here beyond one flag read, the lazy-jit build
path is untouched, and no gauges are created.  When set, executables
are compiled ahead-of-time (``lower().compile()`` — the same
executable, eagerly) so the compiled handle is analyzable.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from . import debug_server as _debug_server
from . import stats as _stats
from ..core import flags as _flags

# bounded: a shape-churning process must not leak perf records
_RECORD_CAP = 256
# wall-time samples retained per record for the roofline summary
_WALL_WINDOW = 64

_lock = threading.Lock()
_records: "OrderedDict[str, PerfRecord]" = OrderedDict()
_seq = 0

_perf_metrics = None


def enabled() -> bool:
    """Is cost/memory attribution on (``FLAGS_perf_attribution``)?"""
    try:
        return bool(_flags.get_flags("perf_attribution"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


def _pm():
    """Cached perf metric handles (same rationale as the executor's)."""
    global _perf_metrics
    m = _perf_metrics
    if m is None:
        sc = _stats.scope("perf")
        import types as _t
        m = _t.SimpleNamespace(
            executables=sc.counter(
                "executables", "executables harvested for cost/memory "
                "attribution"),
            harvest_errors=sc.counter("harvest_errors"),
            achieved_gflops=sc.gauge(
                "last_achieved_gflops",
                "achieved GFLOP/s of the most recently observed step"),
            achieved_gbps=sc.gauge(
                "last_achieved_gbps",
                "achieved HBM GB/s of the most recently observed step"),
            peak_frac=sc.gauge(
                "last_frac_of_peak_flops",
                "achieved/peak FLOP/s of the most recent step (0 when "
                "the platform peak is unknown)"),
        )
        _perf_metrics = m
    return m


def cost_dict(compiled) -> dict:
    """``cost_analysis()`` across jax versions: list-of-dict (0.4.x) or
    plain dict (newer); {} when the executable cannot report.  Public:
    bench.py attributes its timed executables through this."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _memory_dict(compiled) -> dict:
    ma = compiled.memory_analysis()
    if ma is None:
        return {}
    out = {}
    for field, key in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("alias_size_in_bytes", "alias_bytes"),
            ("generated_code_size_in_bytes", "generated_code_bytes")):
        v = getattr(ma, field, None)
        if v is not None:
            out[key] = int(v)
    if out:
        # resident estimate while the executable runs: args + outputs +
        # scratch + code, minus donated/aliased buffers counted twice
        out["peak_bytes"] = (out.get("argument_bytes", 0)
                             + out.get("output_bytes", 0)
                             + out.get("temp_bytes", 0)
                             + out.get("generated_code_bytes", 0)
                             - out.get("alias_bytes", 0))
    return out


class PerfRecord:
    """Cost/memory attribution + live wall-time window for ONE compiled
    executable (one executor-cache slot)."""

    __slots__ = ("key", "source", "mode", "flops", "bytes_accessed",
                 "transcendentals", "memory", "compile_ms", "steps",
                 "walls", "created_ts")

    def __init__(self, key: str, source: str, mode: str,
                 cost: dict, memory: dict,
                 compile_ms: Optional[float] = None):
        self.key = key
        self.source = source          # "compile" | "disk"
        self.mode = mode              # "run" | "run_steps"
        self.flops = float(cost.get("flops", 0.0) or 0.0)
        self.bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
        self.transcendentals = float(cost.get("transcendentals", 0.0) or 0.0)
        self.memory = dict(memory)
        self.compile_ms = compile_ms
        self.steps = 0
        self.walls: deque = deque(maxlen=_WALL_WINDOW)
        self.created_ts = time.time()

    def observe(self, wall_ms: float) -> None:
        # under the module lock: /profilez sorts the walls window from
        # the HTTP thread while the executor appends from the training
        # thread (deque iteration raises on concurrent mutation)
        with _lock:
            self.steps += 1
            self.walls.append(float(wall_ms))

    def wall_ms_p50(self) -> float:
        with _lock:
            w = sorted(self.walls)
        return w[len(w) // 2] if w else 0.0

    def summary(self, peaks: Optional[dict] = None) -> dict:
        wall = self.wall_ms_p50()  # once: one lock+sort, and the
        # reported p50 always matches the rates computed from it
        out = {
            "key": self.key,
            "source": self.source,
            "mode": self.mode,
            "steps": self.steps,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "transcendentals": self.transcendentals,
            "memory": dict(self.memory),
            "compile_ms": self.compile_ms,
            "wall_ms_p50": round(wall, 3),
        }
        out.update(roofline_numbers(
            self.flops, self.bytes_accessed,
            wall / 1e3 if wall > 0 else None, peaks=peaks))
        return out


def roofline_numbers(flops: float, bytes_accessed: float,
                     seconds: Optional[float],
                     peaks: Optional[dict] = None) -> dict:
    """The shared roofline arithmetic (executor records AND bench.py
    configs use this): achieved rates from ``seconds``, arithmetic
    intensity, position vs the platform peak table.

    ``peaks`` defaults to ``platform.platform_peaks()``; pass
    ``{"flops": None}``-shaped dicts to skip the peak comparison.
    Per-step vs per-dispatch normalization cancels in the rates: a
    run_steps executable's flops cover K steps, and so does its wall.
    """
    out: Dict[str, object] = {}
    if flops and bytes_accessed:
        out["intensity_flops_per_byte"] = round(flops / bytes_accessed, 3)
    if seconds and seconds > 0:
        if flops:
            out["achieved_gflops"] = round(flops / seconds / 1e9, 3)
        if bytes_accessed:
            out["achieved_gbps"] = round(bytes_accessed / seconds / 1e9, 3)
    if peaks is None:
        peaks = platform_peaks_cached()
    peak_fl = peaks.get("flops")
    peak_bw = peaks.get("hbm_bytes_per_s")
    if peak_fl and peak_bw:
        out["peak_gflops"] = round(peak_fl / 1e9, 1)
        out["peak_gbps"] = round(peak_bw / 1e9, 1)
        if peaks.get("nominal"):
            out["peaks_nominal"] = True
        if flops and bytes_accessed:
            balance = peak_fl / peak_bw  # machine balance, flops/byte
            out["machine_balance_flops_per_byte"] = round(balance, 3)
            out["bound"] = ("compute"
                            if flops / bytes_accessed >= balance
                            else "memory")
        if seconds and seconds > 0:
            frac_fl = flops / seconds / peak_fl if flops else 0.0
            frac_bw = (bytes_accessed / seconds / peak_bw
                       if bytes_accessed else 0.0)
            if flops:
                out["frac_of_peak_flops"] = round(frac_fl, 4)
            if bytes_accessed:
                out["frac_of_peak_hbm"] = round(frac_bw, 4)
            # position against the roofline ceiling: how close the
            # dominant axis is to its limit
            out["roofline_frac"] = round(max(frac_fl, frac_bw), 4)
    return out


_peaks_cache = None


def platform_peaks_cached() -> dict:
    """``platform.platform_peaks()`` memoized (device kind never changes
    within a process; the lookup walks jax.local_devices())."""
    global _peaks_cache
    if _peaks_cache is None:
        try:
            from .. import platform as _platform
            _peaks_cache = _platform.platform_peaks()
        except Exception:  # pragma: no cover - backend init failure
            _peaks_cache = {"device_kind": "unknown", "platform": "unknown",
                            "flops": None, "hbm_bytes_per_s": None}
    return _peaks_cache


def harvest(compiled, source: str, mode: str,
            compile_ms: Optional[float] = None) -> Optional[PerfRecord]:
    """Build + register a :class:`PerfRecord` for a freshly resolved
    executable.  Never raises — attribution must never fail a run; a
    handle that cannot report (e.g. a deserialized executable on an old
    jaxlib) is counted in ``perf.harvest_errors`` and skipped."""
    global _seq
    if not enabled():
        return None
    try:
        cost = cost_dict(compiled)
        memory = _memory_dict(compiled)
    except Exception:
        _pm().harvest_errors.inc()
        return None
    with _lock:
        _seq += 1
        key = f"exe-{_seq}"
    rec = PerfRecord(key, source, mode, cost, memory, compile_ms=compile_ms)
    with _lock:
        _records[key] = rec
        while len(_records) > _RECORD_CAP:
            _records.popitem(last=False)
    _pm().executables.inc()
    return rec


def observe_step(rec: PerfRecord, program_key: str, wall_ms: float) -> None:
    """Feed one measured step wall time into a record (the executor's
    ``_record_step`` calls this with the StepStats wall).  The first
    observation renames the record to the executable's telemetry
    program_key so /profilez and the StepStats ring share an identity."""
    with _lock:
        if rec.key != program_key:
            _records.pop(rec.key, None)
            rec.key = program_key
        if _records.get(program_key) is not rec:
            # first observation renames in; an evicted-then-reobserved
            # record (its _CacheEntry still holds it) re-enters here
            # regardless of key — a still-dispatching executable must
            # stay visible on /profilez.  Re-enforce the table bound
            _records[program_key] = rec
            while len(_records) > _RECORD_CAP:
                _records.popitem(last=False)
    rec.observe(wall_ms)
    if wall_ms > 0:
        m = _pm()
        secs = wall_ms / 1e3
        m.achieved_gflops.set(round(rec.flops / secs / 1e9, 3))
        m.achieved_gbps.set(round(rec.bytes_accessed / secs / 1e9, 3))
        peaks = platform_peaks_cached()
        if peaks.get("flops"):
            m.peak_frac.set(round(rec.flops / secs / peaks["flops"], 4))


def records() -> List[PerfRecord]:
    with _lock:
        return list(_records.values())


def get_record(key: str) -> Optional[PerfRecord]:
    with _lock:
        return _records.get(key)


def reset() -> None:
    """Drop every record (tests / bench config isolation)."""
    with _lock:
        _records.clear()


def _host_rss_bytes() -> Optional[int]:
    try:
        import resource
        import sys
        try:
            with open("/proc/self/statm") as f:
                return int(f.read().split()[1]) * resource.getpagesize()
        except OSError:
            # non-Linux fallback: PEAK rss from getrusage — ru_maxrss
            # is bytes on macOS, kilobytes on Linux/BSD
            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return peak if sys.platform == "darwin" else peak * 1024
    except Exception:  # pragma: no cover - exotic hosts
        return None


def sample_device_memory(set_gauges: bool = True) -> dict:
    """Live device-memory snapshot: per-device PJRT ``memory_stats()``
    (bytes_in_use / peak_bytes_in_use / bytes_limit — None each on
    backends that don't report, e.g. CPU) + host RSS.  ``set_gauges``
    mirrors every reported number into ``device_mem.*`` gauges so the
    fleet STATS_PULL merge picks them up."""
    out: Dict[str, object] = {"ts": time.time(), "devices": []}
    sc = _stats.scope("device_mem") if set_gauges else None
    try:
        import jax
        devs = jax.local_devices()
    except Exception as e:  # pragma: no cover - backend init failure
        out["error"] = repr(e)[:200]
        devs = []
    for d in devs:
        try:
            ms = (d.memory_stats() or {}) if hasattr(d, "memory_stats") \
                else {}
        except Exception:
            ms = {}
        rec = {"id": d.id, "kind": str(getattr(d, "device_kind", "")),
               "platform": str(getattr(d, "platform", ""))}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                    "largest_free_block_bytes"):
            rec[key] = ms.get(key)
            if sc is not None and ms.get(key) is not None:
                sc.gauge(f"d{d.id}.{key}").set(ms[key])
        out["devices"].append(rec)
    rss = _host_rss_bytes()
    out["host_rss_bytes"] = rss
    if sc is not None and rss is not None:
        sc.gauge("host_rss_bytes",
                 "resident set size of this process").set(rss)
    return out


# -- debug-server payloads (/memz, /profilez) -------------------------------

def memz() -> dict:
    # a read-only GET must not change the exported metric surface:
    # gauges only when the perf plane is opted in
    out = sample_device_memory(set_gauges=enabled())
    # memory-anatomy fold-in (FLAGS_memory_attribution): who owns the
    # bytes the PJRT numbers report.  Lazy import + flag guard keep the
    # flag-off page byte-identical.
    from . import memory as _memory
    if _memory.enabled():
        out["attribution"] = _memory.ledger(set_gauges=False)
    return out


def profilez() -> dict:
    peaks = platform_peaks_cached()
    return {"ts": time.time(),
            "enabled": enabled(),
            "platform_peaks": peaks,
            "records": [r.summary(peaks=peaks) for r in records()]}


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return str(n)


def memz_text(d: Optional[dict] = None) -> str:
    d = d or memz()
    lines = [f"device memory @ {time.strftime('%H:%M:%S')}"]
    for dev in d.get("devices", []):
        lines.append(
            f"  dev {dev['id']} ({dev.get('kind') or dev.get('platform')}): "
            f"in_use={_fmt_bytes(dev.get('bytes_in_use'))} "
            f"peak={_fmt_bytes(dev.get('peak_bytes_in_use'))} "
            f"limit={_fmt_bytes(dev.get('bytes_limit'))}")
    lines.append(f"  host rss: {_fmt_bytes(d.get('host_rss_bytes'))}")
    if "error" in d:
        lines.append(f"  error: {d['error']}")
    led = d.get("attribution")
    if isinstance(led, dict):
        lines.append("  attribution (FLAGS_memory_attribution):")
        for name, p in sorted((led.get("pools") or {}).items()):
            lines.append(
                f"    {name} [{p.get('kind')}]: "
                f"used={_fmt_bytes(p.get('used'))} "
                f"parked={_fmt_bytes(p.get('parked'))} "
                f"reserved={_fmt_bytes(p.get('reserved'))}")
        for dev, rec in sorted((led.get("devices") or {}).items()):
            lines.append(
                f"    {dev}: in_use={_fmt_bytes(rec.get('bytes_in_use'))} "
                f"attributed={_fmt_bytes(rec.get('attributed'))} "
                f"unattributed={_fmt_bytes(rec.get('unattributed_bytes'))}")
    return "\n".join(lines) + "\n"


def profilez_text(d: Optional[dict] = None) -> str:
    d = d or profilez()
    peaks = d.get("platform_peaks", {})
    lines = [f"perf attribution ({'on' if d.get('enabled') else 'OFF'}) — "
             f"{peaks.get('device_kind') or peaks.get('platform')}"
             + (" [nominal peaks]" if peaks.get("nominal") else "")]
    for r in d.get("records", []):
        lines.append(
            f"  {r['key']} [{r['source']}/{r['mode']}] steps={r['steps']} "
            f"flops={r['flops']:.3g} bytes={r['bytes_accessed']:.3g} "
            f"peak_mem={_fmt_bytes(r.get('memory', {}).get('peak_bytes'))}")
        parts = []
        if "intensity_flops_per_byte" in r:
            parts.append(f"intensity={r['intensity_flops_per_byte']} f/B")
        if "achieved_gflops" in r:
            parts.append(f"achieved={r['achieved_gflops']} GF/s")
        if "achieved_gbps" in r:
            parts.append(f"{r['achieved_gbps']} GB/s")
        if "frac_of_peak_flops" in r:
            parts.append(f"{100 * r['frac_of_peak_flops']:.2f}% peak flops")
        if "frac_of_peak_hbm" in r:
            parts.append(f"{100 * r['frac_of_peak_hbm']:.2f}% peak hbm")
        if "bound" in r:
            parts.append(f"{r['bound']}-bound")
        if parts:
            lines.append("      " + "  ".join(parts))
    if not d.get("records"):
        lines.append("  (no records — FLAGS_perf_attribution=1 and run a "
                     "step)")
    return "\n".join(lines) + "\n"


def export() -> dict:
    """JSON-ready bundle for bench artifacts: records + live memory."""
    return {"profilez": profilez(), "memz": memz()}


def _platform_statusz() -> dict:
    from .. import platform as _platform
    return _platform.device_inventory()


# /statusz hardware card: fleet dashboards label perf series by device
_debug_server.register_provider("platform", _platform_statusz)
