"""Cross-worker metric aggregation: STATS_PULL RPC + fleet merge.

The fan-in half of the observability plane: each worker's framed-TCP
``RPCServer`` (pserver, master, registry — any service) answers a
``STATS_PULL`` message with its registry's ``export_state()`` (served
centrally by ``transport._serve_io``, so service objects need no
changes).  Trainer 0 or the master runs a :class:`FleetAggregator`
over the worker endpoints and merges the per-process snapshots into
one fleet view:

- **counters** are summed into a fleet total AND kept as per-worker
  labeled series (``fleet:rpc_server_bytes_in{worker="trainer-1"}``);
- **gauges** stay per-worker labeled (summing queue depths across
  hosts is meaningless);
- **histograms** are bucket-merged (identical bucket layouts — the
  same code runs fleet-wide — so cumulative ``le`` counts, sums and
  totals add; on a layout mismatch the union of edges is summed).

The merged series are exposed under a ``fleet:`` name prefix so a
debug server can append them to its own ``/metrics`` without colliding
with the local (unprefixed) families.  Unreachable workers are skipped
and counted (``fleet.pull_errors``) — a partial fleet view beats none.
"""
from __future__ import annotations

import json
from typing import Dict, Mapping, Optional

from . import audit as _audit
from . import canary as _canary
from . import capacity as _capacity
from . import history as _history
from . import memory as _memory
from . import stats as _stats
from . import tenant as _tenant
from . import trace as _trace

# wire form version guard (payloads cross processes of possibly
# different builds during a rolling restart)
_WIRE_VERSION = 1


def local_snapshot_payload() -> bytes:
    """The STATS_PULL response body: this process's export_state(),
    plus the metric-history rings when that plane is armed
    (``FLAGS_metrics_history_interval_s`` — series carried as
    ``[[age_s, value], ...]``, ages not wall clocks, so skewed worker
    clocks cannot misalign the fleet merge).  Flag off: the payload is
    byte-identical to the pre-history wire."""
    state = _stats.export_state()
    state["version"] = _WIRE_VERSION
    hist = _history.export_history()
    if hist is not None:
        state["history"] = hist
    # saturation-anatomy riders (FLAGS_capacity_attribution /
    # FLAGS_tenant_accounting): same byte-identity discipline — the
    # key exists only when the plane is armed and has data
    cap = _capacity.export_state()
    if cap is not None:
        state["capacity"] = cap
    ten = _tenant.export_state()
    if ten is not None:
        state["tenants"] = ten
    # correctness-anatomy riders (FLAGS_canary_probe /
    # FLAGS_divergence_check): same discipline again
    can = _canary.export_state()
    if can is not None:
        state["canary"] = can
    aud = _audit.export_state()
    if aud is not None:
        state["audit"] = aud
    # memory-anatomy rider (FLAGS_memory_attribution): the full ledger
    # (pool snapshots + per-device reconciliation) rides the same pull
    mem = _memory.export_state()
    if mem is not None:
        state["memory"] = mem
    return json.dumps(state).encode("utf-8")


def parse_snapshot(payload: bytes) -> dict:
    state = json.loads(bytes(payload).decode("utf-8"))
    if state.get("version") != _WIRE_VERSION:
        raise ValueError(
            f"stats snapshot version {state.get('version')!r} != "
            f"{_WIRE_VERSION}")
    return state


def local_trace_payload() -> bytes:
    """The TRACE_PULL response body: this process's span-ring snapshot
    (``trace.local_trace_snapshot()`` — pid/role/host identity + spans),
    versioned like the stats payload."""
    return _trace.local_snapshot_payload()


def parse_trace_snapshot(payload: bytes) -> dict:
    snap = json.loads(bytes(payload).decode("utf-8"))
    if snap.get("version") != _trace._SNAPSHOT_VERSION:
        raise ValueError(
            f"trace snapshot version {snap.get('version')!r} != "
            f"{_trace._SNAPSHOT_VERSION}")
    return snap


def merge_snapshots(per_worker: Mapping[str, dict]) -> dict:
    """{worker: export_state()} → fleet merge (see module doc)."""
    counters: Dict[str, dict] = {}
    gauges: Dict[str, dict] = {}
    hists: Dict[str, dict] = {}
    # each process's constant labels (process_index/process_count from
    # multihost.py) ride along so per-worker fleet series stay
    # distinguishable even if two workers were given the same name
    worker_labels = {w: dict(per_worker[w].get("labels") or {})
                     for w in per_worker}
    # metric-history series stay PER WORKER (ages are relative to each
    # worker's own pull — summing or zipping across workers would
    # invent alignment the clocks never had)
    history: Dict[str, dict] = {}
    # capacity snapshots stay per-worker AND roll into a fleet view
    # (summed ceilings, min headroom); tenant tables merge into one
    # fleet-wide heavy-hitter table
    capacity_pw: Dict[str, dict] = {}
    tenants_pw: Dict[str, dict] = {}
    # correctness plane: canary streaks union fleet-wide, audit rings
    # feed the cross-worker divergence sentinel
    canary_pw: Dict[str, dict] = {}
    audit_pw: Dict[str, dict] = {}
    # memory ledgers stay per-worker AND roll into a fleet view
    # (pool bytes summed, unattributed residual kept per worker — a
    # summed residual would hide which host is leaking)
    memory_pw: Dict[str, dict] = {}
    for worker in sorted(per_worker):
        state = per_worker[worker]
        if isinstance(state.get("history"), dict):
            history[worker] = state["history"]
        if isinstance(state.get("capacity"), dict):
            capacity_pw[worker] = state["capacity"]
        if isinstance(state.get("tenants"), dict):
            tenants_pw[worker] = state["tenants"]
        if isinstance(state.get("canary"), dict):
            canary_pw[worker] = state["canary"]
        if isinstance(state.get("audit"), dict):
            audit_pw[worker] = state["audit"]
        if isinstance(state.get("memory"), dict):
            memory_pw[worker] = state["memory"]
        for name, m in state.get("metrics", {}).items():
            kind = m.get("kind")
            if kind == "counter":
                ent = counters.setdefault(name,
                                          {"total": 0, "per_worker": {}})
                ent["total"] += m["value"]
                ent["per_worker"][worker] = m["value"]
            elif kind == "gauge":
                ent = gauges.setdefault(name, {"per_worker": {}})
                ent["per_worker"][worker] = m["value"]
            elif kind == "histogram":
                ent = hists.setdefault(
                    name, {"buckets": {}, "sum": 0.0, "count": 0,
                           "per_worker_count": {}})
                for le, cum in m["buckets"].items():
                    ent["buckets"][le] = ent["buckets"].get(le, 0) + cum
                ent["sum"] += m["sum"]
                ent["count"] += m["count"]
                ent["per_worker_count"][worker] = m["count"]
    out = {"workers": sorted(per_worker), "worker_labels": worker_labels,
           "counters": counters, "gauges": gauges, "histograms": hists}
    if history:
        out["history"] = history
    if capacity_pw:
        out["capacity"] = {"per_worker": capacity_pw,
                           "fleet": _capacity.merge_states(capacity_pw)}
    if tenants_pw:
        out["tenants"] = _tenant.merge_states(tenants_pw)
    if canary_pw:
        out["canary"] = {"per_worker": canary_pw,
                         "fleet": _canary.merge_states(canary_pw)}
    if audit_pw:
        out["audit"] = _audit.merge_states(audit_pw)
    if memory_pw:
        out["memory"] = {"per_worker": memory_pw,
                         "fleet": _memory.merge_states(memory_pw)}
    return out


def _le_sort_key(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def fleet_prometheus_text(merged: dict) -> str:
    """Exposition text of a merge, families prefixed ``fleet:``."""
    wlabels = merged.get("worker_labels", {})

    def _labels(worker: str) -> str:
        return _stats.prom_labels({**wlabels.get(worker, {}),
                                   "worker": worker})

    lines = []
    for name, ent in sorted(merged["counters"].items()):
        pn = "fleet:" + _stats._prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_stats._prom_num(ent['total'])}")
        for worker, v in sorted(ent["per_worker"].items()):
            lines.append(pn + _labels(worker) + f" {_stats._prom_num(v)}")
    for name, ent in sorted(merged["gauges"].items()):
        pn = "fleet:" + _stats._prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        for worker, v in sorted(ent["per_worker"].items()):
            lines.append(pn + _labels(worker) + f" {_stats._prom_num(v)}")
    for name, ent in sorted(merged["histograms"].items()):
        pn = "fleet:" + _stats._prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        for le in sorted(ent["buckets"], key=_le_sort_key):
            lines.append(pn + f'_bucket{{le="{le}"}} {ent["buckets"][le]}')
        lines.append(f"{pn}_sum {_stats._prom_num(ent['sum'])}")
        lines.append(f"{pn}_count {ent['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


class FleetAggregator:
    """Pulls per-worker snapshots over STATS_PULL and merges them.

    ``workers`` maps a stable worker label (``trainer-0``, ``ps-1``) to
    the host:port of any RPCServer that worker runs.  ``pull()`` skips
    unreachable workers (counted, remembered in ``last_errors``) so a
    dead trainer never takes the fleet view down with it.
    """

    def __init__(self, workers: Mapping[str, str], trainer_id: int = 0,
                 connect_timeout: float = 2.0):
        self.workers: Dict[str, str] = dict(workers)
        self.last_errors: Dict[str, str] = {}
        self.connect_timeout = connect_timeout
        self._trainer_id = trainer_id
        self._client = None

    def _rpc(self):
        if self._client is None:
            from ..distributed import transport
            self._client = transport.RPCClient(self._trainer_id)
        return self._client

    def add_worker(self, name: str, endpoint: str) -> None:
        self.workers[name] = endpoint

    def remove_worker(self, name: str) -> None:
        self.workers.pop(name, None)
        self.last_errors.pop(name, None)

    def _pull_over_rpc(self, msg_type: int, parse, ok_counter: str,
                       err_counter: str) -> Dict[str, dict]:
        """Concurrent {worker: parse(payload)} fan-out for one of the
        centrally-served observability messages (STATS_PULL /
        TRACE_PULL): k unreachable workers cost ONE connect timeout,
        not k of them — /metrics with an aggregator attached must stay
        inside scrape deadlines."""
        from concurrent.futures import ThreadPoolExecutor
        from ..distributed import transport
        client = self._rpc()
        sc = _stats.scope("fleet")
        out: Dict[str, dict] = {}
        errors: Dict[str, str] = {}

        def one(item):
            worker, ep = item
            try:
                # fast-fail: a never-reachable worker costs ONE bounded
                # probe, not the request path's connect-retry loop (which
                # doubles the connect deadline per dead endpoint)
                if not transport.RPCClient._probe(
                        ep, min(1.0, self.connect_timeout)):
                    raise ConnectionError(f"no listener at {ep}")
                payload = client._raw_request(
                    ep, msg_type, connect_timeout=self.connect_timeout)
                out[worker] = parse(payload)
                sc.counter(ok_counter).inc()
            except Exception as e:
                sc.counter(err_counter).inc()
                errors[worker] = repr(e)[:200]

        items = sorted(self.workers.items())
        if items:
            with ThreadPoolExecutor(
                    max_workers=min(8, len(items)),
                    thread_name_prefix="fleet-pull") as pool:
                list(pool.map(one, items))
        self.last_errors = errors
        return out

    def pull(self) -> Dict[str, dict]:
        """{worker: export_state()} for every reachable worker."""
        from ..distributed import transport
        return self._pull_over_rpc(transport.STATS_PULL, parse_snapshot,
                                   "pulls", "pull_errors")

    def pull_traces(self) -> Dict[str, dict]:
        """{worker: trace snapshot} over TRACE_PULL for every reachable
        worker — the fleet half of trace stitching (unreachable workers
        are skipped and counted like metric pulls)."""
        from ..distributed import transport
        return self._pull_over_rpc(transport.TRACE_PULL,
                                   parse_trace_snapshot,
                                   "trace_pulls", "trace_pull_errors")

    def stitched_trace(self, include_self: Optional[str] = None) -> dict:
        """One Chrome/Perfetto JSON stitched from every reachable
        worker's span ring; ``include_self`` adds THIS process's ring
        under that label (trainer 0 usually wants its own spans in the
        picture)."""
        snaps = self.pull_traces()
        if include_self:
            snaps.setdefault(include_self, _trace.local_trace_snapshot())
        return _trace.stitch_chrome_trace(snaps)

    def merged(self) -> dict:
        return merge_snapshots(self.pull())

    def to_prometheus_text(self) -> str:
        return fleet_prometheus_text(self.merged())

    def export(self) -> dict:
        """JSON-ready merge + pull-error map (bench.py artifact form)."""
        merged = self.merged()
        merged["pull_errors"] = dict(self.last_errors)
        return merged
