"""Per-tenant usage metering: who is consuming the replica's capacity?

Serving and decode submissions may carry an optional, client-supplied
``tenant`` id (wire-optional — an absent id leaves INFER/DECODE frames
byte-identical, so old peers interoperate both ways).  When
``FLAGS_tenant_accounting`` is armed, every submission is folded into a
process-wide :class:`TenantMeter`:

- per-tenant counters: requests, rows, prefill tokens, decode tokens,
  cancellations;
- per-tenant **device-ms**, attributed proportionally from the shared
  batch's device wall (a serving batch splits its materialization wall
  by row share; a decode step splits its step wall evenly over the
  LIVE slots) — so per-tenant device-ms sums to the measured device
  time by construction;
- per-tenant latency p99 over a bounded recent-sample ring.

Cardinality is bounded by a **space-saving** (Misra–Gries family)
heavy-hitter sketch: at most ``FLAGS_tenant_top_k`` tenants are tracked
exactly; when a new tenant arrives at capacity, the smallest tracked
entry is evicted — its accumulated usage rolls into the ``other``
bucket and the newcomer inherits the evicted weight as its error bound
(the classic guarantee: any true heavy hitter stays in the table).  An
adversarial id stream can therefore never grow memory or the
``/tenantz`` payload.

Trust caveat: tenant ids are CLIENT-SUPPLIED and unauthenticated —
this is attribution for capacity planning and abuse triage, not a
security boundary.  Ids are clipped to a sane length; requests without
an id are accounted under ``"-"`` so attribution always sums to the
measured totals.

Off (default): submissions' tenant ids are ignored, no sketch exists,
no metric series register, and the STATS_PULL rider
(:func:`export_state`) returns ``None`` — byte-identical payloads.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

from ..core import flags as _flags
from . import stats as _stats

__all__ = [
    "TenantMeter",
    "enabled",
    "top_k",
    "meter",
    "account",
    "tenantz",
    "tenantz_text",
    "export_state",
    "merge_states",
    "reset",
]

UNTENANTED = "-"        # reserved id for requests without a tenant
OTHER = "other"         # the eviction rollup bucket
CANARY = "__canary__"   # reserved id for golden canary probes (canary.py)
                        # — synthetic traffic, excluded from metering
_MAX_ID_LEN = 64        # clip abusive ids (attribution, not storage)
_LAT_RING = 128         # per-tenant recent-latency samples for p99

_DIMS = ("requests", "rows", "prefill_tokens", "decode_tokens",
         "cancellations", "device_ms", "resident_kv_bytes")


def enabled() -> bool:
    """Is tenant accounting armed (``FLAGS_tenant_accounting``)?"""
    try:
        return bool(_flags.get_flags("tenant_accounting"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


def top_k() -> int:
    try:
        return max(1, int(_flags.get_flags("tenant_top_k")))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return 20


class _Entry:
    __slots__ = ("weight", "error", "dims", "lat")

    def __init__(self, weight: float = 0.0, error: float = 0.0):
        self.weight = weight          # space-saving rank key
        self.error = error            # inherited over-count bound
        self.dims = dict.fromkeys(_DIMS, 0.0)
        self.lat: deque = deque(maxlen=_LAT_RING)

    def fold(self, other: "_Entry") -> None:
        for d in _DIMS:
            self.dims[d] += other.dims[d]
        self.weight += other.weight


class TenantMeter:
    """Bounded per-tenant usage table (space-saving top-K sketch)."""

    def __init__(self, k: Optional[int] = None):
        self.k = int(k) if k else top_k()
        self._lock = threading.Lock()
        self._table: Dict[str, _Entry] = {}
        self._other = _Entry()        # eviction rollup (not ranked)
        self._evictions = 0

    def account(self, tenant: Optional[str], requests: int = 0,
                rows: int = 0, prefill_tokens: int = 0,
                decode_tokens: int = 0, cancellations: int = 0,
                device_ms: float = 0.0,
                resident_kv_bytes: float = 0.0,
                latency_ms: Optional[float] = None) -> None:
        """Fold one observation into the tenant's entry (admitting or
        evicting per the space-saving discipline).
        ``resident_kv_bytes`` is a signed DELTA (blocks held × block
        bytes, + at admission / block growth, − at retire/preempt), so
        the dimension reads as the tenant's CURRENT resident KV
        footprint — "whose bytes", next to device_ms's "whose time"."""
        tid = self._clip(tenant)
        with self._lock:
            ent = self._table.get(tid)
            if ent is None:
                if len(self._table) < self.k:
                    ent = self._table[tid] = _Entry()
                else:
                    # evict the minimum-weight entry into `other`; the
                    # newcomer inherits its weight as the error bound
                    victim = min(self._table, key=lambda t:
                                 self._table[t].weight)
                    evicted = self._table.pop(victim)
                    self._other.fold(evicted)
                    self._evictions += 1
                    ent = self._table[tid] = _Entry(
                        weight=evicted.weight, error=evicted.weight)
            ent.weight += requests
            d = ent.dims
            d["requests"] += requests
            d["rows"] += rows
            d["prefill_tokens"] += prefill_tokens
            d["decode_tokens"] += decode_tokens
            d["cancellations"] += cancellations
            d["device_ms"] += device_ms
            d["resident_kv_bytes"] += resident_kv_bytes
            if latency_ms is not None:
                ent.lat.append(float(latency_ms))

    @staticmethod
    def _clip(tenant: Optional[str]) -> str:
        if not tenant:
            return UNTENANTED
        tid = str(tenant)
        return tid[:_MAX_ID_LEN] if len(tid) > _MAX_ID_LEN else tid

    def snapshot(self) -> dict:
        with self._lock:
            tenants = {}
            for tid, ent in self._table.items():
                rec = {d: (round(v, 3) if d == "device_ms" else int(v))
                       for d, v in ent.dims.items()}
                rec["weight_error"] = round(ent.error, 1)
                if ent.lat:
                    rec["p99_ms"] = round(_stats.percentile_sorted(
                        sorted(ent.lat), 0.99), 3)
                tenants[tid] = rec
            out = {"top_k": self.k,
                   "tracked": len(tenants),
                   "evictions": self._evictions,
                   "tenants": tenants}
            if self._evictions:
                out[OTHER] = {
                    d: (round(v, 3) if d == "device_ms" else int(v))
                    for d, v in self._other.dims.items()}
            return out


# -- module singleton -----------------------------------------------------
_lock = threading.Lock()
_meter: Optional[TenantMeter] = None


def meter(create: bool = True) -> Optional[TenantMeter]:
    """The process-wide meter (lazily created when the flag is on)."""
    global _meter
    with _lock:
        if _meter is None and create and enabled():
            _meter = TenantMeter()
        return _meter


def account(tenant: Optional[str], **kw) -> None:
    """Module-level fold — a no-op unless the flag is armed.  Canary
    probes (the reserved ``__canary__`` id) are synthetic traffic and
    never enter user accounting."""
    if not enabled() or tenant == CANARY:
        return
    m = meter()
    if m is not None:
        m.account(tenant, **kw)


def reset() -> None:
    """Drop the meter (tests / bench config isolation)."""
    global _meter
    with _lock:
        _meter = None


# -- pages / riders -------------------------------------------------------
def tenantz() -> dict:
    """The ``/tenantz`` payload."""
    if not enabled():
        return {"tenants": "disabled (set FLAGS_tenant_accounting)"}
    m = meter(create=False)
    if m is None:
        return {"tenants": {}, "tracked": 0, "top_k": top_k(),
                "evictions": 0}
    return m.snapshot()


def tenantz_text(payload: Optional[dict] = None) -> str:
    """Human rendering of :func:`tenantz` (``/tenantz?text=1``)."""
    payload = payload if payload is not None else tenantz()
    tenants = payload.get("tenants")
    if not isinstance(tenants, dict) or not tenants:
        return "tenants: none tracked (flag off or no traffic)\n"
    lines = [f"top_k={payload.get('top_k')} "
             f"tracked={payload.get('tracked')} "
             f"evictions={payload.get('evictions')}"]
    hdr = ("tenant", "reqs", "rows", "prefill_tok", "decode_tok",
           "cancel", "device_ms", "kv_bytes", "p99_ms")
    lines.append(
        "{:<18}{:>8}{:>8}{:>12}{:>11}{:>8}{:>12}{:>10}{:>9}".format(*hdr))
    ordered = sorted(tenants,
                     key=lambda t: -tenants[t].get("device_ms", 0.0))
    for tid in ordered:
        r = tenants[tid]
        lines.append(
            "{:<18}{:>8}{:>8}{:>12}{:>11}{:>8}{:>12}{:>10}{:>9}".format(
                tid[:17], r.get("requests", 0), r.get("rows", 0),
                r.get("prefill_tokens", 0), r.get("decode_tokens", 0),
                r.get("cancellations", 0), r.get("device_ms", 0.0),
                r.get("resident_kv_bytes", 0), r.get("p99_ms", "-")))
    other = payload.get(OTHER)
    if other:
        lines.append(
            "{:<18}{:>8}{:>8}{:>12}{:>11}{:>8}{:>12}{:>10}{:>9}".format(
                OTHER, other.get("requests", 0), other.get("rows", 0),
                other.get("prefill_tokens", 0),
                other.get("decode_tokens", 0),
                other.get("cancellations", 0),
                other.get("device_ms", 0.0),
                other.get("resident_kv_bytes", 0), "-"))
    return "\n".join(lines) + "\n"


def export_state() -> Optional[dict]:
    """The STATS_PULL rider — None when off / no meter (byte-identity)."""
    if not enabled():
        return None
    m = meter(create=False)
    if m is None:
        return None
    return m.snapshot()


def merge_states(per_worker: Dict[str, dict]) -> dict:
    """Fleet rollup of per-worker :func:`export_state` payloads: dims
    sum per tenant, the merged table re-trims to top-K by request
    count (overflow folds into ``other``), p99 takes the worst worker
    — so a fleet-wide heavy hitter is visible from one endpoint."""
    k = top_k()
    merged: Dict[str, dict] = {}
    other = dict.fromkeys(_DIMS, 0.0)
    evictions = 0
    for snap in per_worker.values():
        if not isinstance(snap, dict):
            continue
        evictions += int(snap.get("evictions") or 0)
        for tid, rec in (snap.get("tenants") or {}).items():
            agg = merged.setdefault(tid, dict.fromkeys(_DIMS, 0.0))
            for d in _DIMS:
                agg[d] += float(rec.get(d) or 0.0)
            p99 = rec.get("p99_ms")
            if isinstance(p99, (int, float)):
                agg["p99_ms"] = max(float(p99),
                                    agg.get("p99_ms", 0.0))
        o = snap.get(OTHER)
        if isinstance(o, dict):
            for d in _DIMS:
                other[d] += float(o.get(d) or 0.0)
    keep = sorted(merged, key=lambda t: -merged[t]["requests"])[:k]
    for tid in list(merged):
        if tid not in keep:
            rec = merged.pop(tid)
            for d in _DIMS:
                other[d] += rec[d]
    out = {"top_k": k, "tracked": len(merged), "evictions": evictions,
           "tenants": {
               tid: {d: (round(v, 3) if d in ("device_ms", "p99_ms")
                         else int(v))
                     for d, v in rec.items()}
               for tid, rec in merged.items()}}
    if any(other.values()):
        out[OTHER] = {d: (round(v, 3) if d == "device_ms" else int(v))
                      for d, v in other.items()}
    return out
