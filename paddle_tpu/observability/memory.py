"""Memory anatomy: per-pool HBM attribution, allocation timelines, and
OOM forensics.

The survey's layer 2 is a dedicated memory subsystem (per-device buddy
allocator, ``memory::Alloc/Free``): the reference treats device memory
as a first-class, ACCOUNTED resource.  Our decode plane now lives or
dies by memory economics — refcounted COW KV blocks, overcommit
admission, and preemption all trade HBM bytes for throughput — yet the
only memory signal so far is the PJRT ``bytes_in_use`` blob: when it
climbs nobody can say which pool owns the bytes, and a RESOURCE_EXHAUSTED
is an unattributed crash.

This module is the process-wide **MemoryLedger**.  Every byte-holding
subsystem registers a :class:`MemoryPool` reporting
``reserved``/``used``/``parked`` bytes through a cheap callback (the
decode KV block pool, the executor's executable cache + persistent
scope, the compile cache's on-disk store, serving batch staging,
checkpoint snapshot buffers).  From the pool set the ledger derives:

- **Reconciliation**: per device, the sum of attributed device-pool
  bytes is compared against the live PJRT ``bytes_in_use`` and the
  difference is published as an explicit ``unattributed_bytes``
  residual — the honesty metric; attribution that can't account for
  itself is decoration.  The identity ``attributed + unattributed ==
  bytes_in_use`` holds exactly by construction (the residual may be
  negative: over-attribution is a bug worth seeing too).  On backends
  whose PJRT client reports no memory stats (CPU), ``bytes_in_use``
  falls back to summing ``jax.live_arrays()`` footprints per device, so
  the identity stays testable everywhere.
- **Allocation event ring**: a bounded ring of
  alloc/free/park/reclaim/preempt/evict records with sizes and pool
  ids (``FLAGS_memory_event_ring`` capacity), the timeline half of a
  post-mortem, renderable as Chrome-trace counter lanes through the
  distributed stitcher (``counter_series``).
- **Leak sentinel**: a periodic audit thread
  (``FLAGS_memory_audit_interval_s``) calls each pool's refcount
  invariant (``BlockAllocator.leaked()`` et al.); a nonzero audit is
  promoted to a ``memory`` health dimension on registry heartbeats,
  exactly like the canary dimension — the fleet sees a leaking replica
  without scraping it.
- **OOM forensics** (:func:`oom_forensics`): on any RESOURCE_EXHAUSTED
  escaping a dispatch the handler dumps a flight record with the full
  ledger, top-N holders, the event-ring tail, and block-pool occupancy
  before the caller re-raises (or recovers) — an OOM becomes a named
  post-mortem instead of a crash.

Surfaces: ``/allocz`` (+``?text=1``), the ledger folded into ``/memz``,
a STATS_PULL rider with fleet merge (:func:`export_state` /
:func:`merge_states` — bytes sum, ``unattributed`` per worker), and the
compact lease-data rider (:func:`lease_rider`) that gives
``ElasticController.memory_headroom(role)`` its per-replica view.

Everything is gated by ``FLAGS_memory_attribution``: off (default) no
pool exists, no ``memory.*`` series is registered, no thread starts,
and every rider returns its absent form — heartbeat, lease, and
STATS_PULL payloads stay byte-identical.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..core import flags as _flags
from . import stats as _stats

__all__ = [
    "MemoryPool",
    "enabled",
    "pool",
    "get",
    "unregister",
    "pools",
    "note_event",
    "events",
    "device_bytes_in_use",
    "ledger",
    "top_holders",
    "allocz",
    "allocz_text",
    "counter_series",
    "export_state",
    "merge_states",
    "lease_rider",
    "health_dimension",
    "run_audit",
    "last_audit",
    "maybe_start_sentinel",
    "is_oom",
    "oom_forensics",
    "last_oom",
    "reset",
]

# event kinds the ring accepts (free-form extras ride along, but the
# kind vocabulary is closed so the stitcher can sign them)
EVENT_KINDS = ("alloc", "free", "park", "reclaim", "preempt", "evict")

# per-pool resident/parked byte deltas each event kind implies (the
# counter-lane reconstruction): alloc grows resident, free/preempt
# shrink it, park moves resident->parked, reclaim/evict shrink parked
_RESIDENT_SIGN = {"alloc": 1, "free": -1, "preempt": -1, "park": -1}
_PARKED_SIGN = {"park": 1, "reclaim": -1, "evict": -1}

# how many ring-tail events / top holders an OOM flight record carries
OOM_EVENT_TAIL = 64
OOM_TOP_HOLDERS = 5


def enabled() -> bool:
    """Is memory attribution armed (``FLAGS_memory_attribution``)?"""
    try:
        return bool(_flags.get_flags("memory_attribution"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


class MemoryPool:
    """One byte-holding subsystem's ledger entry.

    ``callback()`` returns the pool's live byte accounting — a dict
    with any of ``reserved`` (bytes the pool holds from its backing
    store), ``used`` (bytes referenced by live work), ``parked``
    (reclaimable bytes held for reuse, e.g. LRU-parked KV blocks) plus
    free-form metadata (block counts, entry counts...).  It runs under
    the ledger's snapshot pass, so it must be cheap and lock-light.

    ``audit()`` (optional) returns the pool's refcount-invariant
    violation count — nonzero means leaked bytes/blocks; the sentinel
    promotes it to the ``memory`` health dimension.
    """

    __slots__ = ("name", "kind", "device", "callback", "audit_fn")

    def __init__(self, name: str, kind: str,
                 callback: Callable[[], dict],
                 audit: Optional[Callable[[], int]] = None,
                 device: int = 0):
        if kind not in ("device", "host", "disk"):
            raise ValueError(f"unknown pool kind {kind!r}")
        self.name = name
        self.kind = kind
        self.device = int(device)
        self.callback = callback
        self.audit_fn = audit

    def snapshot(self) -> dict:
        try:
            raw = self.callback() or {}
        except Exception as e:  # a dying pool must not kill the ledger
            raw = {"error": repr(e)[:120]}
        out = {"kind": self.kind, "device": self.device,
               "reserved": int(raw.get("reserved", 0) or 0),
               "used": int(raw.get("used", 0) or 0),
               "parked": int(raw.get("parked", 0) or 0)}
        for k, v in raw.items():
            if k not in out:
                out[k] = v
        return out

    def audit(self) -> int:
        if self.audit_fn is None:
            return 0
        try:
            return int(self.audit_fn() or 0)
        except Exception:  # pragma: no cover - audit must never raise
            return 0


# -- module registry -------------------------------------------------------
_lock = threading.Lock()
_pools: Dict[str, MemoryPool] = {}
_ring: Optional[deque] = None
_ring_total = 0
_last_audit: Optional[dict] = None
_last_oom: Optional[dict] = None
_oom_count = 0
_sentinel: Optional[threading.Thread] = None
_sentinel_stop = threading.Event()
_gauges: Dict[str, object] = {}


def pool(name: str, kind: str = "device",
         callback: Optional[Callable[[], dict]] = None,
         audit: Optional[Callable[[], int]] = None,
         device: int = 0) -> MemoryPool:
    """Get-or-create the named pool.  Callers gate on :func:`enabled`
    — a flag-off process never creates a pool (or any series)."""
    with _lock:
        p = _pools.get(name)
        if p is None:
            p = _pools[name] = MemoryPool(
                name, kind, callback or (lambda: {}), audit=audit,
                device=device)
        return p


def get(name: str) -> Optional[MemoryPool]:
    with _lock:
        return _pools.get(name)


def unregister(name: str) -> None:
    with _lock:
        _pools.pop(name, None)


def pools() -> Dict[str, MemoryPool]:
    with _lock:
        return dict(_pools)


def reset() -> None:
    """Drop pools, ring, audit/OOM state and stop the sentinel (tests /
    bench config isolation)."""
    global _ring, _ring_total, _last_audit, _last_oom, _oom_count, _sentinel
    _sentinel_stop.set()
    s = _sentinel
    if s is not None and s.is_alive():
        s.join(timeout=2.0)
    with _lock:
        _pools.clear()
        _ring = None
        _ring_total = 0
        _last_audit = None
        _last_oom = None
        _oom_count = 0
        _sentinel = None
        _gauges.clear()


# -- allocation event ring -------------------------------------------------
def _ring_cap() -> int:
    try:
        return max(int(_flags.get_flags("memory_event_ring")), 16)
    except KeyError:  # pragma: no cover
        return 1024


def note_event(kind: str, pool_name: str, nbytes: int, **extra) -> None:
    """File one allocation event (hot path: one flag read when off,
    one bounded append when armed)."""
    global _ring, _ring_total
    if not enabled():
        return
    ev = {"ts": time.time(), "kind": kind, "pool": pool_name,
          "bytes": int(nbytes)}
    if extra:
        ev.update(extra)
    with _lock:
        if _ring is None:
            _ring = deque(maxlen=_ring_cap())
        _ring.append(ev)
        _ring_total += 1


def events(limit: Optional[int] = None) -> List[dict]:
    """The ring tail (newest last), bounded by ``limit``."""
    with _lock:
        evs = list(_ring) if _ring is not None else []
    if limit is not None and len(evs) > limit:
        evs = evs[-limit:]
    return [dict(e) for e in evs]


def counter_series() -> List[dict]:
    """The event ring rebuilt as per-pool resident/parked byte
    counters — what the trace snapshot carries under ``counters`` and
    the distributed stitcher renders as Chrome ``ph:"C"`` lanes.
    Counters start at 0 at the ring's horizon (the ring is bounded, so
    these are deltas over the visible window, not absolute bytes)."""
    out: List[dict] = []
    run: Dict[str, List[int]] = {}
    for ev in events():
        cur = run.setdefault(ev["pool"], [0, 0])
        nb = int(ev.get("bytes", 0))
        cur[0] += _RESIDENT_SIGN.get(ev["kind"], 0) * nb
        cur[1] += _PARKED_SIGN.get(ev["kind"], 0) * nb
        out.append({"ts_us": ev["ts"] * 1e6, "pool": ev["pool"],
                    "resident": cur[0], "parked": cur[1]})
    return out


# -- reconciliation --------------------------------------------------------
def device_bytes_in_use() -> Dict[str, int]:
    """Live per-device footprint, keyed ``d<id>``.  PJRT
    ``memory_stats()['bytes_in_use']`` where the backend reports it;
    CPU clients report none, so the fallback sums ``jax.live_arrays()``
    per device (a sharded array's bytes split across its devices) —
    the reconciliation identity stays exact either way."""
    import jax
    out: Dict[str, int] = {}
    arrays = None
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # pragma: no cover - backend quirk
            stats = None
        if stats and "bytes_in_use" in stats:
            out[f"d{d.id}"] = int(stats["bytes_in_use"])
            continue
        if arrays is None:
            arrays = [a for a in jax.live_arrays()
                      if getattr(a, "is_deleted", lambda: False)() is False]
        total = 0
        for a in arrays:
            try:
                devs = a.devices()
            except Exception:  # pragma: no cover
                continue
            if d in devs:
                total += int(a.nbytes) // max(len(devs), 1)
        out[f"d{d.id}"] = total
    return out


def _gauge(name: str):
    g = _gauges.get(name)
    if g is None:
        g = _gauges[name] = _stats.scope("memory").gauge(name)
    return g


def ledger(set_gauges: bool = True) -> dict:
    """The full attribution snapshot: every pool's bytes, per-kind
    totals, and the per-device reconciliation with its
    ``unattributed_bytes`` residual."""
    snaps = {name: p.snapshot() for name, p in pools().items()}
    totals = {"device": 0, "host": 0, "disk": 0}
    attributed: Dict[str, int] = {}
    for s in snaps.values():
        footprint = s["reserved"] or (s["used"] + s["parked"])
        totals[s["kind"]] += footprint
        if s["kind"] == "device":
            key = f"d{s['device']}"
            attributed[key] = attributed.get(key, 0) + footprint
    devices = {}
    for dev, in_use in device_bytes_in_use().items():
        attr = attributed.get(dev, 0)
        devices[dev] = {"bytes_in_use": in_use, "attributed": attr,
                        "unattributed_bytes": in_use - attr}
    # attributed device pools PJRT never saw (a stub/test device id):
    # keep the identity honest by showing them against a zero in-use
    for dev, attr in attributed.items():
        if dev not in devices:  # pragma: no cover - stub pools only
            devices[dev] = {"bytes_in_use": 0, "attributed": attr,
                            "unattributed_bytes": -attr}
    with _lock:
        audit = dict(_last_audit) if _last_audit else None
    out = {"pools": snaps, "totals": totals, "devices": devices}
    if audit:
        out["audit"] = audit
    if set_gauges and enabled():
        with _lock:
            for name, s in snaps.items():
                _gauge(f"pool.{name}.used").set(s["used"])
                _gauge(f"pool.{name}.reserved").set(s["reserved"])
            for dev, d in devices.items():
                _gauge(f"{dev}.unattributed_bytes").set(
                    d["unattributed_bytes"])
    return out


def top_holders(led: Optional[dict] = None,
                n: int = OOM_TOP_HOLDERS) -> List[dict]:
    """Pools ranked by live footprint (used+parked, falling back to
    reserved) — the "who owns the bytes" list an OOM dump leads with."""
    led = led if led is not None else ledger(set_gauges=False)
    ranked = []
    for name, s in led.get("pools", {}).items():
        footprint = (s["used"] + s["parked"]) or s["reserved"]
        ranked.append({"pool": name, "bytes": footprint,
                       "kind": s["kind"]})
    ranked.sort(key=lambda e: (-e["bytes"], e["pool"]))
    return ranked[:n]


# -- pages -----------------------------------------------------------------
def allocz(events_limit: int = 128) -> dict:
    """The ``/allocz`` payload: ledger + event-ring tail."""
    if not enabled():
        return {"memory": "disabled (set FLAGS_memory_attribution)"}
    with _lock:
        total = _ring_total
        ooms = _oom_count
    out = {"ledger": ledger(), "events": events(events_limit),
           "events_total": total}
    if ooms:
        out["oom_dumps"] = ooms
    return out


def _fmt_bytes(n) -> str:
    n = float(n)
    sign = "-" if n < 0 else ""
    n = abs(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{sign}{n:.1f}{unit}" if unit != "B" \
                else f"{sign}{int(n)}B"
        n /= 1024.0
    return f"{sign}{n:.1f}GiB"  # pragma: no cover


def allocz_text(payload: Optional[dict] = None) -> str:
    """Human rendering of :func:`allocz` (``/allocz?text=1``)."""
    payload = payload if payload is not None else allocz()
    led = payload.get("ledger")
    if not isinstance(led, dict):
        return "memory: attribution off (set FLAGS_memory_attribution)\n"
    lines = ["== memory ledger =="]
    for name in sorted(led.get("pools", {})):
        s = led["pools"][name]
        lines.append(
            "  {:<28} {:<6} reserved={:<10} used={:<10} parked={}".format(
                name, s["kind"], _fmt_bytes(s["reserved"]),
                _fmt_bytes(s["used"]), _fmt_bytes(s["parked"])))
    for dev in sorted(led.get("devices", {})):
        d = led["devices"][dev]
        lines.append(
            "  {:<28} in_use={:<10} attributed={:<10} "
            "unattributed={}".format(
                dev, _fmt_bytes(d["bytes_in_use"]),
                _fmt_bytes(d["attributed"]),
                _fmt_bytes(d["unattributed_bytes"])))
    audit = led.get("audit")
    if audit:
        leaks = audit.get("leaks") or {}
        verdict = ("LEAK " + ", ".join(
            f"{p}={n}" for p, n in sorted(leaks.items()))
            if leaks else "ok")
        lines.append(f"  audit: {verdict}")
    evs = payload.get("events") or []
    if evs:
        lines.append(f"== events (tail {len(evs)} of "
                     f"{payload.get('events_total', len(evs))}) ==")
        for ev in evs:
            extra = " ".join(f"{k}={v}" for k, v in sorted(ev.items())
                             if k not in ("ts", "kind", "pool", "bytes"))
            lines.append("  {:<8} {:<28} {:>10}  {}".format(
                ev["kind"], ev["pool"], _fmt_bytes(ev["bytes"]), extra))
    return "\n".join(lines) + "\n"


# -- riders ----------------------------------------------------------------
def export_state() -> Optional[dict]:
    """The STATS_PULL rider: the ledger, or None when the flag is off /
    nothing registered (payload byte-identity)."""
    if not enabled():
        return None
    if not _pools:
        return None
    return ledger(set_gauges=False)


def merge_states(per_worker: Dict[str, dict]) -> dict:
    """Fleet rollup of per-worker :func:`export_state` payloads: bytes
    SUM per pool name across workers; the ``unattributed`` residual is
    kept per worker (residuals are local honesty metrics — summing
    them would let one worker's over-attribution hide another's
    leak)."""
    fleet_pools: Dict[str, dict] = {}
    unattributed: Dict[str, int] = {}
    total = 0
    for worker, led in per_worker.items():
        if not isinstance(led, dict):
            continue
        for name, s in (led.get("pools") or {}).items():
            if not isinstance(s, dict):
                continue
            agg = fleet_pools.setdefault(name, {
                "workers": 0, "reserved": 0, "used": 0, "parked": 0})
            agg["workers"] += 1
            for k in ("reserved", "used", "parked"):
                agg[k] += int(s.get(k, 0) or 0)
            total += int(s.get("reserved", 0) or 0) or (
                int(s.get("used", 0) or 0) + int(s.get("parked", 0) or 0))
        devs = led.get("devices") or {}
        if devs:
            unattributed[worker] = sum(
                int(d.get("unattributed_bytes", 0) or 0)
                for d in devs.values() if isinstance(d, dict))
    return {"pools": fleet_pools, "total_bytes": total,
            "unattributed": unattributed}


def headroom_frac() -> Optional[float]:
    """Measured byte headroom of the tightest device pool: the
    fraction of its reserved bytes not referenced by live work (parked
    bytes are reclaimable, so they count as headroom).  None when no
    device pool reports reserved bytes."""
    worst = None
    for p in pools().values():
        if p.kind != "device":
            continue
        s = p.snapshot()
        if s["reserved"] <= 0:
            continue
        frac = max(0.0, 1.0 - s["used"] / s["reserved"])
        if worst is None or frac < worst:
            worst = frac
    return round(worst, 4) if worst is not None else None


def lease_rider() -> Optional[dict]:
    """The compact lease-data rider: byte headroom + live footprint
    (+ leak verdict), or None when the flag is off / nothing pooled —
    lease payloads stay byte-identical by default.  Pool snapshots
    only: no PJRT round per heartbeat."""
    if not enabled():
        return None
    ps = pools()
    if not ps:
        return None
    used = parked = reserved = 0
    for p in ps.values():
        s = p.snapshot()
        if p.kind == "device":
            used += s["used"]
            parked += s["parked"]
            reserved += s["reserved"]
    out = {"memory_bytes": used, "memory_parked_bytes": parked}
    hf = headroom_frac()
    if hf is not None:
        out["memory_headroom_frac"] = hf
    with _lock:
        audit = _last_audit
    leaks = (audit or {}).get("leaks") or {}
    if leaks:
        out["memory_leak"] = sum(leaks.values())
    return out


def health_dimension() -> dict:
    """The heartbeat rider: ``{}`` when unarmed (payload byte-identity)
    else the leak-audit verdict — ``memory: ok`` / ``memory: leak``
    with the offending pool names, exactly the canary dimension's
    shape so the supervisor folds it with the same damping."""
    if not enabled():
        return {}
    with _lock:
        audit = _last_audit
        have = bool(_pools)
    if not have and audit is None:
        return {}
    leaks = (audit or {}).get("leaks") or {}
    if leaks:
        return {"memory": "leak", "memory_pools": sorted(leaks)}
    return {"memory": "ok"}


# -- leak sentinel ---------------------------------------------------------
def run_audit() -> dict:
    """One refcount-invariant sweep over every pool with an audit
    callback; returns {pool: violation count} for the NONZERO ones and
    records the result for :func:`health_dimension`."""
    global _last_audit
    leaks = {}
    for name, p in pools().items():
        n = p.audit()
        if n:
            leaks[name] = n
    rec = {"ts": time.time(), "leaks": leaks}
    with _lock:
        _last_audit = rec
        if enabled():
            _gauge("leaked").set(sum(leaks.values()))
    return leaks


def last_audit() -> Optional[dict]:
    with _lock:
        return dict(_last_audit) if _last_audit else None


def _sentinel_loop(interval_s: float) -> None:
    while not _sentinel_stop.wait(interval_s):
        if not enabled():
            return
        run_audit()


def maybe_start_sentinel() -> bool:
    """Start the periodic leak-audit thread once (idempotent).  A
    no-op — zero threads — unless ``FLAGS_memory_attribution`` is on
    and ``FLAGS_memory_audit_interval_s`` > 0."""
    global _sentinel
    if not enabled():
        return False
    try:
        interval = float(_flags.get_flags("memory_audit_interval_s"))
    except KeyError:  # pragma: no cover
        interval = 0.0
    if interval <= 0:
        return False
    with _lock:
        if _sentinel is not None and _sentinel.is_alive():
            return True
        _sentinel_stop.clear()
        _sentinel = threading.Thread(
            target=_sentinel_loop, args=(interval,), daemon=True,
            name="memory-leak-sentinel")
        _sentinel.start()
    return True


# -- OOM forensics ---------------------------------------------------------
def is_oom(exc: BaseException) -> bool:
    """Does this exception carry an XLA/PJRT out-of-memory verdict?
    (``RESOURCE_EXHAUSTED`` is the status XlaRuntimeError stringifies
    with; the chaos ``oom`` rule raises the same shape.)"""
    return "RESOURCE_EXHAUSTED" in f"{type(exc).__name__}: {exc}"


def oom_forensics(exc: BaseException, site: str) -> Optional[dict]:
    """Name the post-mortem: on a RESOURCE_EXHAUSTED escaping a
    dispatch, capture the full ledger, top-N holders, the event-ring
    tail and pool occupancy into the flight recorder (and a retained
    ``last_oom`` record) BEFORE the caller re-raises or recovers.
    Returns the record, or None when unarmed / not an OOM."""
    global _last_oom, _oom_count
    if not enabled() or not is_oom(exc):
        return None
    led = ledger(set_gauges=False)
    rec = {"ts": time.time(), "site": site, "error": repr(exc)[:300],
           "top_holders": top_holders(led),
           "events": events(OOM_EVENT_TAIL), "ledger": led}
    with _lock:
        _last_oom = rec
        _oom_count += 1
        count = _oom_count
    _stats.scope("memory").counter(
        "oom_dumps", "RESOURCE_EXHAUSTED events that produced a "
        "forensic ledger dump").inc()
    from . import flight as _flight
    top = rec["top_holders"][0]["pool"] if rec["top_holders"] else "?"
    _flight.note("oom_forensics", site=site, top_holder=top,
                 error=repr(exc)[:200], dumps=count)
    _flight.dump(f"oom_{site}")
    return rec


def last_oom() -> Optional[dict]:
    """The most recent OOM forensic record (tests / debug pages)."""
    with _lock:
        return dict(_last_oom) if _last_oom else None
