"""Run-scalar log: append-only JSONL of per-step training scalars.

The print-as-you-train convergence record, made durable (reference:
``benchmark/fluid/fluid_benchmark.py:295`` printed loss/elapsed per
step to stdout; here every ``Executor.run``/``run_steps`` appends one
JSON object per step to a log file instead, so convergence curves
survive the process and two runs can be diffed):

    {"step": 12, "ts": 1754..., "step_ms": 8.3, "samples_per_sec": 7700,
     "scalars": {"mean_0.tmp_0": 2.1409}, "grad_global_norm": 0.83}

- ``scalars`` holds every *scalar-shaped* fetch by name (loss, acc, lr
  if fetched); fetched ``*@GRAD`` vars additionally fold into
  ``grad_global_norm``.  Deferred (LazyFetch) fetches are never forced:
  a record whose values are still on device is QUEUED and written when
  they materialize (the user's first read flushes all pending fetches
  in one batched device_get, so the queue drains on the next step's
  append), when the bounded queue overflows, or at ``flush()``/
  ``close()``/interpreter exit — async-fetch pipelining keeps its one
  round trip per read, not one per logged step.
- ``run_steps`` (K steps per dispatch) emits K records, one per scanned
  step, with per-step scalars sliced from the stacked fetches.
- Rotation is atomic and size-capped (``FLAGS_run_log_max_mb``): when
  an append would exceed the cap the generation chain shifts
  (``<name>.1`` newest … ``.8`` oldest, older ages out) and a fresh
  file starts — a reader never sees a torn line, and a long run keeps
  its whole convergence history up to 8 × the cap.
- :meth:`RunLog.watch` tails the log (rotation-aware) for live
  dashboards/tests; ``tools/runlog_report.py`` renders summaries and
  compares two runs offline.

Strictly opt-in: ``FLAGS_run_log_dir`` empty (default) means
:func:`enabled` is one flag read and the executor does zero extra work
and zero I/O.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

from ..core import flags as _flags


def enabled() -> bool:
    try:
        return bool(_flags.get_flags("run_log_dir"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


# deferred-record queue bound, just past LazyFetch._MAX_PENDING (512):
# the executor's own flush backstop guarantees fetches queued this deep
# have materialized, so hitting the cap (which forces a device sync on
# the head entry) takes a pathological never-read-anything loop
_DEFERRED_CAP = 576


def _is_deferred(v) -> bool:
    """Is ``v`` a fetch value still computing on device?  Reading it
    now would BLOCK on the dispatch the async fetch path exists to
    overlap.  Two shapes: LazyFetch wrappers (duck-typed on the
    materialized/_err slots — executor imports runlog, not vice versa)
    and raw ``jax.Array``\\ s from ``run(return_numpy=False)`` (their
    non-blocking ``is_ready()``).  Sync-free either way."""
    ev = getattr(v, "_done", None)
    if ev is not None:
        return (getattr(v, "_np", None) is None
                and getattr(v, "_err", None) is None
                and not ev.is_set())
    is_ready = getattr(v, "is_ready", None)
    if callable(is_ready):
        try:
            return not is_ready()
        except Exception:
            return False
    return False


class RunLog:
    """One append-only JSONL scalar log with atomic size-capped rotation."""

    def __init__(self, path: str, max_bytes: int = 64 << 20):
        self.path = path
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._f = None
        self._size = 0
        self._step = 0
        # records whose fetch values are still on device (LazyFetch):
        # written in order once they materialize — see defer()/drain()
        self._dlock = threading.Lock()
        self._deferred: deque = deque()

    def _open(self) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")
        self._size = self._f.tell()

    def log(self, record: Dict[str, object]) -> None:
        """Append one record (adds ``step``/``ts`` when absent); rotates
        first when the append would exceed the cap."""
        line = None
        with self._lock:
            self._step += 1
            rec = {"step": record.get("step", self._step),
                   "ts": record.get("ts", time.time())}
            rec.update({k: v for k, v in record.items()
                        if k not in ("step", "ts")})
            line = json.dumps(rec) + "\n"
            nbytes = len(line.encode("utf-8"))  # _size is file BYTES
            if self._f is None:
                self._open()
            if self.max_bytes and self._size and \
                    self._size + nbytes > self.max_bytes:
                self._rotate_locked()
            self._f.write(line)
            self._f.flush()
            self._size += nbytes

    # rotated generations kept per log (<name>.1 newest .. .8 oldest):
    # the whole convergence history survives up to 8 x max_bytes, then
    # the oldest generation ages out — never silently just-one-file
    KEEP_ROTATIONS = 8

    def _rotate_locked(self) -> None:
        self._f.close()
        for k in range(self.KEEP_ROTATIONS, 1, -1):  # shift .7→.8, ...
            older = f"{self.path}.{k - 1}"
            if os.path.exists(older):
                os.replace(older, f"{self.path}.{k}")
        os.replace(self.path, self.path + ".1")  # atomic; no torn lines
        self._f = open(self.path, "a", encoding="utf-8")
        self._size = 0

    def defer(self, entry) -> None:
        """Queue one executor run's fetch entry (see :func:`log_run` /
        :func:`log_run_steps` for the shapes), then write every queued
        record whose values have since materialized.  Entries never
        block on the device except past the queue cap."""
        with self._dlock:
            self._deferred.append(entry)
        self.drain()

    def drain(self, force: bool = False) -> None:
        """Write queued records, oldest first, stopping at the first
        whose values are still on device.  ``force`` materializes them
        instead (one batched flush — the head read resolves every
        pending fetch): close()/flush()/cap-overflow paths."""
        while True:
            with self._dlock:
                if not self._deferred:
                    return
                entry = self._deferred[0]
                if not force and len(self._deferred) <= _DEFERRED_CAP \
                        and any(_is_deferred(v) for v in entry[2]):
                    return
                self._deferred.popleft()
            try:
                self._write_entry(entry)
            except OSError:
                pass

    def _write_entry(self, entry) -> None:
        kind, names, values, k, wall_ms, batch = entry
        if kind == "steps":
            self._write_steps(names, values, k, wall_ms, batch)
            return
        scalars, gsq, had_grads, unreadable = _scalars_of(names, values)
        rec: Dict[str, object] = {"scalars": scalars}
        if wall_ms is not None:
            rec["step_ms"] = round(wall_ms, 3)
            if batch and wall_ms > 0:
                rec["samples_per_sec"] = round(batch / (wall_ms / 1e3), 1)
        if had_grads:
            rec["grad_global_norm"] = round(gsq ** 0.5, 6)
        if unreadable:
            rec["unreadable_fetches"] = unreadable
        self.log(rec)

    def _write_steps(self, names, values, k: int,
                     wall_ms: Optional[float],
                     batch: Optional[int]) -> None:
        import numpy as np
        step_ms = (wall_ms / max(k, 1)) if wall_ms is not None else None
        # materialize only the stacked fetches that are per-step scalars
        # (plus @GRAD fetches, which fold into a per-step global norm)
        cols: Dict[str, object] = {}
        gsq = None
        unreadable = 0
        for name, v in zip(names, values):
            shape = getattr(v, "shape", None)
            if shape is None or len(shape) < 1 or int(shape[0]) != k:
                continue
            if name.endswith("@GRAD"):
                try:
                    a = np.asarray(v).astype("float64",
                                             copy=False).reshape(k, -1)
                    g = (a * a).sum(axis=1)
                    gsq = g if gsq is None else gsq + g
                except Exception:
                    unreadable += 1  # stamped below: loss never silent
                continue
            n = 1
            for dim in shape[1:]:
                n *= int(dim)
            if n != 1:
                continue
            try:
                cols[name] = np.asarray(v).reshape(k)
            except Exception:
                unreadable += 1
                continue
        for i in range(k):
            rec: Dict[str, object] = {
                "scalars": {name: float(col[i])
                            for name, col in cols.items()}}
            if step_ms is not None:
                rec["step_ms"] = round(step_ms, 3)
                if batch and step_ms > 0:
                    rec["samples_per_sec"] = round(
                        batch / (step_ms / 1e3), 1)
            if gsq is not None:
                rec["grad_global_norm"] = round(float(gsq[i]) ** 0.5, 6)
            if unreadable:
                rec["unreadable_fetches"] = unreadable
            rec["k_steps"] = k
            self.log(rec)

    def close(self) -> None:
        self.drain(force=True)
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    # -- reading ----------------------------------------------------------
    @staticmethod
    def read(path: str) -> List[dict]:
        """Parse one JSONL file; a torn final line (live writer racing a
        reader at rotation) is skipped, not fatal."""
        out = []
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            pass
        return out

    def watch(self, poll_interval: float = 0.1,
              timeout: Optional[float] = None,
              from_start: bool = True) -> Iterator[dict]:
        """Tail the log: yield each appended record as it lands.
        Rotation-aware: on an inode change the unread tail of the
        generation the watcher was on (found by inode under
        ``<path>.1..``) and every newer generation are yielded before
        restarting on the fresh file.  Best-effort under pathological
        churn — a generation that ages past the chain (more than
        ``KEEP_ROTATIONS`` rotations within one poll) is gone.
        ``timeout`` bounds the wait for the NEXT record — the generator
        returns after that much inactivity (None = tail forever)."""
        def _stat():
            try:
                st = os.stat(self.path)
                return st.st_size, st.st_ino
            except OSError:
                return 0, None

        def _read_from(p, start):
            try:
                with open(p, encoding="utf-8") as f:
                    f.seek(start)
                    return f.read()
            except OSError:
                return ""

        def _parse_lines(chunk):
            for line in chunk.split("\n"):
                if not line.strip():
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue

        size0, ino = _stat()
        pos = 0 if from_start else size0
        buf = ""
        last_new = time.monotonic()
        while True:
            size, cur_ino = _stat()
            if cur_ino != ino or size < pos:
                # rotated under us (the inode catches it even when the
                # fresh file already grew past our old offset within
                # one poll).  Before restarting on the new file, yield
                # the unread tail of the generation we were on — find
                # it by inode among <path>.1.. — plus any generations
                # rotated in above it, or those records vanish from
                # the tail silently
                rotated = []
                if ino is not None and cur_ino is not None:
                    old_gen = None
                    for k in range(1, self.KEEP_ROTATIONS + 1):
                        try:
                            if os.stat(f"{self.path}.{k}").st_ino == ino:
                                old_gen = k
                                break
                        except OSError:
                            continue
                    if old_gen is not None:
                        for k in range(old_gen, 0, -1):  # oldest first
                            start = pos if k == old_gen else 0
                            rotated.append(
                                _read_from(f"{self.path}.{k}", start))
                for rec in _parse_lines(buf + "".join(rotated)):
                    last_new = time.monotonic()
                    yield rec
                pos, buf, ino = 0, "", cur_ino
            if size > pos:
                with open(self.path, encoding="utf-8") as f:
                    f.seek(pos)
                    chunk = f.read()
                pos += len(chunk.encode("utf-8"))
                buf += chunk
                while "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    last_new = time.monotonic()
                    yield rec
            if timeout is not None and \
                    time.monotonic() - last_new > timeout:
                return
            time.sleep(poll_interval)


# -- the executor-facing default log ----------------------------------------

_lock = threading.Lock()
_default_log: Optional[RunLog] = None
_default_dir: Optional[str] = None
_atexit_armed = False


def default_log() -> Optional[RunLog]:
    """The process-wide log under ``FLAGS_run_log_dir`` (file
    ``run_<pid>.jsonl``), re-created if the flag is re-pointed (tests);
    None when the flag is unset."""
    global _default_log, _default_dir, _atexit_armed
    if not enabled():
        return None
    d = str(_flags.get_flags("run_log_dir"))
    stale = None
    with _lock:
        if _default_log is None or _default_dir != d:
            stale = _default_log
            try:
                max_mb = int(_flags.get_flags("run_log_max_mb"))
            except KeyError:  # pragma: no cover
                max_mb = 64
            _default_log = RunLog(
                os.path.join(d, f"run_{os.getpid()}.jsonl"),
                max_bytes=max_mb << 20)
            _default_dir = d
            if not _atexit_armed:
                import atexit
                atexit.register(flush)  # the tail of a never-read run
                _atexit_armed = True
        log = _default_log
    if stale is not None:
        stale.close()  # outside _lock: close() force-drains (device sync)
    return log


def reset() -> None:
    """Close + forget the default log (tests)."""
    global _default_log, _default_dir
    with _lock:
        log = _default_log
        _default_log, _default_dir = None, None
    if log is not None:
        log.close()


def _scalars_of(fetch_names, values):
    """(scalars dict, grad sum-of-squares, had_grads, unreadable) from
    one run's fetches.  Only scalar-shaped values are materialized
    (LazyFetch .shape is sync-free), except fetched @GRAD vars which
    fold into the global-norm accumulator.  ``unreadable`` counts
    values that raised on read (e.g. a deferred fetch whose buffer a
    later dispatch donated before the drain) — callers stamp it on the
    record so the loss is visible in the log, never silent."""
    import numpy as np
    scalars: Dict[str, float] = {}
    gsq, had_grads, unreadable = 0.0, False, 0
    for name, v in zip(fetch_names, values):
        shape = getattr(v, "shape", None)
        if shape is None:
            continue
        n = 1
        for dim in shape:
            n *= int(dim)
        if name.endswith("@GRAD"):
            try:
                a = np.asarray(v).astype("float64", copy=False)
                gsq += float((a * a).sum())
                had_grads = True
            except Exception:
                unreadable += 1
            continue
        if n != 1:
            continue
        try:
            f = float(np.asarray(v).reshape(()))
        except Exception:
            unreadable += 1
            continue
        scalars[name] = f
    return scalars, gsq, had_grads, unreadable


def log_run(fetch_names, values, wall_ms: Optional[float] = None,
            batch: Optional[int] = None) -> None:
    """One ``Executor.run`` worth of scalars into the default log.
    Deferred (LazyFetch) values queue the record instead of forcing a
    device sync; it writes when they materialize (see :meth:`RunLog.
    drain`).  Never raises — the log must not take training down."""
    log = default_log()
    if log is None:
        return
    try:
        log.defer(("run", list(fetch_names), list(values), 1,
                   wall_ms, batch))
    except OSError:
        pass


def log_run_steps(fetch_names, stacked_values, k: int,
                  wall_ms: Optional[float] = None,
                  batch: Optional[int] = None) -> None:
    """K records from one ``run_steps`` dispatch: per-step scalars are
    sliced out of the stacked ``[K, ...]`` fetches; ``step_ms`` is the
    dispatch wall split evenly (the scan hides per-step boundaries)."""
    log = default_log()
    if log is None:
        return
    try:
        log.defer(("steps", list(fetch_names), list(stacked_values), k,
                   wall_ms, batch))
    except OSError:
        pass


def flush() -> None:
    """Force-write every queued deferred record of the default log
    (materializing still-pending fetches).  Registered at interpreter
    exit so a run that never read its last fetches still logs them."""
    with _lock:
        log = _default_log
    if log is not None:
        log.drain(force=True)


def drain_pending() -> None:
    """Opportunistic non-forcing drain of the default log.  The
    executor calls this at the TOP of run/run_steps, before the next
    dispatch donates buffers: a deferred fetch that aliases persistable
    state must land while its buffer is still alive (by then the
    previous dispatch has typically completed, so this writes without
    blocking).  No-op when nothing is queued."""
    with _lock:
        log = _default_log
    if log is not None:
        log.drain()


def batch_of(feed_vals, axis: int = 0) -> Optional[int]:
    """Batch size for the throughput line: dim ``axis`` of the LARGEST
    feed (by bytes) — the batch-major input dominates the feed payload,
    so an aux scalar or small table sorting first can't win.  None when
    no feed has that axis (throughput is then omitted, not wrong)."""
    best, best_n = None, -1
    for a in feed_vals:
        shp = getattr(a, "shape", None)
        if not shp or len(shp) <= axis:
            continue
        n = getattr(a, "nbytes", 0) or 0
        if n > best_n:
            best, best_n = shp, n
    return int(best[axis]) if best is not None else None
