"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The runtime-facing half of the observability layer (the reference's
profiler counted op spans only; production serving needs rates and
distributions that survive past a trace window).  Design points:

- one flat registry of named metrics; dots namespace them
  (``executor.cache_hits``) and ``scope()`` returns a prefixing view so
  call sites never concatenate strings by hand;
- every metric is thread-safe (executor runs, RPC server handlers and
  the data-layer threads all report concurrently);
- histograms are fixed-bucket (Prometheus semantics: cumulative
  ``le``-bucket counts + sum + count) so ``observe`` is O(log buckets)
  with no allocation — safe on hot paths;
- exports: ``snapshot()`` (plain dict), ``to_prometheus_text()``
  (text exposition format, scrape-ready), ``dump_json()`` (artifact
  files, e.g. bench.py's per-config ``step_stats.json``).

Collection is gated by ``FLAGS_runtime_stats`` at the *instrumentation
sites* (executor/transport/lowering), not here: the registry itself has
no opinion about whether the process wants telemetry.
"""
from __future__ import annotations

import bisect
import json
import re
import threading
import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

# default latency buckets in MILLISECONDS: sub-ms dispatches up through
# multi-second XLA compiles / tunneled RPC round trips
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Sanitize a dotted metric name into Prometheus [a-zA-Z0-9_:]."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_value(v) -> str:
    """Escape a label value per the exposition format."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prom_labels(labels: Dict[str, object], extra: str = "") -> str:
    """``{k="v",...}`` rendering (sorted keys; '' when empty)."""
    parts = [f'{_prom_name(k)}="{_prom_label_value(v)}"'
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def percentile_sorted(sorted_vals: Sequence[float], q: float) -> float:
    """THE percentile over raw samples, shared by every surface.

    Linear interpolation between closest ranks (numpy's default /
    Hyndman-Fan type 7): ``pos = q * (n - 1)``, value interpolated
    between ``sorted_vals[floor(pos)]`` and ``sorted_vals[ceil(pos)]``.
    ``/servingz``'s recent-window gauges, the StepStats summaries and
    the decode plane all route through here so a 5-sample window
    reports the SAME p99 everywhere (they used to disagree: the serving
    gauge truncated to a nearest rank while StepStats interpolated)."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    if n == 1:
        return float(sorted_vals[0])
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_vals[lo]) * (1 - frac) + float(sorted_vals[hi]) * frac


def histogram_percentile(snap: dict, q: float,
                         finite_max: Optional[float] = None) -> float:
    """Quantile estimate from a fixed-bucket snapshot (``{"buckets":
    {le: cumulative}, "count": n}`` — :meth:`Histogram.snapshot`).

    Prometheus ``histogram_quantile`` semantics: find the first bucket
    whose cumulative count reaches ``q * count``, then linearly
    interpolate INSIDE that bucket assuming observations are uniform
    over ``(lower_edge, upper_edge]`` (the first bucket interpolates
    from 0).  Returning the raw upper edge (the old behavior) made
    every small-window quantile snap to a bucket boundary and disagree
    with the raw-sample surfaces; interpolation keeps the estimate
    inside the same bucket but boundary-continuous.  The +Inf bucket
    has no finite width, so a quantile landing there reports the
    largest finite edge (``finite_max`` override) — the honest lower
    bound."""
    total = snap.get("count", 0)
    if not total:
        return 0.0
    target = q * total
    prev_le, prev_cum = 0.0, 0
    last_finite = 0.0
    edges = []
    for le, cum in snap["buckets"].items():
        le_f = float(le) if not isinstance(le, str) else (
            float("inf") if le == "+Inf" else float(le))
        edges.append((le_f, cum))
    for le_f, cum in sorted(edges):
        if le_f != float("inf"):
            last_finite = le_f
        if cum >= target:
            if le_f == float("inf"):
                return finite_max if finite_max is not None else prev_le
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return le_f
            frac = (target - prev_cum) / in_bucket
            return prev_le + (le_f - prev_le) * min(max(frac, 0.0), 1.0)
        prev_le, prev_cum = (le_f if le_f != float("inf") else prev_le), cum
    return finite_max if finite_max is not None else last_finite


def _jsonable(v):
    if isinstance(v, dict):
        # histogram bucket keys are floats incl. +Inf: stringify every
        # key so sort_keys never compares str to float
        return {(k if isinstance(k, str) else _prom_num(k)):
                _jsonable(x) for k, x in v.items()}
    return v


def _prom_num(v) -> str:
    """Prometheus floats: +Inf spelled out, integers without .0 noise."""
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class Counter:
    """Monotonic counter (``inc`` only; ``reset`` zeroes for tests/bench)."""

    kind = "counter"

    def __init__(self, name: str, help_str: str = ""):
        self.name = name
        self.help = help_str
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def snapshot(self):
        return self.value


class Gauge:
    """Point-in-time value (queue depths, resident bytes, flags)."""

    kind = "gauge"

    def __init__(self, name: str, help_str: str = ""):
        self.name = name
        self.help = help_str
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    def snapshot(self):
        return self.value


class Histogram:
    """Fixed-bucket histogram with Prometheus cumulative-``le`` semantics.

    ``buckets`` are the finite upper bounds (inclusive, sorted); an
    implicit ``+Inf`` bucket catches the tail.  ``observe`` is a bisect +
    two adds under the lock — hot-path safe.
    """

    kind = "histogram"

    def __init__(self, name: str, buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                 help_str: str = ""):
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        self.name = name
        self.help = help_str
        self.buckets = tuple(b)
        self._lock = threading.Lock()
        self._counts = [0] * (len(b) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, cum_counts = 0, []
        for c in counts:
            cum += c
            cum_counts.append(cum)
        edges = list(self.buckets) + [float("inf")]
        return {"buckets": {le: c for le, c in zip(edges, cum_counts)},
                "sum": s, "count": total}

    def percentile(self, q: float) -> float:
        """Bucket quantile via the shared :func:`histogram_percentile`
        (linear interpolation inside the covering bucket; the +Inf
        bucket reports the largest finite edge — the honest lower
        bound)."""
        return histogram_percentile(self.snapshot(), q,
                                    finite_max=self.buckets[-1])


class _Scope:
    """Prefixing view over a registry: ``scope('rpc.client').counter('retries')``
    creates/fetches ``rpc.client.retries``."""

    def __init__(self, registry: "StatsRegistry", prefix: str):
        self._registry = registry
        self._prefix = prefix.rstrip(".") + "."

    def counter(self, name: str, help_str: str = "") -> Counter:
        return self._registry.counter(self._prefix + name, help_str)

    def gauge(self, name: str, help_str: str = "") -> Gauge:
        return self._registry.gauge(self._prefix + name, help_str)

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                  help_str: str = "") -> Histogram:
        return self._registry.histogram(self._prefix + name, buckets, help_str)

    def scope(self, name: str) -> "_Scope":
        return _Scope(self._registry, self._prefix + name)


class StatsRegistry:
    """Name → metric map; get-or-create, kind-checked, thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        # constant labels stamped on every exported series (e.g.
        # process_index/process_count from parallel/multihost.py) so a
        # fleet scrape can tell per-process exports apart
        self._constant_labels: Dict[str, str] = {}

    def set_constant_labels(self, labels: Dict[str, object]) -> None:
        """Replace the constant label set ({} clears).  Applied at export
        time only — metric objects and snapshots are label-free."""
        with self._lock:
            self._constant_labels = {str(k): str(v)
                                     for k, v in (labels or {}).items()}

    def constant_labels(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._constant_labels)

    def _get_or_create(self, name: str, factory, kind: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif m.kind != kind:
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}")
            return m

    def counter(self, name: str, help_str: str = "") -> Counter:
        return self._get_or_create(
            name, lambda: Counter(name, help_str), "counter")

    def gauge(self, name: str, help_str: str = "") -> Gauge:
        return self._get_or_create(
            name, lambda: Gauge(name, help_str), "gauge")

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                  help_str: str = "") -> Histogram:
        h = self._get_or_create(
            name, lambda: Histogram(name, buckets, help_str), "histogram")
        if tuple(sorted(float(x) for x in buckets)) != h.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}")
        return h

    def scope(self, prefix: str) -> _Scope:
        return _Scope(self, prefix)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> Iterable[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, object]:
        """{name: value} for counters/gauges, {name: {buckets,sum,count}}
        for histograms — JSON-ready except the +Inf key (see to_json)."""
        with self._lock:
            items = sorted(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition format, one family per metric."""
        with self._lock:
            items = sorted(self._metrics.items())
            clabels = dict(self._constant_labels)
        base = prom_labels(clabels)
        lines = []
        for name, m in items:
            pn = _prom_name(name)
            if m.help:
                lines.append(f"# HELP {pn} {m.help}")
            lines.append(f"# TYPE {pn} {m.kind}")
            if isinstance(m, Histogram):
                snap = m.snapshot()
                for le, cum in snap["buckets"].items():
                    lines.append(
                        pn + "_bucket"
                        + prom_labels(clabels, f'le="{_prom_num(le)}"')
                        + f" {cum}")
                lines.append(f"{pn}_sum{base} {_prom_num(snap['sum'])}")
                lines.append(f"{pn}_count{base} {snap['count']}")
            else:
                lines.append(f"{pn}{base} {_prom_num(m.snapshot())}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready ``snapshot()``: histogram bucket keys (floats incl.
        +Inf) stringified so the dict survives ``json.dumps`` untouched —
        the shape ``observability.export()`` embeds directly."""
        return {k: _jsonable(v) for k, v in self.snapshot().items()}

    def export_state(self) -> dict:
        """Merge-ready wire form for cross-worker aggregation
        (observability/aggregate.py): every metric tagged with its kind,
        histogram buckets as stringified cumulative-``le`` counts, plus
        this process's constant labels."""
        with self._lock:
            items = sorted(self._metrics.items())
            clabels = dict(self._constant_labels)
        metrics = {}
        for name, m in items:
            if isinstance(m, Histogram):
                snap = _jsonable(m.snapshot())
                metrics[name] = {"kind": m.kind, **snap}
            else:
                metrics[name] = {"kind": m.kind, "value": m.snapshot()}
        return {"labels": clabels, "metrics": metrics}

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps({"ts": time.time(), "metrics": self.to_dict()},
                          indent=indent, sort_keys=True)

    def dump_json(self, path: str, indent: int = 2) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(indent=indent))

    def reset(self) -> None:
        """Zero every metric IN PLACE (handles held by call sites stay
        valid — bench.py resets between configs)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()

    def clear(self) -> None:
        """Drop every registration (tests only: held handles detach)."""
        with self._lock:
            self._metrics.clear()


_default = StatsRegistry()


def default_registry() -> StatsRegistry:
    return _default


# module-level conveniences over the default registry
def counter(name: str, help_str: str = "") -> Counter:
    return _default.counter(name, help_str)


def gauge(name: str, help_str: str = "") -> Gauge:
    return _default.gauge(name, help_str)


def histogram(name: str, buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
              help_str: str = "") -> Histogram:
    return _default.histogram(name, buckets, help_str)


def scope(prefix: str) -> _Scope:
    return _default.scope(prefix)


def snapshot() -> Dict[str, object]:
    return _default.snapshot()


def to_prometheus_text() -> str:
    return _default.to_prometheus_text()


def to_dict() -> Dict[str, object]:
    return _default.to_dict()


def export_state() -> dict:
    return _default.export_state()


def to_json(indent: Optional[int] = None) -> str:
    return _default.to_json(indent)


def dump_json(path: str, indent: int = 2) -> None:
    _default.dump_json(path, indent)


def reset() -> None:
    _default.reset()
