"""Golden canary prober: does the replica still give *recorded* answers?

A latency SLO cannot see a replica that answers fast and wrong.  When
``FLAGS_canary_probe`` is armed, a background prober periodically
replays a small **golden set** — input -> expected-output pairs
recorded with ``tools/golden.py record`` against a trusted build —
through the *real* submit path of every registered replica target
(serving batcher, decode engine), compares replies against the goldens
with per-model rtol, and maintains per-target pass/fail streaks:

- probes are tenant-tagged :data:`tenant.CANARY` (``__canary__``) so
  per-tenant metering (PR 15) excludes them from user accounting;
- a sustained fail streak (``FLAGS_canary_fail_streak``) flips the
  ``canary`` health dimension on every registry heartbeat to ``fail``
  — the supervisor's ``quarantine_on_canary_fail`` policy then DRAINs
  (never kills) the replica after its own hysteresis;
- every failure leaves a flight-recorder note and a ``canary.*``
  counter; ``/canaryz`` (+``?text=1``) renders streaks; a STATS_PULL
  rider merges fleet-wide; probe busy time is tracked so benches can
  report ``canary_overhead_frac``.

Trust caveats, in order of importance: a canary pass is a REGRESSION
check against whatever build recorded the goldens — it is not a proof
of correctness, and a golden set recorded on a broken build blesses
the breakage.  Comparison is rtol-based, so it tolerates (and is
blind to) numeric drift inside the tolerance; record goldens with the
tightest rtol the hardware pair actually sustains.  Coverage is the
golden set: a bug outside the recorded inputs' activation paths passes
every probe.  The cross-replica divergence sentinel (audit.py) is the
complementary check that needs no trusted recording at all.

Off (default): no thread, no targets probed, no metric series, the
health dimension is empty and the STATS_PULL/lease riders return
``None`` — byte-identical payloads.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import flags as _flags
from . import flight as _flight
from . import stats as _stats
from .tenant import CANARY as CANARY_TENANT

__all__ = [
    "CANARY_TENANT",
    "GoldenSet",
    "CanaryProber",
    "enabled",
    "encode_array",
    "decode_array",
    "compare_pairs",
    "load_goldens",
    "register_target",
    "unregister_target",
    "prober",
    "probe_once",
    "health_dimension",
    "lease_rider",
    "overhead_frac",
    "canaryz",
    "canaryz_text",
    "export_state",
    "merge_states",
    "maybe_start_from_flags",
    "stop",
    "reset",
]

GOLDEN_FORMAT_VERSION = 1
_MAX_FAIL_DETAIL = 200


def enabled() -> bool:
    """Is the canary prober armed (``FLAGS_canary_probe``)?"""
    try:
        return bool(_flags.get_flags("canary_probe"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


def _interval_s() -> float:
    try:
        return max(0.05, float(_flags.get_flags("canary_interval_s")))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return 5.0


def _default_rtol() -> float:
    try:
        return float(_flags.get_flags("canary_rtol"))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return 1e-5


def fail_streak_threshold() -> int:
    try:
        return max(1, int(_flags.get_flags("canary_fail_streak")))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return 3


# -- golden-set codec -----------------------------------------------------
def encode_array(a) -> dict:
    """JSON-safe encoding of one array (dtype/shape/flat data)."""
    a = np.asarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data": np.ascontiguousarray(a).ravel().tolist()}


def decode_array(d: dict) -> np.ndarray:
    return np.asarray(d["data"], dtype=np.dtype(d["dtype"])).reshape(
        d["shape"])


class GoldenSet:
    """A recorded golden set: per-model cases + per-model rtol.

    On-disk format (``tools/golden.py record``)::

        {"format_version": 1,
         "provenance": {...},            # free-form trust breadcrumbs
         "models": {"<model>": {
             "rtol": 1e-5,               # optional, beats FLAGS_canary_rtol
             "cases": [{"feeds": {name: enc_array},
                        "expect": [[name, enc_array], ...]}, ...]}}}
    """

    def __init__(self, payload: Optional[dict] = None):
        payload = payload or {}
        self.provenance = dict(payload.get("provenance") or {})
        self.models: Dict[str, dict] = {}
        for model, spec in (payload.get("models") or {}).items():
            cases = []
            for case in spec.get("cases") or ():
                feeds = {n: decode_array(e)
                         for n, e in (case.get("feeds") or {}).items()}
                expect = [(n, decode_array(e))
                          for n, e in (case.get("expect") or ())]
                cases.append({"feeds": feeds, "expect": expect})
            self.models[str(model)] = {
                "rtol": spec.get("rtol"), "cases": cases}

    def rtol(self, model: str) -> float:
        r = self.models.get(model, {}).get("rtol")
        return float(r) if r is not None else _default_rtol()

    def cases(self, model: str) -> List[dict]:
        return self.models.get(model, {}).get("cases", [])

    def n_cases(self) -> int:
        return sum(len(m["cases"]) for m in self.models.values())

    def to_payload(self) -> dict:
        return {"format_version": GOLDEN_FORMAT_VERSION,
                "provenance": self.provenance,
                "models": {
                    model: {
                        **({"rtol": spec["rtol"]}
                           if spec.get("rtol") is not None else {}),
                        "cases": [{
                            "feeds": {n: encode_array(a) for n, a
                                      in c["feeds"].items()},
                            "expect": [[n, encode_array(a)] for n, a
                                       in c["expect"]],
                        } for c in spec["cases"]]}
                    for model, spec in self.models.items()}}


def load_goldens(path: str) -> GoldenSet:
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    ver = payload.get("format_version")
    if ver != GOLDEN_FORMAT_VERSION:
        raise ValueError(f"golden set {path}: format_version {ver!r} "
                         f"(prober speaks {GOLDEN_FORMAT_VERSION})")
    return GoldenSet(payload)


def compare_pairs(expect, got, rtol: float) -> Optional[str]:
    """Compare a reply against a golden.  ``None`` = pass, else a short
    human mismatch description (first offense wins)."""
    got_by_name = {str(n): v for n, v in (got or ())}
    for name, exp in expect:
        g = got_by_name.get(str(name))
        if g is None:
            return f"missing output '{name}'"
        ga, ea = np.asarray(g), np.asarray(exp)
        if ga.shape != ea.shape:
            return (f"'{name}' shape {list(ga.shape)} != golden "
                    f"{list(ea.shape)}")
        if not np.allclose(ga.astype(np.float64, copy=False),
                           ea.astype(np.float64, copy=False),
                           rtol=rtol, atol=rtol, equal_nan=True):
            diff = np.abs(ga.astype(np.float64) - ea.astype(np.float64))
            return (f"'{name}' max_abs_diff={float(np.max(diff)):.6g} "
                    f"(rtol={rtol:g})")
    return None


# -- the prober -----------------------------------------------------------
class _Target:
    __slots__ = ("name", "model", "submit_fn")

    def __init__(self, name: str, model: str,
                 submit_fn: Callable[[dict, str], list]):
        self.name = name          # replica-qualified, e.g. serving/m/r0
        self.model = model        # golden-set model this target answers
        self.submit_fn = submit_fn


class CanaryProber:
    """Replays goldens through registered targets, keeps streaks."""

    def __init__(self, goldens: Optional[GoldenSet] = None):
        self.goldens = goldens or GoldenSet()
        self._lock = threading.Lock()
        self._targets: Dict[str, _Target] = {}
        self._streaks: Dict[str, dict] = {}
        self._busy_s = 0.0
        self._armed_t0 = time.monotonic()
        self._cycles = 0
        sc = _stats.scope("canary")
        self._c_probes = sc.counter(
            "probes", "golden canary cases replayed (FLAGS_canary_probe)")
        self._c_failures = sc.counter(
            "failures", "golden canary case mismatches")
        self._g_failing = sc.gauge(
            "failing_targets", "targets at/over FLAGS_canary_fail_streak")

    # targets ------------------------------------------------------------
    def register(self, name: str, model: str,
                 submit_fn: Callable[[dict, str], list]) -> None:
        with self._lock:
            self._targets[str(name)] = _Target(str(name), str(model),
                                               submit_fn)
            self._streaks.setdefault(str(name), {
                "pass_streak": 0, "fail_streak": 0, "probes": 0,
                "failures": 0, "last_fail": None})

    def unregister(self, name: str) -> None:
        with self._lock:
            self._targets.pop(str(name), None)

    # probing ------------------------------------------------------------
    def run_cycle(self) -> dict:
        """One synchronous probe cycle over every (target x case).
        Returns ``{target: ok_bool}`` for this cycle."""
        with self._lock:
            targets = list(self._targets.values())
        results: Dict[str, bool] = {}
        t0 = time.monotonic()
        for tgt in targets:
            cases = self.goldens.cases(tgt.model)
            if not cases:
                continue
            rtol = self.goldens.rtol(tgt.model)
            fail: Optional[str] = None
            for i, case in enumerate(cases):
                self._c_probes.inc()
                try:
                    got = tgt.submit_fn(case["feeds"], CANARY_TENANT)
                    mismatch = compare_pairs(case["expect"], got, rtol)
                except Exception as e:
                    mismatch = f"probe error: {repr(e)[:120]}"
                if mismatch is not None:
                    fail = f"case {i}: {mismatch}"[:_MAX_FAIL_DETAIL]
                    break
            results[tgt.name] = fail is None
            self._fold(tgt, fail)
        with self._lock:
            self._busy_s += time.monotonic() - t0
            self._cycles += 1
            self._g_failing.set(sum(
                1 for s in self._streaks.values()
                if s["fail_streak"] >= fail_streak_threshold()))
        return results

    def _fold(self, tgt: _Target, fail: Optional[str]) -> None:
        with self._lock:
            s = self._streaks.setdefault(tgt.name, {
                "pass_streak": 0, "fail_streak": 0, "probes": 0,
                "failures": 0, "last_fail": None})
            s["probes"] += 1
            if fail is None:
                s["pass_streak"] += 1
                s["fail_streak"] = 0
                return
            s["failures"] += 1
            s["fail_streak"] += 1
            s["pass_streak"] = 0
            s["last_fail"] = fail
            streak = s["fail_streak"]
        self._c_failures.inc()
        _stats.counter(f"canary.{tgt.model}.failures").inc()
        _flight.note("canary_fail", target=tgt.name, model=tgt.model,
                     detail=fail, streak=streak)

    # surfaces -----------------------------------------------------------
    def failing_targets(self) -> List[str]:
        thr = fail_streak_threshold()
        with self._lock:
            return sorted(t for t, s in self._streaks.items()
                          if s["fail_streak"] >= thr)

    def streaks(self) -> Dict[str, dict]:
        with self._lock:
            return {t: dict(s) for t, s in self._streaks.items()}

    def overhead_frac(self) -> float:
        with self._lock:
            wall = max(1e-9, time.monotonic() - self._armed_t0)
            return min(1.0, self._busy_s / wall)

    def snapshot(self) -> dict:
        with self._lock:
            busy = self._busy_s
            wall = max(1e-9, time.monotonic() - self._armed_t0)
            return {"targets": len(self._targets),
                    "golden_cases": self.goldens.n_cases(),
                    "cycles": self._cycles,
                    "fail_streak_threshold": fail_streak_threshold(),
                    "overhead_frac": round(min(1.0, busy / wall), 6),
                    "streaks": {t: dict(s)
                                for t, s in self._streaks.items()}}


# -- module singleton + lifecycle (slo.py discipline) ---------------------
_lock = threading.Lock()
_prober: Optional[CanaryProber] = None
_thread: Optional[threading.Thread] = None
_stop_evt = threading.Event()


def prober(create: bool = True) -> Optional[CanaryProber]:
    """The process-wide prober (lazily created when armed)."""
    global _prober
    with _lock:
        if _prober is None and create and enabled():
            golden_path = ""
            try:
                golden_path = str(_flags.get_flags("canary_golden_path")
                                  or "")
            except KeyError:  # pragma: no cover
                pass
            goldens = None
            if golden_path:
                try:
                    goldens = load_goldens(golden_path)
                except Exception as e:
                    # an unreadable golden set arms an empty prober —
                    # a bad path must never take the serving path down
                    _flight.note("canary_golden_load_error",
                                 path=golden_path, error=repr(e)[:200])
            _prober = CanaryProber(goldens)
        return _prober


def register_target(name: str, model: str,
                    submit_fn: Callable[[dict, str], list]) -> bool:
    """Register one replica submit path — a no-op unless armed."""
    if not enabled():
        return False
    p = prober()
    if p is None:
        return False
    p.register(name, model, submit_fn)
    return True


def unregister_target(name: str) -> None:
    p = prober(create=False)
    if p is not None:
        p.unregister(name)


def probe_once() -> dict:
    """One synchronous cycle (tests, bench) — ``{}`` unless armed."""
    p = prober(create=False) or (prober() if enabled() else None)
    return p.run_cycle() if p is not None else {}


def _run_loop() -> None:
    while not _stop_evt.wait(_interval_s()):
        p = prober(create=False)
        if p is None:
            continue
        try:
            p.run_cycle()
        except Exception:  # a broken probe never kills its thread
            pass


def maybe_start_from_flags() -> bool:
    """Idempotently start the prober thread when the flag is armed."""
    global _thread
    if not enabled():
        return False
    prober()                      # force creation + golden load
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        _stop_evt.clear()
        _thread = threading.Thread(target=_run_loop, daemon=True,
                                   name="canary-prober")
        _thread.start()
        return True


def stop() -> None:
    """Stop the prober thread (tests / shutdown)."""
    global _thread
    with _lock:
        t, _thread = _thread, None
    _stop_evt.set()
    if t is not None and t.is_alive():
        t.join(2.0)


def reset() -> None:
    """Drop prober + targets + streaks (tests / bench isolation)."""
    global _prober
    stop()
    with _lock:
        _prober = None
    _stop_evt.clear()


# -- health / riders / pages ----------------------------------------------
def health_dimension() -> dict:
    """The heartbeat rider: ``{}`` unless a prober is armed (so flags
    off leaves the payload byte-identical), else ``{"canary": "ok"}``
    or ``{"canary": "fail", "canary_targets": [...]}``."""
    try:
        p = prober(create=False)
        if p is None or not enabled():
            return {}
        failing = p.failing_targets()
        if failing:
            return {"canary": "fail", "canary_targets": failing}
        return {"canary": "ok"}
    except Exception:  # pragma: no cover - a broken probe never
        return {}      # stops a lease


def lease_rider(target: str) -> Optional[dict]:
    """Per-target streak summary for one replica's lease data — None
    when off / unknown target (byte-identity)."""
    p = prober(create=False)
    if p is None or not enabled():
        return None
    s = p.streaks().get(str(target))
    if s is None:
        return None
    return {"fail_streak": s["fail_streak"], "probes": s["probes"],
            "failures": s["failures"], "last_fail": s["last_fail"]}


def overhead_frac() -> float:
    p = prober(create=False)
    return p.overhead_frac() if p is not None else 0.0


def canaryz() -> dict:
    """The ``/canaryz`` payload (audit section appended by the page)."""
    if not enabled():
        return {"canary": "disabled (set FLAGS_canary_probe)"}
    p = prober(create=False)
    if p is None:
        return {"canary": {"targets": 0, "golden_cases": 0,
                           "cycles": 0, "streaks": {}}}
    return {"canary": p.snapshot()}


def canaryz_text(payload: Optional[dict] = None) -> str:
    """Human rendering of :func:`canaryz` (``/canaryz?text=1``)."""
    payload = payload if payload is not None else canaryz()
    can = payload.get("canary")
    if not isinstance(can, dict):
        return f"canary: {can}\n"
    streaks = can.get("streaks") or {}
    lines = [f"targets={can.get('targets')} "
             f"golden_cases={can.get('golden_cases')} "
             f"cycles={can.get('cycles')} "
             f"overhead_frac={can.get('overhead_frac')}"]
    hdr = ("target", "probes", "fail", "pass_strk", "fail_strk",
           "last_fail")
    lines.append("{:<26}{:>8}{:>6}{:>11}{:>11}  {}".format(*hdr))
    for t in sorted(streaks):
        s = streaks[t]
        lines.append("{:<26}{:>8}{:>6}{:>11}{:>11}  {}".format(
            t[:25], s.get("probes", 0), s.get("failures", 0),
            s.get("pass_streak", 0), s.get("fail_streak", 0),
            (s.get("last_fail") or "-")[:60]))
    if not streaks:
        lines.append("no targets probed yet")
    return "\n".join(lines) + "\n"


def export_state() -> Optional[dict]:
    """The STATS_PULL rider — None when off / no prober."""
    if not enabled():
        return None
    p = prober(create=False)
    if p is None:
        return None
    return p.snapshot()


def merge_states(per_worker: Dict[str, dict]) -> dict:
    """Fleet rollup: streak tables union (targets are replica-qualified
    so they never collide), totals sum, overhead takes the worst."""
    streaks: Dict[str, dict] = {}
    cases = cycles = 0
    overhead = 0.0
    failing = []
    for snap in per_worker.values():
        if not isinstance(snap, dict):
            continue
        cases = max(cases, int(snap.get("golden_cases") or 0))
        cycles += int(snap.get("cycles") or 0)
        overhead = max(overhead, float(snap.get("overhead_frac") or 0.0))
        thr = int(snap.get("fail_streak_threshold") or
                  fail_streak_threshold())
        for t, s in (snap.get("streaks") or {}).items():
            streaks[t] = dict(s)
            if int(s.get("fail_streak") or 0) >= thr:
                failing.append(t)
    return {"targets": len(streaks), "golden_cases": cases,
            "cycles": cycles, "overhead_frac": round(overhead, 6),
            "failing": sorted(failing), "streaks": streaks}
