"""Saturation anatomy: phase-level utilization and capacity modeling.

``FLAGS_phase_attribution`` (observability/phase.py) made latency
*attributable* — each request knows where its milliseconds went.  This
module answers the next question a fleet sizer needs: **how close to
saturation is each replica, and which phase binds first?**

One :class:`CapacityTracker` per pipeline (one per serving batcher, one
per decode engine) accounts per-component BUSY time — wall-clock spans
during which that component's single worker thread was occupied — into
a bounded sliding window:

- serving: ``assemble`` (feed concatenation on the scheduler thread),
  ``dispatch`` (predictor enqueue, same thread), ``device`` (host-side
  materialization drain on the completer thread), ``reply`` (slicing +
  future delivery, same thread);
- decode: ``prefill`` (bucketed prompt prefill) and ``decode`` (the
  fixed-width decode step), both on the engine thread.

Because each component's spans come from ONE serial thread, windowed
``busy/wall`` is a true utilization in [0, 1].  From there the
operational laws do the rest: with X = completions/s observed in the
window and S = busy-ms-per-completion of a component, U = X*S — so the
capacity ceiling of the pipeline is the throughput at which the BINDING
component (max U) reaches U = 1::

    S_b               = busy_ms(binding) / completions(window)
    predicted_max_qps = 1000 / S_b
    headroom_frac     = 1 - U(binding)

Per-bucket service-time fits (``device`` busy keyed by the padded batch
bucket, decode ``prefill`` by the prompt bucket) expose how the padding
ladder shifts S, and a saturation ``verdict`` names the binding phase
(``ok`` / ``approaching`` / ``saturated``).

Everything is gated by ``FLAGS_capacity_attribution``: off (default),
no tracker is created, no ``*.util.*`` gauge series exist, and the
STATS_PULL rider (:func:`export_state`) returns ``None`` so snapshots
stay byte-identical.  All accounting is host-side clock arithmetic on
stamps the hot paths already take — zero added device syncs.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import flags as _flags
from . import stats as _stats

__all__ = [
    "CapacityTracker",
    "enabled",
    "tracker",
    "get",
    "unregister",
    "trackers",
    "capacityz",
    "capacityz_text",
    "headroom",
    "export_state",
    "merge_states",
    "reset",
]

# default snapshot window (seconds) — long enough to smooth scheduler
# jitter, short enough that a load change shows within a scrape or two
DEFAULT_WINDOW_S = 30.0

# verdict thresholds on the binding component's utilization
APPROACHING_UTIL = 0.60
SATURATED_UTIL = 0.85

_SLOT_S = 2.0          # busy-window slot width
_SLOTS = 64            # retained slots (128 s — covers any sane window)


def enabled() -> bool:
    """Is capacity attribution armed (``FLAGS_capacity_attribution``)?"""
    try:
        return bool(_flags.get_flags("capacity_attribution"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


class _BusyWindow:
    """Bounded time-sliced accumulator of (busy_ms, work) samples.

    Slots are ``_SLOT_S`` wide; at most ``_SLOTS`` are retained, so
    memory is O(1) regardless of request rate.  Not thread-safe — the
    owning tracker serializes access under its lock.
    """

    __slots__ = ("_slots",)

    def __init__(self):
        self._slots: Dict[int, List[float]] = {}  # idx -> [busy_ms, work]

    def add(self, busy_ms: float, work: float, now: float) -> None:
        idx = int(now / _SLOT_S)
        slot = self._slots.get(idx)
        if slot is None:
            if len(self._slots) >= _SLOTS:
                for old in sorted(self._slots)[:len(self._slots) - _SLOTS + 1]:
                    del self._slots[old]
            self._slots[idx] = [busy_ms, work]
        else:
            slot[0] += busy_ms
            slot[1] += work

    def window(self, now: float, window_s: float) -> Tuple[float, float]:
        """(busy_ms, work) summed over slots younger than ``window_s``."""
        lo = int((now - window_s) / _SLOT_S)
        busy = work = 0.0
        for idx, (b, w) in self._slots.items():
            if idx >= lo:
                busy += b
                work += w
        return busy, work


class CapacityTracker:
    """Windowed busy-time accounting for one pipeline's components."""

    def __init__(self, name: str, components: Sequence[str]):
        self.name = name
        self.components = tuple(components)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._busy = {c: _BusyWindow() for c in self.components}
        self._done = _BusyWindow()          # completions (work = count)
        # lifetime per-(component, bucket) service fits:
        # (count, busy_ms, rows) — bucketed components only
        self._fits: Dict[Tuple[str, object], List[float]] = {}
        sc = _stats.scope(name)
        self._gauges = {c: sc.gauge(f"util.{c}") for c in self.components}
        self._headroom_g = sc.gauge("util.headroom_frac")

    # -- accounting (hot path; one dict update under a short lock) -------
    def note(self, component: str, busy_ms: float,
             bucket=None, work: float = 0.0) -> None:
        """Account ``busy_ms`` of busy wall to ``component`` (one span
        of its serial worker thread).  ``bucket`` keys a lifetime
        service-time fit; ``work`` is the rows/requests the span
        covered (for the per-bucket rows/s ceiling)."""
        if busy_ms < 0.0:
            busy_ms = 0.0
        now = time.monotonic()
        with self._lock:
            win = self._busy.get(component)
            if win is None:       # unknown component: file, don't drop
                win = self._busy[component] = _BusyWindow()
            win.add(busy_ms, work, now)
            if bucket is not None:
                fit = self._fits.get((component, bucket))
                if fit is None:
                    self._fits[(component, bucket)] = [1.0, busy_ms,
                                                       float(work)]
                else:
                    fit[0] += 1.0
                    fit[1] += busy_ms
                    fit[2] += float(work)

    def note_done(self, n: int = 1) -> None:
        """Account ``n`` pipeline completions (the X of U = X*S)."""
        now = time.monotonic()
        with self._lock:
            self._done.add(0.0, float(n), now)

    # -- modeling --------------------------------------------------------
    def snapshot(self, window_s: float = DEFAULT_WINDOW_S) -> dict:
        """Utilization + operational-law capacity estimate over the
        trailing ``window_s`` seconds (bounded by the tracker's age)."""
        now = time.monotonic()
        span_s = max(1e-6, min(window_s, now - self._t0))
        with self._lock:
            per = {c: w.window(now, window_s)
                   for c, w in self._busy.items()}
            _, done = self._done.window(now, window_s)
            fits = {k: list(v) for k, v in self._fits.items()}
        comps = {}
        binding = None
        for c, (busy_ms, work) in per.items():
            util = min(1.0, busy_ms / (span_s * 1000.0))
            comps[c] = {"busy_ms": round(busy_ms, 3),
                        "util": round(util, 4)}
            if binding is None or (util, busy_ms) > (
                    comps[binding]["util"], comps[binding]["busy_ms"]):
                binding = c
        out = {"name": self.name,
               "window_s": round(span_s, 3),
               "components": comps,
               "completed": int(done),
               "qps": round(done / span_s, 3)}
        if binding is not None:
            b = comps[binding]
            out["binding_phase"] = binding
            out["utilization"] = b["util"]
            out["headroom_frac"] = round(1.0 - b["util"], 4)
            if done > 0 and b["busy_ms"] > 0:
                s_ms = b["busy_ms"] / done
                out["service_ms"] = round(s_ms, 3)
                out["predicted_max_qps"] = round(1000.0 / s_ms, 2)
            out["verdict"] = (
                "saturated" if b["util"] >= SATURATED_UTIL else
                "approaching" if b["util"] >= APPROACHING_UTIL else "ok")
        for c, g in self._gauges.items():
            if c in comps:
                g.set(comps[c]["util"])
        if "headroom_frac" in out:
            self._headroom_g.set(out["headroom_frac"])
        bucket_fits: Dict[str, dict] = {}
        for (comp, bucket), (count, busy_ms, rows) in fits.items():
            ent = {"count": int(count),
                   "mean_ms": round(busy_ms / count, 3)}
            if rows > 0 and busy_ms > 0:
                ent["rows_per_s_cap"] = round(rows / (busy_ms / 1000.0), 1)
            bucket_fits.setdefault(comp, {})[str(bucket)] = ent
        if bucket_fits:
            out["bucket_fits"] = bucket_fits
        return out

    def headroom(self) -> Optional[dict]:
        """The compact lease-data rider: headroom + binding phase +
        predicted ceiling, or None before any completion."""
        snap = self.snapshot()
        if "headroom_frac" not in snap or not snap.get("completed"):
            return None
        out = {"headroom_frac": snap["headroom_frac"],
               "binding_phase": snap["binding_phase"]}
        if "predicted_max_qps" in snap:
            out["predicted_max_qps"] = snap["predicted_max_qps"]
        return out


# -- module registry (one tracker per live pipeline) ----------------------
_lock = threading.Lock()
_trackers: Dict[str, CapacityTracker] = {}


def tracker(name: str, components: Sequence[str]) -> CapacityTracker:
    """Get-or-create the named tracker.  Callers gate on
    :func:`enabled` — creating one instantiates its gauge series."""
    with _lock:
        t = _trackers.get(name)
        if t is None:
            t = _trackers[name] = CapacityTracker(name, components)
        return t


def get(name: str) -> Optional[CapacityTracker]:
    with _lock:
        return _trackers.get(name)


def unregister(name: str) -> None:
    with _lock:
        _trackers.pop(name, None)


def trackers() -> Dict[str, CapacityTracker]:
    with _lock:
        return dict(_trackers)


def reset() -> None:
    """Drop all trackers (tests / bench config isolation)."""
    with _lock:
        _trackers.clear()


# -- pages / riders -------------------------------------------------------
def capacityz(window_s: float = DEFAULT_WINDOW_S) -> dict:
    """The ``/capacityz`` payload: one snapshot per live tracker."""
    if not enabled():
        return {"capacity": "disabled (set FLAGS_capacity_attribution)"}
    return {"window_s": window_s,
            "pipelines": {n: t.snapshot(window_s)
                          for n, t in trackers().items()}}


def capacityz_text(payload: Optional[dict] = None) -> str:
    """Human rendering of :func:`capacityz` (``/capacityz?text=1``)."""
    payload = payload if payload is not None else capacityz()
    pipes = payload.get("pipelines")
    if not isinstance(pipes, dict) or not pipes:
        return "capacity: no live pipelines (flag off or nothing served)\n"
    lines = []
    for name in sorted(pipes):
        s = pipes[name]
        lines.append(f"== {name} ==")
        lines.append(
            "  verdict={} binding={} util={:.1%} headroom={:.1%} "
            "qps={} predicted_max_qps={}".format(
                s.get("verdict", "-"), s.get("binding_phase", "-"),
                s.get("utilization", 0.0), s.get("headroom_frac", 1.0),
                s.get("qps", 0.0), s.get("predicted_max_qps", "-")))
        for c in sorted(s.get("components", {})):
            e = s["components"][c]
            lines.append(f"  {c:<10} busy_ms={e['busy_ms']:<10} "
                         f"util={e['util']:.1%}")
        for comp, buckets in sorted(s.get("bucket_fits", {}).items()):
            for b in sorted(buckets, key=lambda x: (len(x), x)):
                f = buckets[b]
                lines.append(
                    f"  fit {comp}[{b}] n={f['count']} "
                    f"mean_ms={f['mean_ms']}"
                    + (f" rows_per_s_cap={f['rows_per_s_cap']}"
                       if "rows_per_s_cap" in f else ""))
    return "\n".join(lines) + "\n"


def headroom() -> Dict[str, dict]:
    """{tracker name: compact headroom rider} for every pipeline that
    has completed work — what /healthz and the lease data carry."""
    out = {}
    for name, t in trackers().items():
        h = t.headroom()
        if h is not None:
            out[name] = h
    return out


def export_state() -> Optional[dict]:
    """The STATS_PULL rider: per-pipeline snapshots, or None when the
    flag is off / nothing tracked (payload byte-identity)."""
    if not enabled():
        return None
    t = trackers()
    if not t:
        return None
    return {n: tr.snapshot() for n, tr in t.items()}


def merge_states(per_worker: Dict[str, dict]) -> dict:
    """Fleet rollup of per-worker :func:`export_state` payloads.

    Pipelines are per-replica (no shared queue), so fleet capacity SUMS
    predicted ceilings per pipeline name while headroom takes the MIN
    (the tightest replica binds a balanced fleet first).
    """
    fleet: Dict[str, dict] = {}
    for worker, pipes in per_worker.items():
        if not isinstance(pipes, dict):
            continue
        for name, snap in pipes.items():
            if not isinstance(snap, dict):
                continue
            agg = fleet.setdefault(name, {
                "replicas": 0, "qps": 0.0, "predicted_max_qps": 0.0,
                "headroom_frac": None, "binding_phase": None,
                "min_headroom_worker": None})
            agg["replicas"] += 1
            agg["qps"] = round(agg["qps"] + float(snap.get("qps") or 0.0), 3)
            if isinstance(snap.get("predicted_max_qps"), (int, float)):
                agg["predicted_max_qps"] = round(
                    agg["predicted_max_qps"] + snap["predicted_max_qps"], 2)
            hf = snap.get("headroom_frac")
            if isinstance(hf, (int, float)) and (
                    agg["headroom_frac"] is None
                    or hf < agg["headroom_frac"]):
                agg["headroom_frac"] = hf
                agg["binding_phase"] = snap.get("binding_phase")
                agg["min_headroom_worker"] = worker
    return fleet
