"""Heartbeat-based worker health registry: HEALTHY → SUSPECT → DEAD.

Borg/Borgmon-style liveness for the fleet: every worker already
refreshes a TTL lease against the discovery registry
(``distributed/registry.py`` — the etcd keepalive analogue), so the
heartbeat piggybacks a small health payload (role, step counter, last
error) on that existing REG_SET instead of adding a second RPC.  The
registry side files each refresh into a :class:`HealthTable`; state is
computed *lazily at read time* from the age of the last heartbeat
measured in missed lease terms:

- ``age <= suspect_misses * ttl``  → ``HEALTHY``
- ``age <= dead_misses * ttl``     → ``SUSPECT`` (lease lapsed; the
  worker may be GC-pausing, swapping, or mid-restart)
- beyond                            → ``DEAD`` (consumers may act:
  ``TaskMaster`` requeues its leases immediately instead of waiting
  out the task-lease timeout)

Thresholds come from ``FLAGS_health_suspect_misses`` /
``FLAGS_health_dead_misses`` (overridable per table).  ``snapshot()``
exports fleet-level ``health.workers_{healthy,suspect,dead}`` gauges
into the default stats registry so ``/metrics`` carries liveness.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from . import stats as _stats

HEALTHY = "HEALTHY"
SUSPECT = "SUSPECT"
DEAD = "DEAD"


def _flag(name: str, default: float) -> float:
    from ..core import flags
    try:
        return float(flags.get_flags(name))
    except KeyError:  # pragma: no cover - flags always defined
        return default


class _WorkerEntry:
    __slots__ = ("name", "role", "step", "last_error", "trainer_id",
                 "ttl", "last_seen", "heartbeats", "standby", "slo",
                 "slo_rules", "canary", "canary_targets", "memory",
                 "memory_pools")

    def __init__(self, name: str):
        self.name = name
        self.role = ""
        self.step = None
        self.last_error = None
        self.trainer_id = None
        self.ttl = 0.0
        self.last_seen = 0.0
        self.heartbeats = 0
        # HA: candidate id while this worker is a STANDBY replica for
        # its logical key (None = primary / not replicated); cleared on
        # promotion, so the fleet view shows who is warm-sparing whom
        self.standby = None
        # SLO watchdog dimension (observability/slo.py): "ok"/"breach"
        # as reported by the worker's own in-process watchdog, riding
        # the same heartbeat payload — liveness says the worker is
        # alive, this says whether it is USEFUL.  None = worker runs no
        # watchdog (the pre-slo wire)
        self.slo = None
        self.slo_rules = None
        # correctness dimension (observability/canary.py): "ok"/"fail"
        # as reported by the worker's own golden-canary prober, plus
        # the replica-qualified targets at/over the fail-streak
        # threshold.  None = worker runs no prober (the pre-canary wire)
        self.canary = None
        self.canary_targets = None
        # memory dimension (observability/memory.py): "ok"/"leak" as
        # reported by the worker's own leak sentinel (refcount audits
        # over its registered pools), plus the leaking pool names.
        # None = worker runs no memory attribution (the pre-memory wire)
        self.memory = None
        self.memory_pools = None


class HealthTable:
    """Last-heartbeat table with miss-threshold state transitions.

    ``observe()`` is called by the registry service on every REG_SET
    that carries a health payload; readers (``snapshot()`` /
    ``dead_trainers()``) never block writers for longer than a dict
    copy.  Thresholds are in units of the *worker's own* lease TTL, so
    a 2 s-lease trainer and a 10 s-lease pserver age out on their own
    clocks.
    """

    _FORGET_AUTO = "auto"

    def __init__(self, suspect_misses: Optional[float] = None,
                 dead_misses: Optional[float] = None,
                 forget_misses=_FORGET_AUTO):
        self.suspect_misses = (suspect_misses if suspect_misses is not None
                               else _flag("health_suspect_misses", 1.0))
        self.dead_misses = (dead_misses if dead_misses is not None
                            else _flag("health_dead_misses", 3.0))
        if self.dead_misses <= self.suspect_misses:
            raise ValueError(
                "dead_misses must exceed suspect_misses (check "
                "FLAGS_health_dead_misses vs FLAGS_health_suspect_misses)")
        # retention bound: entries DEAD for this many lease terms are
        # dropped at read time, so a long-lived registry doesn't report
        # (and remember) every worker of every finished job forever.
        # "auto" scales with dead_misses so a flags-only change (e.g.
        # FLAGS_health_dead_misses=150) can never invert the ordering
        # and crash the registry at construction.  None = keep forever.
        # Workers that exit CLEANLY should send a goodbye instead
        # (registry.deregister / Heartbeat.stop(bye=True)).
        if forget_misses == self._FORGET_AUTO:
            forget_misses = max(120.0, 10.0 * self.dead_misses)
        if forget_misses is not None and forget_misses <= self.dead_misses:
            raise ValueError("forget_misses must exceed dead_misses")
        self.forget_misses = forget_misses
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerEntry] = {}

    def observe(self, name: str, ttl: float, role: str = "",
                step: Optional[int] = None,
                last_error: Optional[str] = None,
                trainer_id: Optional[int] = None,
                standby=None, slo=None, slo_rules=None,
                canary=None, canary_targets=None,
                memory=None, memory_pools=None) -> None:
        """File one heartbeat (idempotent re-registration included)."""
        with self._lock:
            e = self._workers.get(name)
            if e is None:
                e = self._workers[name] = _WorkerEntry(name)
            e.ttl = float(ttl)
            if role:
                e.role = role
            if step is not None:
                e.step = int(step)
            e.last_error = last_error
            if trainer_id is not None:
                e.trainer_id = int(trainer_id)
            # always assigned (not only when present): a promoted
            # backup's next heartbeat clears its standby marker, and a
            # cleared SLO breach clears the slo dimension
            e.standby = standby
            e.slo = slo
            e.slo_rules = slo_rules
            e.canary = canary
            e.canary_targets = canary_targets
            e.memory = memory
            e.memory_pools = memory_pools
            e.last_seen = time.monotonic()
            e.heartbeats += 1

    def forget(self, name: str) -> None:
        with self._lock:
            self._workers.pop(name, None)

    def _state(self, e: _WorkerEntry, now: float) -> str:
        age = now - e.last_seen
        if e.ttl <= 0 or age <= self.suspect_misses * e.ttl:
            return HEALTHY
        if age <= self.dead_misses * e.ttl:
            return SUSPECT
        return DEAD

    def _reap_forgotten(self, now: float) -> None:
        """Drop entries past the retention bound (callers hold no lock)."""
        if self.forget_misses is None:
            return
        with self._lock:
            gone = [n for n, e in self._workers.items()
                    if e.ttl > 0 and now - e.last_seen
                    > self.forget_misses * e.ttl]
            for n in gone:
                del self._workers[n]

    def status(self, name: str) -> Optional[str]:
        self._reap_forgotten(time.monotonic())
        with self._lock:
            e = self._workers.get(name)
            return self._state(e, time.monotonic()) if e else None

    def snapshot(self) -> Dict[str, dict]:
        """{worker: {state, role, step, age_s, ...}}; refreshes the
        fleet-level ``health.workers_*`` gauges as a side effect."""
        now = time.monotonic()
        self._reap_forgotten(now)
        with self._lock:
            entries = list(self._workers.values())
        out, tallies = {}, {HEALTHY: 0, SUSPECT: 0, DEAD: 0}
        for e in entries:
            state = self._state(e, now)
            tallies[state] += 1
            ent = {
                "state": state,
                "role": e.role,
                "step": e.step,
                "last_error": e.last_error,
                "trainer_id": e.trainer_id,
                "ttl": e.ttl,
                "age_s": round(now - e.last_seen, 3),
                "heartbeats": e.heartbeats,
                "standby": e.standby,
            }
            if e.slo is not None:
                ent["slo"] = e.slo
                if e.slo_rules:
                    ent["slo_rules"] = e.slo_rules
            if e.canary is not None:
                ent["canary"] = e.canary
                if e.canary_targets:
                    ent["canary_targets"] = list(e.canary_targets)
            if e.memory is not None:
                ent["memory"] = e.memory
                if e.memory_pools:
                    ent["memory_pools"] = list(e.memory_pools)
            out[e.name] = ent
        sc = _stats.scope("health")
        sc.gauge("workers_healthy").set(tallies[HEALTHY])
        sc.gauge("workers_suspect").set(tallies[SUSPECT])
        sc.gauge("workers_dead").set(tallies[DEAD])
        sc.gauge("workers_slo_breach").set(
            sum(1 for e in entries if e.slo == "breach"))
        return out

    def dead_trainers(self) -> set:
        """Trainer ids currently DEAD (the master's requeue predicate).

        Only ``role == "TRAINER"`` entries count: non-trainer workers
        (pserver Heartbeats) carry the default RPC-client trainer_id of
        0, and a dead pserver must never read as "trainer 0 is dead"."""
        now = time.monotonic()
        self._reap_forgotten(now)
        with self._lock:
            entries = list(self._workers.values())
        return {e.trainer_id for e in entries
                if e.trainer_id is not None and e.role == "TRAINER"
                and self._state(e, now) == DEAD}
