"""In-process debug HTTP server: /metrics, /healthz, /statusz, /stepz.

The Borgmon/Prometheus pull model for the telemetry core: every process
(trainer, pserver, master) can expose its :mod:`stats` registry and
:mod:`step_stats` ring on a loopback HTTP port so operators and
scrapers reach telemetry *without* attaching to the process.  Strictly
opt-in: with ``FLAGS_debug_server_port`` unset (0, the default) no
socket is opened and no thread is started — ``maybe_start_from_flags``
is a flag read and nothing else.

Endpoints (all GET):

- ``/metrics``  Prometheus text from ``stats.to_prometheus_text()``;
  when a fleet aggregator is attached (``attach_aggregator``), its
  ``fleet:*``-prefixed cross-worker series are appended.
- ``/healthz``  JSON liveness: process uptime, steps recorded, age of
  the last ``Executor.run`` StepStats record (a serving process whose
  last-step age keeps growing is stuck even though the port answers).
- ``/statusz``  JSON process card: role, pid, flags, and every
  registered status provider (executor cache occupancy, ``TaskMaster``
  queue depths, ...).
- ``/stepz``    JSON ``observability.export()`` (metrics snapshot +
  step-stats summary/tail).
- ``/memz``     live device-memory snapshot (PJRT ``memory_stats()``
  per device + host RSS); ``/profilez`` the per-executable XLA
  cost/memory attribution records with roofline positions
  (:mod:`perf`).  Both JSON by default, ``?text=1`` human text.
- ``/servingz`` the model-serving plane (``paddle_tpu/serving``): per
  in-process ModelServer, the version router plus per-model QPS,
  queue-depth, batch-occupancy, shed and latency-percentile gauges.
- ``/fleetz``  the fleet-supervisor plane (``distributed/supervisor``):
  per-worker lifecycle state machine + restart budgets, with query
  params as the admin surface (resize/drain/resume/cut —
  ``tools/fleet.py`` is the CLI).
- ``/varz``    the metric history rings (:mod:`history`,
  ``FLAGS_metrics_history_interval_s``): ``?window=<s>`` bounds the
  returned series, ``?grep=<substr>`` filters metric names — "what
  changed in the last 10 minutes" without an external scraper.
- ``/sloz``    the SLO watchdog (:mod:`slo`, ``FLAGS_slo_rules``):
  rule table with live values, thresholds, breach state.

Built on stdlib ``http.server`` (ThreadingHTTPServer, daemon threads):
no new dependencies, safe to leave running in tests and serving
processes.  One process-wide singleton; ``start()``/``stop()`` are
idempotent and test-friendly.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from . import history as _history
from . import slo as _slo
from . import stats as _stats
from . import step_stats as _step_stats
from . import trace as _trace

_START_TIME = time.time()

# liveness activity marks beyond the training StepStats ring: the
# serving batcher and decode engine note each dispatch here so a pure
# inference process (which never appends StepStats) still reports a
# bounded last-activity age on /healthz instead of an ever-growing
# last-step age (it looked permanently stuck to any prober)
_activity: Dict[str, float] = {}


def note_activity(plane: str) -> None:
    """Record a liveness mark for ``plane`` ('serving', 'decode', ...).
    One clock read + dict store — safe on hot paths, no flag needed."""
    _activity[plane] = time.time()

_lock = threading.Lock()
_server: Optional["DebugServer"] = None
_providers: Dict[str, Callable[[], object]] = {}
_role: Optional[str] = None
_aggregator = None  # duck-typed: anything with .to_prometheus_text()
# /servingz sources: one per in-process ModelServer (keyed by its
# endpoint), each fn() returning that server's router + model gauges
_servingz: Dict[str, Callable[[], object]] = {}
# /decodez sources: one per in-process DecodeEngine (keyed by model
# name), each fn() returning that engine's slots/cache/queue gauges
_decodez: Dict[str, Callable[[], object]] = {}
# /fleetz sources: one per in-process fleet Supervisor (keyed by fleet
# name): (status_fn, admin_fn) — status_fn() returns the per-worker
# state-machine card, admin_fn(cmd_dict) applies resize/drain/resume/
# cut mutations (the tools/fleet.py surface)
_fleetz: Dict[str, tuple] = {}


def register_provider(name: str, fn: Callable[[], object]) -> None:
    """Add a /statusz section: ``fn()`` returns a JSON-able value.
    Re-registering a name replaces it (latest owner wins)."""
    with _lock:
        _providers[name] = fn


def unregister_provider(name: str) -> None:
    with _lock:
        _providers.pop(name, None)


def register_servingz(name: str, fn: Callable[[], object]) -> None:
    """Add a /servingz source (a ModelServer's ``manager.servingz``).
    Re-registering a name replaces it (latest owner wins)."""
    with _lock:
        _servingz[name] = fn


def unregister_servingz(name: str) -> None:
    with _lock:
        _servingz.pop(name, None)


def _servingz_payload() -> dict:
    with _lock:
        sources = dict(_servingz)
    if not sources:
        return {"serving": "no model server registered in this process"}
    out = {}
    for name, fn in sorted(sources.items()):
        try:
            out[name] = fn()
        except Exception as e:  # one broken server must not 500 the page
            out[name] = {"error": repr(e)[:200]}
    return out


def register_decodez(name: str, fn: Callable[[], object]) -> None:
    """Add a /decodez source (a DecodeEngine's ``decodez``).
    Re-registering a name replaces it (latest owner wins)."""
    with _lock:
        _decodez[name] = fn


def unregister_decodez(name: str) -> None:
    with _lock:
        _decodez.pop(name, None)


def _decodez_payload() -> dict:
    with _lock:
        sources = dict(_decodez)
    if not sources:
        return {"decode": "no decode engine registered in this process"}
    out = {}
    for name, fn in sorted(sources.items()):
        try:
            out[name] = fn()
        except Exception as e:  # one broken engine must not 500 the page
            out[name] = {"error": repr(e)[:200]}
    return out


def register_fleetz(name: str, status_fn: Callable[[], object],
                    admin_fn: Optional[Callable[[dict], object]] = None
                    ) -> None:
    """Add a /fleetz source (a Supervisor's ``status``/``_admin``).
    Re-registering a name replaces it (latest owner wins)."""
    with _lock:
        _fleetz[name] = (status_fn, admin_fn)


def unregister_fleetz(name: str) -> None:
    with _lock:
        _fleetz.pop(name, None)


def _fleetz_payload(query: str = "") -> tuple:
    """(status_code, payload) for /fleetz.  A bare GET lists every
    fleet's worker state machine; query params mutate — ``?resize=
    role:count``, ``?drain=worker``, ``?resume=[role]``, ``?cut=1
    [&wait=s]`` (``&fleet=name`` picks one when several run)."""
    from urllib.parse import parse_qs
    # keep_blank_values: the documented bare "?resume=" form must act,
    # not silently fall through to the status listing
    q = {k: v[0] for k, v in parse_qs(query,
                                      keep_blank_values=True).items()}
    with _lock:
        sources = dict(_fleetz)
    if not sources:
        return 200, {"fleet": "no supervisor registered in this process"}
    target = q.pop("fleet", None)
    cmd = {k: v for k, v in q.items()
           if k in ("resize", "drain", "resume", "cut", "wait")}
    # "wait" only modifies "cut" — alone it must not select the admin
    # path (a bare ?wait=30 falls through to the status listing)
    if any(k in cmd for k in ("resize", "drain", "resume", "cut")):
        if target is None and len(sources) > 1:
            return 400, {"error": "several fleets registered; pass "
                                  "&fleet=<name>",
                         "fleets": sorted(sources)}
        name = target if target is not None else next(iter(sources))
        ent = sources.get(name)
        if ent is None:
            return 404, {"error": f"no fleet {name!r}",
                         "fleets": sorted(sources)}
        _, admin_fn = ent
        if admin_fn is None:
            return 400, {"error": f"fleet {name!r} is read-only"}
        try:
            return 200, {name: admin_fn(cmd)}
        except Exception as e:
            return 400, {"error": repr(e)[:400]}
    out = {}
    for name, (status_fn, _) in sorted(sources.items()):
        if target is not None and name != target:
            continue
        try:
            out[name] = status_fn()
        except Exception as e:  # one broken fleet must not 500 the page
            out[name] = {"error": repr(e)[:200]}
    return 200, out


def set_role(role: Optional[str]) -> None:
    """Override the /statusz role (default: PADDLE_TRAINING_ROLE env)."""
    global _role
    _role = role


def attach_aggregator(agg) -> None:
    """Serve a FleetAggregator's merged series on /metrics (trainer 0 /
    the master call this; ``None`` detaches)."""
    global _aggregator
    _aggregator = agg


def _current_role() -> str:
    if _role:
        return _role
    return os.environ.get("PADDLE_TRAINING_ROLE", "STANDALONE")


def _healthz() -> dict:
    rec = _step_stats.recorder()
    last = rec.last_n(1)
    now = time.time()
    # liveness = the freshest of ANY dispatch plane: the training
    # StepStats ring, plus the serving/decode activity marks.  A pure
    # inference server's liveness must not age out on the training ring
    ages = {}
    if last:
        ages["train"] = round(now - last[0].ts, 3)
    # copy first: hot-path threads insert NEW plane keys concurrently,
    # and iterating the live dict could 500 a healthy process's probe
    for plane, ts in sorted(dict(_activity).items()):
        ages[plane] = round(now - ts, 3)
    out = {
        "status": "ok",
        "role": _current_role(),
        "uptime_s": round(now - _START_TIME, 3),
        "runtime_stats": _trace.flags_on(),
        "steps_recorded": rec.total_recorded,
        "last_step_age_s": (min(ages.values()) if ages else None),
        "activity_age_s": ages,
    }
    # load next to liveness (FLAGS_capacity_attribution): a drained-
    # but-saturated replica must read differently from an idle one.
    # Flag off ⇒ no key, payload identical to the pre-capacity build
    from . import capacity as _capacity
    if _capacity.enabled():
        hr = _capacity.headroom()
        if hr:
            out["headroom"] = hr
    return out


def _statusz() -> dict:
    from ..core import flags as _flags
    with _lock:
        providers = dict(_providers)
    out = {
        "role": _current_role(),
        "pid": os.getpid(),
        "argv": sys.argv,
        "uptime_s": round(time.time() - _START_TIME, 3),
        "constant_labels": _stats.default_registry().constant_labels(),
        "flags": _flags.all_flags(),
    }
    for name, fn in sorted(providers.items()):
        try:
            out[name] = fn()
        except Exception as e:  # one broken provider must not 500 the page
            out[name] = {"error": repr(e)[:200]}
    return out


class _Handler(BaseHTTPRequestHandler):
    # stderr-per-request logging would swamp training logs; count instead
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _reply(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype + "; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _tracez(self, query: str) -> str:
        from urllib.parse import parse_qs
        q = parse_qs(query)

        def _flag(name: str) -> bool:
            return q.get(name, ["0"])[0] not in ("0", "", "false")

        if _flag("recent"):
            # the flight-recorder view, served LIVE (what a dump file
            # would contain right now): recent + in-flight spans, log
            # events, step-stats tail
            from . import flight as _flight
            return json.dumps(_flight.snapshot("tracez"), indent=2,
                              default=repr)
        snap = _trace.local_trace_snapshot()
        if _flag("raw"):
            # the TRACE_PULL snapshot form — what tools/stitch_trace.py
            # merges across workers
            return json.dumps(snap, indent=2)
        # default: this process's ring as a directly-loadable
        # Chrome/Perfetto trace (real pid + process/thread names)
        label = f"{snap['role'].lower()}-{snap['pid']}"
        return json.dumps(_trace.stitch_chrome_trace({label: snap}))

    def do_GET(self):  # noqa: N802 (http.server casing)
        path, _, query = self.path.partition("?")
        path = path.rstrip("/") or "/"
        sc = _stats.scope("debug_server")
        try:
            if path == "/metrics":
                text = _stats.to_prometheus_text()
                agg = _aggregator
                if agg is not None:
                    try:
                        text += agg.to_prometheus_text()
                    except Exception as e:
                        text += f"# fleet aggregation failed: {e!r}\n"
                self._reply(200, text, "text/plain; version=0.0.4")
            elif path == "/healthz":
                self._reply(200, json.dumps(_healthz(), indent=2),
                            "application/json")
            elif path == "/statusz":
                self._reply(200, json.dumps(_statusz(), indent=2,
                                            default=repr),
                            "application/json")
            elif path == "/stepz":
                from . import export
                self._reply(200, json.dumps(export(), indent=2),
                            "application/json")
            elif path == "/tracez":
                self._reply(200, self._tracez(query), "application/json")
            elif path in ("/memz", "/profilez"):
                # the perf/numerics plane (observability/perf.py): live
                # device-memory stats and per-executable cost/memory
                # attribution + rooflines.  JSON by default, ?text=1 for
                # the human rendering (tools/dump_metrics.py --memz /
                # --profilez is the operator CLI)
                from urllib.parse import parse_qs
                from . import perf as _perf
                q = parse_qs(query)
                text = q.get("text", ["0"])[0] not in ("0", "", "false")
                if path == "/memz":
                    body = (_perf.memz_text() if text
                            else json.dumps(_perf.memz(), indent=2))
                else:
                    body = (_perf.profilez_text() if text
                            else json.dumps(_perf.profilez(), indent=2))
                self._reply(200, body,
                            "text/plain" if text else "application/json")
            elif path == "/servingz":
                # the serving-plane debug page: router state + per-model
                # QPS / queue-depth / batch-occupancy / latency gauges
                # for every ModelServer in this process
                self._reply(200, json.dumps(_servingz_payload(), indent=2,
                                            default=repr),
                            "application/json")
            elif path == "/decodez":
                # the decode-plane debug page: per-engine slot table,
                # paged-cache occupancy, queue depth, tokens/s gauges
                self._reply(200, json.dumps(_decodez_payload(), indent=2,
                                            default=repr),
                            "application/json")
            elif path == "/fleetz":
                # the fleet-supervisor debug page: per-worker lifecycle
                # state machine (STARTING→LIVE→DRAINING→DEAD→REPLACING)
                # + restart budgets; query params resize/drain/resume/
                # cut a running fleet (tools/fleet.py is the CLI)
                code, payload = _fleetz_payload(query)
                self._reply(code, json.dumps(payload, indent=2,
                                             default=repr),
                            "application/json")
            elif path == "/varz":
                # the metric-history plane (observability/history.py):
                # bounded downsampled time series per counter/gauge,
                # ?window=<s> bounds the ages, ?grep filters names
                from urllib.parse import parse_qs
                q = parse_qs(query)
                window = q.get("window", [None])[0]
                window_s = float(window) if window else None
                pattern = q.get("grep", [""])[0]
                self._reply(200, json.dumps(
                    _history.varz(window_s, pattern), indent=2),
                    "application/json")
            elif path == "/sloz":
                # the SLO watchdog (observability/slo.py): rule table
                # with live values / thresholds / breach state
                self._reply(200, json.dumps(_slo.sloz(), indent=2,
                                            default=repr),
                            "application/json")
            elif path in ("/capacityz", "/tenantz"):
                # the saturation-anatomy plane (observability/
                # capacity.py + tenant.py): phase-level utilization,
                # operational-law headroom and per-tenant usage
                # metering.  JSON by default, ?text=1 for the human
                # rendering (tools/dump_metrics.py --capacityz /
                # --tenantz is the operator CLI)
                from urllib.parse import parse_qs
                from . import capacity as _capacity
                from . import tenant as _tenant
                q = parse_qs(query)
                text = q.get("text", ["0"])[0] not in ("0", "", "false")
                if path == "/capacityz":
                    body = (_capacity.capacityz_text() if text
                            else json.dumps(_capacity.capacityz(),
                                            indent=2))
                else:
                    body = (_tenant.tenantz_text() if text
                            else json.dumps(_tenant.tenantz(), indent=2))
                self._reply(200, body,
                            "text/plain" if text else "application/json")
            elif path == "/allocz":
                # the memory-anatomy plane (observability/memory.py):
                # per-pool HBM/host/disk attribution ledger, per-device
                # PJRT reconciliation, allocation event ring.  JSON by
                # default, ?text=1 for the human rendering
                # (tools/dump_metrics.py --allocz is the operator CLI)
                from urllib.parse import parse_qs
                from . import memory as _memory
                q = parse_qs(query)
                text = q.get("text", ["0"])[0] not in ("0", "", "false")
                body = (_memory.allocz_text() if text
                        else json.dumps(_memory.allocz(), indent=2,
                                        default=repr))
                self._reply(200, body,
                            "text/plain" if text else "application/json")
            elif path == "/quantz":
                # the low-precision-serving plane (kernels/quant.py):
                # per-layer calibration records (scales, clip
                # fractions), quantized-matmul launch/fallback
                # counters, quantized KV cache pools.  JSON by
                # default, ?text=1 for the human rendering
                # (tools/dump_metrics.py --quantz is the operator CLI)
                from urllib.parse import parse_qs
                from ..kernels import quant as _quant
                q = parse_qs(query)
                text = q.get("text", ["0"])[0] not in ("0", "", "false")
                body = (_quant.quantz_text() if text
                        else json.dumps(_quant.quantz(), indent=2,
                                        default=repr))
                self._reply(200, body,
                            "text/plain" if text else "application/json")
            elif path == "/canaryz":
                # the correctness-anatomy plane (observability/
                # canary.py + audit.py): golden-probe streak table plus
                # the divergence audit ring.  JSON by default, ?text=1
                # for the human rendering (tools/dump_metrics.py
                # --canaryz is the operator CLI)
                from urllib.parse import parse_qs
                from . import audit as _audit
                from . import canary as _canary
                q = parse_qs(query)
                text = q.get("text", ["0"])[0] not in ("0", "", "false")
                if text:
                    body = _canary.canaryz_text()
                else:
                    payload = _canary.canaryz()
                    payload.update(_audit.auditz())
                    body = json.dumps(payload, indent=2, default=repr)
                self._reply(200, body,
                            "text/plain" if text else "application/json")
            elif path == "/chaosz":
                # fault-injection control plane (distributed/faults.py):
                # ?inject=<spec> arms rules, ?clear=1 removes runtime
                # rules, bare GET lists what's armed.  tools/chaos.py is
                # the operator CLI over this endpoint.
                from urllib.parse import parse_qs, unquote
                from ..distributed import faults as _faults
                q = parse_qs(query)
                if q.get("inject"):
                    try:
                        added = _faults.inject(unquote(q["inject"][0]))
                    except ValueError as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}) + "\n", "application/json")
                        return
                    self._reply(200, json.dumps(
                        {"injected": added}, indent=2), "application/json")
                elif q.get("clear"):
                    self._reply(200, json.dumps(
                        {"cleared": _faults.clear()}), "application/json")
                else:
                    self._reply(200, json.dumps(
                        {"rules": _faults.list_rules()}, indent=2),
                        "application/json")
            elif path == "/":
                self._reply(200, "\n".join(
                    ["paddle_tpu debug server", "",
                     "/metrics  /healthz  /statusz  /stepz",
                     "/tracez  (?raw=1 span snapshot, ?recent=1 flight "
                     "recorder)",
                     "/memz  /profilez  (?text=1 human rendering)",
                     "/servingz  (model-server router + batching gauges)",
                     "/decodez  (decode engines: slots, paged cache, "
                     "queue)",
                     "/fleetz  (supervised fleet state machine; "
                     "?resize=role:n ?drain=w ?resume= ?cut=1)",
                     "/varz  (metric history rings; ?window=<s> "
                     "?grep=<substr>)",
                     "/sloz  (SLO watchdog rule table)",
                     "/capacityz  (phase utilization + headroom; "
                     "?text=1)",
                     "/tenantz  (per-tenant usage metering; ?text=1)",
                     "/allocz  (memory-attribution ledger + event ring; "
                     "?text=1)",
                     "/quantz  (int8 calibration, quantized matmul "
                     "fallbacks, KV dtype; ?text=1)",
                     "/canaryz  (golden canary streaks + divergence "
                     "audit; ?text=1)",
                     "/chaosz  (?inject=<spec> arm faults, ?clear=1)", ""]),
                    "text/plain")
            else:
                sc.counter("not_found").inc()
                self._reply(404, f"no such page: {path}\n", "text/plain")
                return
            sc.counter("requests" + path.replace("/", ".")).inc()
        except Exception as e:  # pragma: no cover - handler last resort
            try:
                self._reply(500, f"internal error: {e!r}\n", "text/plain")
            except Exception:
                pass


class DebugServer:
    """One ThreadingHTTPServer on a daemon thread (see module doc)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name=f"debug-server-{host}:{self.port}")

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def server() -> Optional[DebugServer]:
    """The running singleton, or None (the flag-off steady state)."""
    return _server


def start(port: int = 0, host: Optional[str] = None) -> DebugServer:
    """Start (or return) the process-wide server.  ``port=0`` binds an
    ephemeral port — tests read ``.port`` back."""
    global _server
    with _lock:
        if _server is not None:
            return _server
        from ..core import flags as _flags
        if host is None:
            try:
                host = _flags.get_flags("debug_server_host")
            except KeyError:  # pragma: no cover
                host = "127.0.0.1"
        srv = DebugServer(port=port, host=host)
        srv.start()
        _server = srv
        return srv


def stop() -> None:
    global _server
    with _lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def maybe_start_from_flags() -> Optional[DebugServer]:
    """The wiring hook (Executor init, RPCServer start): starts the
    singleton iff ``FLAGS_debug_server_port`` > 0.  With the flag at its
    default 0 this is a dict lookup — no socket, no thread.  The
    metric-history sampler and SLO watchdog ride the same hook (each
    behind its OWN flag — they work without the HTTP server; flags at
    defaults, each check is one dict lookup)."""
    from ..core import flags as _flags
    from . import canary as _canary
    _history.maybe_start_from_flags()
    _slo.maybe_start_from_flags()
    _canary.maybe_start_from_flags()
    try:
        port = int(_flags.get_flags("debug_server_port"))
    except KeyError:  # pragma: no cover
        return None
    if port <= 0:
        return _server
    try:
        return start(port=port)
    except OSError as e:
        # a second process on the host with the same flag value: telemetry
        # must never take training down — warn and run without the server
        print(f"[debug-server] cannot bind port {port}: {e}",
              file=sys.stderr, flush=True)
        return None
