"""Metric history rings: "what changed in the last 10 minutes?"

Counters and gauges in the :mod:`stats` registry are point-in-time —
without an external scraper there is no way to ask how a metric MOVED.
This module keeps a bounded, self-downsampling time series per
counter/gauge in-process:

- a sampler (thread under ``FLAGS_metrics_history_interval_s``, or
  explicit :meth:`HistoryStore.sample` calls in tests) appends one
  ``(monotonic_ts, value)`` point per metric per period;
- each :class:`SeriesRing` holds at most ``FLAGS_metrics_history_points``
  points; when full it HALVES its resolution — adjacent samples merge
  into their mean, the stored stride doubles — so memory stays bounded
  while the covered window keeps extending (a long-lived server holds a
  coarse day next to a fine last-hour);
- queries (``/varz?window=<s>``, :func:`query`) return ``[[age_s,
  value], ...]`` — ages, not wall clocks.  The STATS_PULL fleet merge
  carries each worker's series the same way, so skewed worker wall
  clocks can never misalign the fleet view: every sample is "N seconds
  before that worker answered the pull".

Strictly flag-gated: with ``FLAGS_metrics_history_interval_s`` at its
default 0 no thread starts, no ring allocates, and ``export_state()``
payloads carry no history key — byte-identical to the pre-history wire.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from . import stats as _stats
from ..core import flags as _flags

__all__ = ["SeriesRing", "HistoryStore", "store", "maybe_start_from_flags",
           "query", "export_history", "varz", "stop"]


class SeriesRing:
    """One metric's bounded, resolution-doubling time series.

    Points are ``(t_monotonic, value)``.  ``append`` accumulates
    ``stride`` raw samples into one stored point (mean value, last
    timestamp); when the ring is full, adjacent stored points merge
    pairwise into their means and ``stride`` doubles.  Mean-of-means
    stays exact because merged pairs hold equal sample counts (an odd
    ring capacity leaves one boundary point approximate; the default
    capacity is even).
    """

    __slots__ = ("capacity", "stride", "_pts", "_acc_n", "_acc_sum",
                 "_acc_t")

    def __init__(self, capacity: int):
        self.capacity = max(8, int(capacity))
        self.stride = 1
        self._pts: List[List[float]] = []    # [t, mean]
        self._acc_n = 0
        self._acc_sum = 0.0
        self._acc_t = 0.0

    def append(self, t: float, v: float) -> None:
        self._acc_n += 1
        self._acc_sum += float(v)
        self._acc_t = t
        if self._acc_n < self.stride:
            return
        self._pts.append([self._acc_t, self._acc_sum / self._acc_n])
        self._acc_n, self._acc_sum = 0, 0.0
        if len(self._pts) >= self.capacity:
            merged = []
            pts = self._pts
            for i in range(0, len(pts) - 1, 2):
                merged.append([pts[i + 1][0],
                               (pts[i][1] + pts[i + 1][1]) / 2.0])
            if len(pts) % 2:                 # odd leftover kept verbatim
                merged.append(pts[-1])
            self._pts = merged
            self.stride *= 2

    def __len__(self) -> int:
        return len(self._pts)

    def points(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[List[float]]:
        """``[[age_s, value], ...]`` oldest-first (ages decreasing)."""
        now = time.monotonic() if now is None else now
        out = []
        for t, v in self._pts:
            age = now - t
            if window_s is not None and age > window_s:
                continue
            out.append([round(age, 3), v])
        return out


class HistoryStore:
    """Every counter/gauge of one registry, ringed (see module doc)."""

    def __init__(self, registry: Optional[_stats.StatsRegistry] = None,
                 points: Optional[int] = None):
        self.registry = registry or _stats.default_registry()
        if points is None:
            points = int(_flags.get_flags("metrics_history_points"))
        self.points = points
        self._lock = threading.Lock()
        self._series: Dict[str, SeriesRing] = {}
        self._samples = 0

    def sample(self, now: Optional[float] = None) -> int:
        """Append one point per counter/gauge (histograms keep their
        own bucket state and are skipped).  Returns metrics sampled."""
        now = time.monotonic() if now is None else now
        snap = self.registry.snapshot()
        n = 0
        with self._lock:
            for name, val in snap.items():
                if isinstance(val, dict):     # histogram snapshot
                    continue
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = SeriesRing(self.points)
                ring.append(now, float(val))
                n += 1
            self._samples += 1
        return n

    def query(self, window_s: Optional[float] = None,
              pattern: str = "", now: Optional[float] = None
              ) -> Dict[str, List[List[float]]]:
        """{metric: [[age_s, value], ...]} within ``window_s``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            items = sorted(self._series.items())
        out = {}
        for name, ring in items:
            if pattern and pattern not in name:
                continue
            pts = ring.points(window_s, now=now)
            if pts:
                out[name] = pts
        return out

    def export_state(self, now: Optional[float] = None) -> dict:
        """Merge-ready wire form for the STATS_PULL fleet aggregation:
        ages only (clock-skew-proof), plus this store's strides so a
        reader knows each series' current resolution."""
        now = time.monotonic() if now is None else now
        with self._lock:
            items = sorted(self._series.items())
            samples = self._samples
        return {"samples": samples,
                "series": {name: ring.points(now=now)
                           for name, ring in items},
                "strides": {name: ring.stride for name, ring in items}}

    def stats(self) -> dict:
        with self._lock:
            return {"series": len(self._series),
                    "samples": self._samples,
                    "points": sum(len(r) for r in self._series.values()),
                    "capacity_points": self.points}


_lock = threading.Lock()
_store: Optional[HistoryStore] = None
_thread: Optional[threading.Thread] = None
_stop = threading.Event()


def interval_s() -> float:
    try:
        return float(_flags.get_flags("metrics_history_interval_s"))
    except KeyError:  # pragma: no cover - flag always defined
        return 0.0


def enabled() -> bool:
    return interval_s() > 0.0


def store(create: bool = False) -> Optional[HistoryStore]:
    """The process-wide store (None until armed)."""
    global _store
    with _lock:
        if _store is None and create:
            _store = HistoryStore()
        return _store


def maybe_start_from_flags() -> Optional[HistoryStore]:
    """Arm the sampler thread iff ``FLAGS_metrics_history_interval_s``
    > 0 (idempotent; called next to the debug-server opt-in).  Flag at
    its default 0: one dict lookup, nothing else."""
    global _thread
    if not enabled():
        return _store
    st = store(create=True)
    with _lock:
        if _thread is not None and _thread.is_alive():
            return st
        _stop.clear()

        def _loop():
            while not _stop.wait(max(0.05, interval_s())):
                try:
                    st.sample()
                except Exception:  # pragma: no cover - never kill host
                    pass

        _thread = threading.Thread(target=_loop, daemon=True,
                                   name="metrics-history-sampler")
        _thread.start()
    return st


def stop() -> None:
    """Stop the sampler and drop the store (tests)."""
    global _store, _thread
    _stop.set()
    with _lock:
        t, _thread = _thread, None
        _store = None
    if t is not None:
        t.join(timeout=2.0)


def query(window_s: Optional[float] = None, pattern: str = ""
          ) -> Dict[str, List[List[float]]]:
    st = store()
    return st.query(window_s, pattern) if st is not None else {}


def export_history() -> Optional[dict]:
    """The STATS_PULL rider: this process's series, or None when the
    plane is off (the payload then stays byte-identical to the
    pre-history wire)."""
    st = store()
    if st is None:
        return None
    return st.export_state()


def varz(window_s: Optional[float] = None, pattern: str = "") -> dict:
    """The /varz page payload."""
    st = store()
    if st is None:
        return {"history": "disabled (set FLAGS_metrics_history_"
                           "interval_s > 0)"}
    out = {"interval_s": interval_s(), **st.stats()}
    out["window_s"] = window_s
    out["series_points"] = query(window_s, pattern)
    return out
