"""Per-request latency-phase attribution: where did the p99 go?

The serving and decode planes used to export ONE end-to-end latency
histogram per model — enough to see a tail regression, useless for
operating on it (queue wait, batch assembly, device execution and
readback/reply all hide inside one number).  This module gives every
request a :class:`PhaseTimeline` of monotonic stamps through its
lifecycle and folds the finished timelines into a per-model
:class:`PhaseRecorder`:

- one fixed-bucket histogram per phase (``<scope>.phase.<name>``,
  exported like any other metric — /metrics, STATS_PULL fleet merge);
- a bounded per-request sample ring (the "request flight recorder") —
  the raw recent tail an operator reads after a spike;
- slowest-request exemplars (top-N by total) that keep their trace ids,
  so the worst request links straight into the PR-4 distributed trace.

**The invariant**: a timeline's phases are consecutive deltas of ONE
``time.monotonic()`` clock, so recorded phase durations sum EXACTLY to
the recorded end-to-end wall — a p99 regression always names its phase,
nothing leaks into an unattributed gap.  (Tests pin the recorded total
against an externally measured wall within 5%.)

Strictly flag-gated (``FLAGS_phase_attribution``): stamps are host-side
``time.monotonic()`` reads only (zero device syncs), and with the flag
off no timeline is created and no ``*.phase.*`` series ever registers —
the metric surface is byte-identical to the pre-phase build.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from . import stats as _stats
from ..core import flags as _flags

# phase histograms reuse the default ms buckets; the sample ring and
# exemplar list are small fixed bounds (operator tails, not archives)
_SAMPLE_RING = 64
_EXEMPLARS = 8


def enabled() -> bool:
    """One dict lookup — the per-request gate."""
    try:
        return bool(_flags.get_flags("phase_attribution"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


class PhaseTimeline:
    """Monotonic stamps along one request's lifecycle.

    ``stamp(name)`` closes the interval that started at the previous
    stamp (or at construction) and labels it ``name``; ``durations()``
    returns the ordered ``{name: ms}`` map whose values sum to
    ``total_ms()`` by construction.
    """

    __slots__ = ("t0", "marks")

    def __init__(self, t0: Optional[float] = None):
        self.t0 = time.monotonic() if t0 is None else t0
        self.marks: List[tuple] = []

    def stamp(self, name: str, t: Optional[float] = None) -> None:
        """Close the current interval as ``name``.  ``t`` lets a batch
        event stamp many timelines with ONE clock read; stamps are
        clamped monotonic so a shared batch timestamp that races a
        per-request stamp can never produce a negative phase."""
        now = time.monotonic() if t is None else t
        last = self.marks[-1][1] if self.marks else self.t0
        self.marks.append((name, max(now, last)))

    def total_ms(self) -> float:
        if not self.marks:
            return 0.0
        return (self.marks[-1][1] - self.t0) * 1e3

    def durations(self) -> Dict[str, float]:
        """Ordered {phase: ms}; values sum to total_ms() exactly."""
        out: Dict[str, float] = {}
        prev = self.t0
        for name, t in self.marks:
            out[name] = out.get(name, 0.0) + (t - prev) * 1e3
            prev = t
        return out


class PhaseRecorder:
    """One model/plane's phase aggregation (see module doc).

    Histograms are created lazily on the first observed timeline so a
    flag-off process never registers ``*.phase.*`` series.
    """

    def __init__(self, scope: str, phases: Sequence[str] = ()):
        self.scope = scope
        self._declared = tuple(phases)
        self._lock = threading.Lock()
        self._hists: Dict[str, _stats.Histogram] = {}
        self._total: Optional[_stats.Histogram] = None
        self._ring: deque = deque(maxlen=_SAMPLE_RING)
        self._slowest: List[dict] = []   # kept sorted, slowest first
        self._observed = 0

    def _hist(self, phase: str) -> _stats.Histogram:
        h = self._hists.get(phase)
        if h is None:
            h = _stats.histogram(f"{self.scope}.phase.{phase}_ms")
            self._hists[phase] = h
        return h

    def observe(self, tl: PhaseTimeline, trace_id: Optional[int] = None,
                **meta) -> None:
        """Fold one finished timeline in (engine/batcher side)."""
        durs = tl.durations()
        total = tl.total_ms()
        sample = {"ts": time.time(), "total_ms": round(total, 3),
                  "phases": {k: round(v, 3) for k, v in durs.items()}}
        if trace_id:
            sample["trace_id"] = format(trace_id, "x")
        if meta:
            sample.update(meta)
        with self._lock:
            for k, v in durs.items():
                self._hist(k).observe(v)
            if self._total is None:
                self._total = _stats.histogram(
                    f"{self.scope}.phase.total_ms")
            self._total.observe(total)
            self._observed += 1
            self._ring.append(sample)
            # slowest-request exemplars: tiny N, insertion sort is fine
            self._slowest.append(sample)
            self._slowest.sort(key=lambda s: -s["total_ms"])
            del self._slowest[_EXEMPLARS:]

    def snapshot(self) -> dict:
        """The /servingz//decodez payload: per-phase percentiles, the
        slowest-phase attribution, recent samples, exemplars."""
        with self._lock:
            hists = dict(self._hists)
            total = self._total
            recent = list(self._ring)[-16:]
            slowest = [dict(s) for s in self._slowest]
            observed = self._observed
        phases = {}
        worst_name, worst_p99 = None, -1.0
        for name, h in hists.items():
            snap = h.snapshot()
            p50 = _stats.histogram_percentile(snap, 0.50,
                                              finite_max=h.buckets[-1])
            p99 = _stats.histogram_percentile(snap, 0.99,
                                              finite_max=h.buckets[-1])
            phases[name] = {"count": snap["count"],
                            "mean_ms": round(snap["sum"]
                                             / max(snap["count"], 1), 3),
                            "p50_ms": round(p50, 3),
                            "p99_ms": round(p99, 3)}
            if p99 > worst_p99:
                worst_name, worst_p99 = name, p99
        out = {"observed": observed, "phases": phases,
               "slowest_phase": worst_name,
               "recent": recent, "slowest_requests": slowest}
        if total is not None:
            tsnap = total.snapshot()
            out["total_p99_ms"] = round(_stats.histogram_percentile(
                tsnap, 0.99, finite_max=total.buckets[-1]), 3)
        return out

    def phase_p99_ms(self) -> Dict[str, float]:
        """{phase: p99 ms} — the bench-artifact form."""
        snap = self.snapshot()
        return {name: ent["p99_ms"]
                for name, ent in snap["phases"].items()}
