"""Per-``Executor.run`` step records in a bounded ring buffer.

Each run of the lower→jit→cache pipeline appends one :class:`StepStats`:
which executable served it (``program_key``), whether the compile cache
hit, where the time went (lowering vs first-call XLA compile vs total
wall), and how many bytes crossed the host↔device boundary.  A recompile
storm, a feed-transfer bottleneck, or a silently-degrading benchmark run
shows up here as data instead of as a mystery (BENCH_r0*.json motivated
this: runs degraded to skipped/zero metrics with no signal why).

The buffer is process-wide and bounded (``maxlen`` ring), so it is safe
to leave recording on in serving processes; ``summary()`` gives
percentile aggregates and ``last_n()`` the raw tail.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from .stats import percentile_sorted


def approx_nbytes(v) -> int:
    """Bytes of a host/device array from metadata only — never syncs.

    Works for numpy, jax.Array, SelectedRows (rows+values) and anything
    else exposing nbytes or shape+dtype; returns 0 for unsized values.
    """
    try:
        sz = getattr(v, "size", None)  # numpy + jax fast path (metadata;
        dt = getattr(v, "dtype", None)  # jax .nbytes is ~6x slower)
        if sz is not None and dt is not None:
            return int(sz) * dt.itemsize
        rows = getattr(v, "rows", None)
        values = getattr(v, "values", None)
        if rows is not None and values is not None:  # SelectedRows pytree
            return approx_nbytes(rows) + approx_nbytes(values)
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is None or dtype is None:
            return 0
        import numpy as np
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    except Exception:
        return 0


@dataclass
class StepStats:
    """One ``Executor.run`` (or ``run_steps`` dispatch) worth of telemetry."""

    program_key: str        # short id of the executable-cache key
    cache_hit: bool
    lowering_ms: float = 0.0   # analyze_block + build_block_fn (miss only)
    compile_ms: float = 0.0    # first jitted call: trace + XLA compile
    feed_bytes: int = 0        # host→device feed payload
    fetch_bytes: int = 0       # device→host fetch payload (metadata-sized)
    sync_ms: float = 0.0       # explicit device sync inside run (if any)
    wall_ms: float = 0.0       # whole run() wall time
    ts: float = field(default_factory=time.time)
    # model-health scalars registered via Program.step_stat_vars and
    # fetched this step (e.g. switch_moe's aux loss / dropped-token
    # fraction) — EP/MoE health lands in /stepz next to the step timing
    extras: Optional[Dict[str, float]] = None

    def to_dict(self) -> dict:
        return asdict(self)


# the shared raw-sample percentile (observability/stats.py): /servingz,
# /decodez and these summaries must agree on small windows
_percentile = percentile_sorted


class StepStatsRecorder:
    """Bounded ring of StepStats + aggregate summaries (thread-safe)."""

    def __init__(self, capacity: int = 512):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._total_recorded = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def record(self, ss: StepStats) -> None:
        with self._lock:
            self._ring.append(ss)
            self._total_recorded += 1

    def last_n(self, n: int) -> List[StepStats]:
        with self._lock:
            if n <= 0:
                return []
            return list(self._ring)[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total_recorded(self) -> int:
        """Lifetime count, including entries the ring has dropped."""
        with self._lock:
            return self._total_recorded

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._total_recorded = 0

    def summary(self) -> Dict[str, object]:
        """Aggregates over the retained window (NOT lifetime): hit rate,
        wall-time percentiles, compile/transfer totals."""
        with self._lock:
            steps = list(self._ring)
            total = self._total_recorded
        hits = sum(1 for s in steps if s.cache_hit)
        walls = sorted(s.wall_ms for s in steps)
        out: Dict[str, object] = {
            "window": len(steps),
            "total_recorded": total,
            "cache_hits": hits,
            "cache_misses": len(steps) - hits,
            "hit_rate": round(hits / len(steps), 4) if steps else 0.0,
            "compile_ms_total": round(sum(s.compile_ms for s in steps), 3),
            "lowering_ms_total": round(sum(s.lowering_ms for s in steps), 3),
            "feed_bytes_total": sum(s.feed_bytes for s in steps),
            "fetch_bytes_total": sum(s.fetch_bytes for s in steps),
        }
        out["wall_ms"] = {
            "p50": round(_percentile(walls, 0.50), 3),
            "p90": round(_percentile(walls, 0.90), 3),
            "p99": round(_percentile(walls, 0.99), 3),
            "mean": round(sum(walls) / len(walls), 3) if walls else 0.0,
            "max": round(walls[-1], 3) if walls else 0.0,
        }
        return out

    def export(self, tail: int = 32) -> Dict[str, object]:
        """summary + the raw last-``tail`` records, JSON-ready."""
        return {"summary": self.summary(),
                "last": [s.to_dict() for s in self.last_n(tail)]}


_recorder = StepStatsRecorder()


def recorder() -> StepStatsRecorder:
    return _recorder


def record(ss: StepStats) -> None:
    _recorder.record(ss)


def last_n(n: int) -> List[StepStats]:
    return _recorder.last_n(n)


def summary() -> Dict[str, object]:
    return _recorder.summary()


def clear() -> None:
    _recorder.clear()
