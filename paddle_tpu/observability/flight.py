"""Crash flight recorder: a black-box post-mortem for dead workers.

The health plane (PR 2) tells the fleet *that* a worker died; this
module records *what it was doing*.  Every process keeps a bounded ring
of recent log events (:func:`note`) next to the distributed-span ring
(``trace.py``) and the step-stats ring; when the process dies badly the
whole bundle — recent spans, **in-flight** spans, log events, the
step-stats tail — is dumped as one JSON file into
``FLAGS_flight_record_dir``:

- unhandled exceptions (``sys.excepthook`` / ``threading.excepthook``),
- SIGTERM (a killed worker still leaves its black box, main thread
  only — signal handlers cannot be installed elsewhere),
- explicit dirty exits (``Heartbeat.stop(bye=False)`` — the path a
  worker takes when it stops heartbeating without saying goodbye).

Strictly opt-in: with ``FLAGS_flight_record_dir`` empty (the default)
:func:`arm_from_flags` reads one flag and installs nothing; ``note()``
still records into the in-memory ring (cheap, bounded) so the
``/tracez?recent=1`` debug page works without the dump-to-disk hooks.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from . import step_stats as _step_stats
from . import trace as _trace
from ..core import flags as _flags

_EVENT_RING = 256      # recent log events kept
_SPAN_TAIL = 256       # completed spans included in a dump
_STEP_TAIL = 8         # step-stats records included in a dump

_lock = threading.Lock()
_events: deque = deque(maxlen=_EVENT_RING)
_total_events = 0
_hooks_installed = False
_last_dump_path: Optional[str] = None


def record_dir() -> str:
    try:
        return str(_flags.get_flags("flight_record_dir") or "")
    except KeyError:  # pragma: no cover - flag always defined
        return ""


def armed() -> bool:
    """Dump-to-disk hooks wanted (``FLAGS_flight_record_dir`` set)?"""
    return bool(record_dir())


def note(msg: str, **fields) -> None:
    """Append one log event to the flight ring (always-on, bounded).
    Call sites are the runtime's 'loud' moments — failovers, apply
    errors, dirty exits — so a post-mortem reads as a story."""
    global _total_events
    ev = {"ts": time.time(), "msg": str(msg)}
    if fields:
        ev.update(fields)
    with _lock:
        _events.append(ev)
        _total_events += 1


def events() -> List[dict]:
    with _lock:
        return [dict(e) for e in _events]


def clear_events() -> None:
    global _total_events
    with _lock:
        _events.clear()
        _total_events = 0


def snapshot(reason: str, exc_info=None) -> dict:
    """The post-mortem bundle (what :func:`dump` writes and the
    ``/tracez?recent=1`` debug page serves live)."""
    out = {
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "role": os.environ.get("PADDLE_TRAINING_ROLE", "STANDALONE"),
        "argv": list(sys.argv),
        "open_spans": _trace.open_spans(),
        "spans": _trace.spans(limit=_SPAN_TAIL),
        "lanes": {str(k): v for k, v in _trace.local_trace_snapshot(
            limit=0)["lanes"].items()},
        "events": events(),
        "step_stats": _step_stats.recorder().export(tail=_STEP_TAIL),
    }
    if exc_info is not None:
        tp, val, tb = exc_info
        out["exception"] = "".join(
            traceback.format_exception(tp, val, tb))[-8000:]
    return out


def last_dump_path() -> Optional[str]:
    return _last_dump_path


def dump(reason: str, exc_info=None,
         dirname: Optional[str] = None) -> Optional[str]:
    """Write the post-mortem; returns the path (None when disarmed or
    the write fails — a dying process must never die harder over its
    own black box)."""
    global _last_dump_path
    dirname = dirname or record_dir()
    if not dirname:
        return None
    try:
        os.makedirs(dirname, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(
            dirname, f"flight_{os.getpid()}_{stamp}_{reason}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snapshot(reason, exc_info=exc_info), f, indent=2,
                      default=repr)
        os.replace(tmp, path)  # atomic: a reader never sees a partial
        _last_dump_path = path
        return path
    except Exception:  # pragma: no cover - disk full, perms, ...
        return None


def export_events(path: str, role: str = "") -> str:
    """Write this process's event ring as one JSON record
    ``{"role", "pid", "events"}`` (atomic rename).  The chaos suite's
    runners call it on the way out so a test can stitch the
    cross-process note chain without arming the full dump hooks."""
    rec = {"role": role or os.environ.get("PADDLE_TRAINING_ROLE", ""),
           "pid": os.getpid(), "events": events()}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, default=repr)
    os.replace(tmp, path)
    return path


def dirty_exit(reason: str) -> Optional[str]:
    """A worker leaving without a goodbye (``Heartbeat.stop(bye=False)``
    and friends): dump if armed, no-op otherwise."""
    note("dirty_exit", reason=reason)
    if not armed():
        return None
    return dump(reason)


def arm_from_flags() -> bool:
    """Install the crash hooks iff ``FLAGS_flight_record_dir`` is set
    (idempotent; called from ``Executor.__init__`` and
    ``RPCServer.start`` next to the debug-server opt-in).  Returns
    whether hooks are installed."""
    global _hooks_installed
    if _hooks_installed:
        return True
    if not armed():
        return False
    with _lock:
        if _hooks_installed:
            return True
        _hooks_installed = True

    prev_except = sys.excepthook

    def _excepthook(tp, val, tb):
        dump("unhandled_exception", exc_info=(tp, val, tb))
        prev_except(tp, val, tb)

    sys.excepthook = _excepthook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        dump("unhandled_thread_exception",
             exc_info=(args.exc_type, args.exc_value, args.exc_traceback))
        prev_thread(args)

    threading.excepthook = _thread_hook

    if threading.current_thread() is threading.main_thread():
        try:
            prev_term = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                dump("sigterm")
                if callable(prev_term):
                    prev_term(signum, frame)
                else:
                    # restore the default disposition and re-deliver so
                    # the exit status still says "killed by SIGTERM"
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):  # pragma: no cover - exotic hosts
            pass
    return True
