"""Runtime telemetry: metrics registry, per-step stats, runtime spans.

Three cooperating pieces (see each module's docstring):

- :mod:`stats` — process-wide counters / gauges / fixed-bucket
  histograms with ``snapshot()`` / ``to_prometheus_text()`` / JSON
  export; the instrumented layers (``core/executor.py``,
  ``core/lowering.py``, ``parallel/parallel_executor.py``,
  ``distributed/transport.py``) report here under the ``executor.*``,
  ``lowering.*``, ``parallel.*`` and ``rpc.*`` scopes.
- :mod:`step_stats` — a bounded ring of per-``Executor.run`` records
  (cache hit/miss, lowering + XLA compile time, feed/fetch bytes, wall
  time) with ``last_n()`` and percentile ``summary()``.
- :mod:`trace` — runtime spans feeding the existing profiler event
  stream under a ``runtime::`` category, so Chrome traces show executor
  internals alongside user spans — PLUS the distributed-tracing layer:
  trace/span ids, head sampling (``FLAGS_trace_sample_rate``),
  cross-process context propagation over the RPC wire, a bounded span
  ring per process, and ``stitch_chrome_trace`` fleet stitching.
- :mod:`flight` — the crash flight recorder: bounded log-event ring +
  post-mortem dumps (recent/in-flight spans, events, step tail) to
  ``FLAGS_flight_record_dir`` on unhandled exceptions, SIGTERM and
  dirty exits.
- :mod:`perf` — the perf/numerics attribution plane
  (``FLAGS_perf_attribution``): XLA ``cost_analysis``/
  ``memory_analysis`` per executable, roofline positions vs the
  platform peak table, live device-memory gauges; served on
  ``/profilez`` + ``/memz``.
- :mod:`runlog` — append-only JSONL per-step scalar log
  (``FLAGS_run_log_dir``): loss/any scalar fetch, grad global norm,
  step_ms, samples/s, with atomic rotation and a ``watch()`` tail;
  ``tools/runlog_report.py`` renders/compares.

The latency-anatomy / SLO plane (all strictly flag-gated):

- :mod:`phase` — per-request phase attribution
  (``FLAGS_phase_attribution``): monotonic phase timelines through the
  serving batcher / decode engine lifecycles, per-phase histograms, a
  bounded per-request sample ring with slowest-request exemplars
  linked to trace ids; phases sum to the end-to-end wall by
  construction, so a p99 regression names its phase.
- :mod:`history` — bounded, resolution-doubling metric history rings
  (``FLAGS_metrics_history_interval_s``): every counter/gauge retains
  a downsampled time series, served on ``/varz?window=...`` and
  carried (age-aligned, clock-skew-proof) through the STATS_PULL
  fleet merge.
- :mod:`capacity` — phase-level utilization + capacity modeling
  (``FLAGS_capacity_attribution``): per-component busy-time windows
  (``*.util.*`` gauges), operational-law service-time fits (U = X·S),
  ``predicted_max_qps`` / ``headroom_frac`` with a saturation verdict
  naming the binding phase; served on ``/capacityz``, merged over
  STATS_PULL, riding serving/decode lease data into the
  ElasticController's HOLD-safe ``capacity`` input.
- :mod:`tenant` — per-tenant usage metering
  (``FLAGS_tenant_accounting``): wire-optional tenant ids accounted
  into a space-saving top-K sketch (requests/rows/tokens/cancellations
  + proportionally attributed device-ms, per-tenant p99); served on
  ``/tenantz``, fleet-merged so a fleet-wide heavy hitter is visible
  from one endpoint.  Ids are client-supplied — attribution, not
  isolation.
- :mod:`slo` — the declarative SLO watchdog (``FLAGS_slo_rules``):
  metric × percentile/rate × threshold × sustain-window rules
  evaluated in-process; breaches count, leave flight notes, render on
  ``/sloz`` and ride the registry heartbeat as an ``slo`` health
  dimension the ElasticController/supervisor consume.
- :mod:`canary` — the golden canary prober (``FLAGS_canary_probe``):
  a background thread replays recorded input→expected-output goldens
  (``tools/golden.py record``) through every registered replica's real
  submit path, compares with per-model rtol, keeps per-replica
  pass/fail streaks; served on ``/canaryz``, fleet-merged, riding the
  heartbeat as a ``canary`` health dimension the supervisor's
  ``quarantine_on_canary_fail`` policy consumes (DRAIN, never kill).
- :mod:`audit` — the cross-replica divergence sentinel
  (``FLAGS_divergence_check``): reply-batch content digests / decode
  token rolling hashes / periodic DP parameter checksums folded into a
  bounded ring riding the lease data; digests grouped by (model,
  version, request-hash) across replicas NAME a divergent minority
  replica — silent data corruption surfaces without trusting any
  single machine.

The export/aggregation half (this package's fleet plane):

- :mod:`debug_server` — opt-in (``FLAGS_debug_server_port``) HTTP
  daemon serving ``/metrics`` ``/healthz`` ``/statusz`` ``/stepz``;
- :mod:`health` — heartbeat-driven worker liveness
  (HEALTHY/SUSPECT/DEAD), fed by the discovery registry's TTL leases;
- :mod:`aggregate` — STATS_PULL RPC + cross-worker merge of counters /
  gauges / histograms into per-worker-labeled ``fleet:*`` series.

Everything is gated by ``FLAGS_runtime_stats`` (env
``FLAGS_runtime_stats=0`` disables all collection); spans additionally
require the profiler to be armed, so the default-path overhead is a
flag lookup.
"""
from __future__ import annotations

from . import (  # noqa: F401
    aggregate,
    audit,
    canary,
    capacity,
    debug_server,
    flight,
    health,
    history,
    perf,
    phase,
    runlog,
    slo,
    stats,
    step_stats,
    tenant,
    trace,
)
from .aggregate import FleetAggregator  # noqa: F401
from .health import HealthTable  # noqa: F401
from .stats import (  # noqa: F401
    StatsRegistry,
    default_registry,
    snapshot,
    to_prometheus_text,
)
from .step_stats import StepStats, StepStatsRecorder  # noqa: F401
from .trace import SpanContext, start_span, stitch_chrome_trace  # noqa: F401


def enabled() -> bool:
    """Is runtime telemetry collection on (``FLAGS_runtime_stats``)?"""
    return trace.flags_on()


def export(step_tail: int = 32) -> dict:
    """One JSON-ready bundle: metrics snapshot + step-stats summary/tail.

    The shape bench.py dumps per config into ``step_stats.json`` and the
    debug server serves on ``/stepz``.
    """
    return {"stats": stats.to_dict(),
            "step_stats": step_stats.recorder().export(tail=step_tail)}


def reset() -> None:
    """Zero all metrics and drop the step ring (bench isolates configs)."""
    stats.reset()
    step_stats.clear()
