"""Declarative SLO watchdog: rules over the live metric surface.

The self-healing loop (PR 13) acts on liveness alone — a replica can be
alive and useless (TTFT p99 at 4 s) without the fleet ever noticing.
This module evaluates operator-declared rules against the in-process
:mod:`stats` registry and turns sustained violations into every signal
the rest of the stack already consumes:

- ``slo.breaches`` / ``slo.<rule>.breaches`` counters and a
  ``slo.breached`` gauge (how many rules are in breach right now);
- a flight-recorder note per breach/clear transition (the post-mortem
  reads "slo_breach ttft" next to the death it preceded);
- the ``/sloz`` debug page (rule table: live value, threshold, state,
  sustain progress);
- an ``slo`` **health dimension** merged into every registry heartbeat
  payload (``registry.Heartbeat._health_payload``), so the fleet health
  table, :class:`~paddle_tpu.checkpoint.elastic.ElasticController` and
  the supervisor see breach state per worker WITHOUT a new RPC.

Rule grammar (``FLAGS_slo_rules``, semicolon-separated)::

    name=metric:stat(op)threshold[:for=sustain_s]

    ttft=decode.lm.ttft_ms:p99>250:for=5
    errors=serving.mnist.errors:rate>0.5:for=10
    queue=decode.lm.queue_depth:value>48

``stat`` is ``p50``/``p90``/``p99``/``p999`` (histograms — via the
shared :func:`stats.histogram_percentile`, computed over the
observations SINCE the previous evaluation so the rule tracks current
behavior and can clear; an interval with no observations expresses no
opinion), ``rate`` (counters, per-second over the evaluation
interval), or ``value`` (gauges).  A
rule BREACHES only after its condition holds for ``for`` seconds of
consecutive evaluations, and CLEARS only after it fails for the same
window — symmetric hysteresis, so one outlier evaluation can neither
trip nor silence the alarm.  Consumers stay HOLD-safe: a breach is a
decision *input* (reported, damped), never an automatic resize.

Strictly flag-gated: ``FLAGS_slo_rules`` empty (default) means no
watchdog thread, no metric series, and zero bytes added to the
heartbeat payload.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional

from . import flight as _flight
from . import stats as _stats
from ..core import flags as _flags

__all__ = ["SloRule", "SloWatchdog", "parse_rules", "watchdog",
           "maybe_start_from_flags", "health_dimension", "active",
           "sloz", "stop"]

OK = "OK"
PENDING = "PENDING"
BREACH = "BREACH"

_STATS = ("p50", "p90", "p99", "p999", "rate", "value")
# metric charset includes '@' and '/': serving metrics are scoped by
# model@version, registry logical keys by path (serving/<m>/<replica>)
_RULE_RE = re.compile(
    r"^(?P<name>[\w.-]+)=(?P<metric>[\w.:@/-]+):"
    r"(?P<stat>p50|p90|p99|p999|rate|value)"
    r"(?P<op>[<>])(?P<threshold>-?[\d.]+(?:[eE][-+]?\d+)?)"
    r"(?::for=(?P<sustain>[\d.]+))?$")


class SloRule:
    """One parsed rule (see the module-doc grammar)."""

    def __init__(self, name: str, metric: str, stat: str, op: str,
                 threshold: float, sustain_s: float = 0.0):
        if stat not in _STATS:
            raise ValueError(f"slo rule {name!r}: unknown stat {stat!r}")
        if op not in ("<", ">"):
            raise ValueError(f"slo rule {name!r}: op must be < or >")
        self.name = name
        self.metric = metric
        self.stat = stat
        self.op = op
        self.threshold = float(threshold)
        self.sustain_s = float(sustain_s)
        # evaluation state (owned by the watchdog)
        self.state = OK
        self.since: Optional[float] = None     # condition flip time
        self.last_value: Optional[float] = None
        self.breaches = 0
        self._last_counter: Optional[tuple] = None   # (t, value) for rate
        self._last_hist: Optional[dict] = None       # snapshot for pXX

    def condition(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" \
            else value < self.threshold

    def to_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "stat": self.stat, "op": self.op,
                "threshold": self.threshold, "sustain_s": self.sustain_s,
                "state": self.state, "last_value": self.last_value,
                "breaches": self.breaches}


def parse_rules(spec: str) -> List[SloRule]:
    """Parse the flag grammar; malformed rules raise ValueError naming
    the offending fragment (a typo'd SLO must fail loudly at arm time,
    not silently never fire)."""
    rules = []
    for frag in str(spec or "").split(";"):
        frag = frag.strip()
        if not frag:
            continue
        m = _RULE_RE.match(frag)
        if m is None:
            raise ValueError(
                f"bad slo rule {frag!r}; expected "
                "'name=metric:stat(<|>)threshold[:for=sustain_s]'")
        rules.append(SloRule(m.group("name"), m.group("metric"),
                             m.group("stat"), m.group("op"),
                             float(m.group("threshold")),
                             float(m.group("sustain") or 0.0)))
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate slo rule names in {spec!r}")
    return rules


class SloWatchdog:
    """Evaluates rules in-process (module doc)."""

    def __init__(self, rules, registry: Optional[_stats.StatsRegistry] = None):
        self.rules: List[SloRule] = (parse_rules(rules)
                                     if isinstance(rules, str)
                                     else list(rules))
        self.registry = registry or _stats.default_registry()
        self._lock = threading.Lock()
        sc = _stats.scope("slo")
        self._c_breaches = sc.counter(
            "breaches", "SLO rule breach transitions (sustained "
            "violations; per-rule twins under slo.<rule>.breaches)")
        self._c_clears = sc.counter("clears", "breach -> OK transitions")
        self._g_breached = sc.gauge(
            "breached", "rules currently in BREACH")

    def _resolve(self, rule: SloRule, now: float) -> Optional[float]:
        m = self.registry.get(rule.metric)
        if m is None:
            return None
        if rule.stat in ("p50", "p90", "p99", "p999"):
            if not isinstance(m, _stats.Histogram):
                return None
            q = {"p50": 0.50, "p90": 0.90, "p99": 0.99,
                 "p999": 0.999}[rule.stat]
            # WINDOWED percentile: over the observations since the
            # previous evaluation (bucket-count delta), like `rate` for
            # counters.  A lifetime-cumulative percentile could never
            # CLEAR — one bad minute an hour ago would hold p99 high
            # forever.  No new observations => no opinion (None)
            snap = m.snapshot()
            prev, rule._last_hist = rule._last_hist, snap
            if prev is None:
                return None
            dcount = snap["count"] - prev["count"]
            if dcount <= 0:
                return None
            dbuckets = {le: cum - prev["buckets"].get(le, 0)
                        for le, cum in snap["buckets"].items()}
            return _stats.histogram_percentile(
                {"buckets": dbuckets, "count": dcount}, q,
                finite_max=m.buckets[-1])
        if rule.stat == "rate":
            v = float(m.value)
            prev = rule._last_counter
            rule._last_counter = (now, v)
            if prev is None or now <= prev[0]:
                return None          # first sighting: no interval yet
            return (v - prev[1]) / (now - prev[0])
        return float(m.value)

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation round; returns breach/clear TRANSITIONS."""
        now = time.monotonic() if now is None else now
        events = []
        with self._lock:
            breached = 0
            for rule in self.rules:
                value = self._resolve(rule, now)
                if value is None:
                    # metric not registered yet / wrong kind: not a
                    # breach (a decode engine that hasn't served yet
                    # must not page anyone)
                    if rule.state != BREACH:
                        rule.state, rule.since = OK, None
                    breached += rule.state == BREACH
                    continue
                rule.last_value = round(float(value), 4)
                cond = rule.condition(value)
                if rule.state == BREACH:
                    if cond:
                        rule.since = None        # still breaching
                    else:
                        if rule.since is None:
                            rule.since = now     # clear window opens
                        if now - rule.since >= rule.sustain_s:
                            rule.state, rule.since = OK, None
                            self._c_clears.inc()
                            events.append({"rule": rule.name,
                                           "event": "clear",
                                           "value": rule.last_value})
                else:
                    if not cond:
                        rule.state, rule.since = OK, None
                    else:
                        if rule.since is None:
                            rule.since = now     # breach window opens
                            rule.state = PENDING
                        if now - rule.since >= rule.sustain_s:
                            rule.state, rule.since = BREACH, None
                            rule.breaches += 1
                            self._c_breaches.inc()
                            _stats.counter(
                                f"slo.{rule.name}.breaches").inc()
                            events.append({"rule": rule.name,
                                           "event": "breach",
                                           "value": rule.last_value,
                                           "threshold": rule.threshold})
                breached += rule.state == BREACH
            self._g_breached.set(breached)
        for ev in events:
            _flight.note(f"slo_{ev['event']}", **ev)
        return events

    def breached(self) -> List[str]:
        with self._lock:
            return [r.name for r in self.rules if r.state == BREACH]

    def health_dimension(self) -> dict:
        """The heartbeat rider: ``{"slo": "ok"|"breach"[, "slo_rules":
        [names]]}`` — small, merge-ready, absent entirely when the
        plane is off (see :func:`health_dimension` below)."""
        names = self.breached()
        if not names:
            return {"slo": "ok"}
        return {"slo": "breach", "slo_rules": names}

    def sloz(self) -> dict:
        """The /sloz payload."""
        with self._lock:
            rules = [r.to_dict() for r in self.rules]
        return {"rules": rules, "breached": self.breached(),
                "eval_interval_s": eval_interval_s()}


_lock = threading.Lock()
_watchdog: Optional[SloWatchdog] = None
_thread: Optional[threading.Thread] = None
_stop = threading.Event()


def rules_spec() -> str:
    try:
        return str(_flags.get_flags("slo_rules") or "")
    except KeyError:  # pragma: no cover - flag always defined
        return ""


def eval_interval_s() -> float:
    try:
        return float(_flags.get_flags("slo_eval_interval_s"))
    except KeyError:  # pragma: no cover - flag always defined
        return 1.0


def active() -> bool:
    """A watchdog exists (armed from flags or installed explicitly)."""
    return _watchdog is not None


def watchdog() -> Optional[SloWatchdog]:
    return _watchdog


def install(wd: Optional[SloWatchdog]) -> Optional[SloWatchdog]:
    """Install (or clear, with None) the process watchdog explicitly —
    servers that build their rules in code rather than flags."""
    global _watchdog
    with _lock:
        _watchdog = wd
    return wd


def maybe_start_from_flags() -> Optional[SloWatchdog]:
    """Arm the watchdog + evaluation thread iff ``FLAGS_slo_rules`` is
    non-empty (idempotent, called next to the debug-server opt-in).
    Flag empty: one dict lookup, nothing else."""
    global _watchdog, _thread
    spec = rules_spec()
    if not spec:
        return _watchdog
    with _lock:
        if _watchdog is None:
            _watchdog = SloWatchdog(spec)
        wd = _watchdog
        if _thread is not None and _thread.is_alive():
            return wd
        _stop.clear()

        def _loop():
            while not _stop.wait(max(0.05, eval_interval_s())):
                try:
                    wd.evaluate()
                except Exception:  # pragma: no cover - never kill host
                    pass

        _thread = threading.Thread(target=_loop, daemon=True,
                                   name="slo-watchdog")
        _thread.start()
    return wd


def stop() -> None:
    """Stop the thread and drop the watchdog (tests)."""
    global _watchdog, _thread
    _stop.set()
    with _lock:
        t, _thread = _thread, None
        _watchdog = None
    if t is not None:
        t.join(timeout=2.0)


def health_dimension() -> dict:
    """What a registry heartbeat merges into its health payload: the
    watchdog's slo dimension, or ``{}`` when no watchdog is armed (the
    wire stays byte-identical to the pre-slo build)."""
    wd = _watchdog
    if wd is None:
        return {}
    try:
        return wd.health_dimension()
    except Exception:  # pragma: no cover - a broken probe never stops a lease
        return {}


def sloz() -> dict:
    """The /sloz page payload (armed or not)."""
    wd = _watchdog
    if wd is None:
        return {"slo": "no rules armed (set FLAGS_slo_rules or "
                       "slo.install(SloWatchdog(...)))"}
    return wd.sloz()
