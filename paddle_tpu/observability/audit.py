"""Cross-replica divergence sentinel: is the fleet still *agreeing*?

The numerics sentinel catches NaN/Inf; nothing before this plane caught
a replica that silently returns plausible-but-wrong numbers after a bad
hydrate, a stale hot-swap, or hardware silent data corruption.  When
``FLAGS_divergence_check`` is armed:

- **Serving replies**: each reply batch folds a content digest (FNV-1a
  64-bit over fetch names + raw array bytes) keyed by
  ``(model, version, request-hash)`` into a bounded per-model audit
  ring.  The ring rides the replica's registry lease data
  (:func:`recent_digests`), so the supervisor can group digests across
  replicas with zero new RPCs.
- **Decode streams**: the engine folds every emitted token id into a
  per-stream rolling hash; the finished stream's digest enters the same
  ring keyed by its prompt hash.
- **Training**: :meth:`ParallelExecutor` folds a periodic u64 parameter
  checksum (every ``FLAGS_divergence_param_steps`` steps) under the
  reserved model name ``__params__`` keyed by ``step:<n>`` — cross-DP
  state divergence is caught within K steps through the same grouping.

:func:`name_divergent` is the sentinel proper: it groups digests by
``(model, version, request-hash)`` across replicas and NAMES any
replica whose digest disagrees with a strict majority (>= 2 agreeing
peers) — a single divergent replica is *named*, not just suspected.
Two replicas that disagree with no tiebreaker are reported as a
``suspect`` pair instead.  Findings surface as ``divergence.*``
counters, flight-recorder notes, the ``/canaryz`` audit section, and a
STATS_PULL rider merged fleet-wide.

Determinism caveat: digests only group when replicas compute the SAME
request — grouping keys on the request-hash, so replicas that never
see common traffic (no canary, disjoint batches) simply produce no
groups.  The golden canary prober (canary.py) exists precisely to
guarantee common, repeated traffic across all replicas.

Off (default): no digests are computed, no metric series register, the
lease rider and STATS_PULL rider (:func:`export_state`) return ``None``
— byte-identical payloads.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..core import flags as _flags
from . import flight as _flight
from . import stats as _stats

__all__ = [
    "PARAMS_MODEL",
    "enabled",
    "fnv1a64",
    "fold_bytes",
    "fold_token",
    "digest_pairs",
    "request_hash",
    "AuditRing",
    "ring",
    "note_reply",
    "note_stream",
    "note_param_checksum",
    "recent_digests",
    "name_divergent",
    "auditz",
    "export_state",
    "merge_states",
    "reset",
]

PARAMS_MODEL = "__params__"   # reserved pipeline name for param checksums
_RING = 64                    # recalled (request_hash -> digest) per model
_RIDER = 16                   # newest entries published on the lease
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def enabled() -> bool:
    """Is the divergence sentinel armed (``FLAGS_divergence_check``)?"""
    try:
        return bool(_flags.get_flags("divergence_check"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


def param_steps() -> int:
    try:
        return max(1, int(_flags.get_flags("divergence_param_steps")))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return 50


# -- digests --------------------------------------------------------------
def fnv1a64(data: bytes, h: int = _FNV_OFFSET) -> int:
    """FNV-1a 64-bit over ``data`` (content fingerprint, not crypto)."""
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def fold_bytes(h: int, data: bytes) -> int:
    return fnv1a64(data, h)


def fold_token(h: int, token: int) -> int:
    """Fold one decode token id into a per-stream rolling hash."""
    return fnv1a64(int(token).to_bytes(8, "little", signed=True), h)


def _fold_array(h: int, v) -> int:
    a = np.ascontiguousarray(np.asarray(v))
    h = fold_bytes(h, str(a.dtype).encode())
    h = fold_bytes(h, repr(a.shape).encode())
    return fold_bytes(h, a.tobytes())


def digest_pairs(pairs) -> str:
    """Content digest of a serving reply batch ``[(name, array), ...]``."""
    h = _FNV_OFFSET
    for name, v in pairs:
        h = fold_bytes(h, str(name).encode())
        h = _fold_array(h, v)
    return f"{h:016x}"


def request_hash(feeds) -> str:
    """Grouping key: digest of the request content itself, so replicas
    that answered the SAME question are comparable fleet-wide."""
    h = _FNV_OFFSET
    if isinstance(feeds, dict):
        for name in sorted(feeds):
            h = fold_bytes(h, str(name).encode())
            h = _fold_array(h, feeds[name])
    elif isinstance(feeds, (bytes, bytearray)):
        h = fold_bytes(h, bytes(feeds))
    else:
        h = fold_bytes(h, repr(feeds).encode())
    return f"{h:016x}"


# -- the per-process audit ring -------------------------------------------
class AuditRing:
    """Bounded per-model ring of ``request_hash -> digest`` entries."""

    def __init__(self, cap: int = _RING):
        self.cap = int(cap)
        self._lock = threading.Lock()
        # model -> OrderedDict[(version, request_hash)] = digest
        self._rings: Dict[str, OrderedDict] = {}
        self._noted = 0
        self._c_noted = _stats.counter(
            "divergence.digests", "reply/stream/param digests folded "
            "into the audit ring (FLAGS_divergence_check)")

    def note(self, model: str, version: str, req_hash: str,
             digest: str) -> None:
        with self._lock:
            ring = self._rings.setdefault(str(model), OrderedDict())
            key = (str(version), str(req_hash))
            ring.pop(key, None)           # re-answer refreshes recency
            ring[key] = str(digest)
            while len(ring) > self.cap:
                ring.popitem(last=False)
            self._noted += 1
        self._c_noted.inc()

    def recent(self, limit: int = _RIDER) -> dict:
        """Compact lease/STATS_PULL rider: newest entries per model as
        ``{model: [[version, request_hash, digest], ...]}``."""
        with self._lock:
            out = {}
            for model, ring in self._rings.items():
                items = list(ring.items())[-int(limit):]
                out[model] = [[v, rh, d] for (v, rh), d in items]
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"noted": self._noted,
                    "models": {m: len(r) for m, r in self._rings.items()}}


# -- module singleton -----------------------------------------------------
_lock = threading.Lock()
_ring: Optional[AuditRing] = None


def ring(create: bool = True) -> Optional[AuditRing]:
    """The process-wide audit ring (lazily created when armed)."""
    global _ring
    with _lock:
        if _ring is None and create and enabled():
            _ring = AuditRing()
        return _ring


def note_reply(model: str, version: str, req_hash: str,
               digest: str) -> None:
    """Fold one serving reply digest — a no-op unless armed."""
    if not enabled():
        return
    r = ring()
    if r is not None:
        r.note(model, version, req_hash, digest)


def note_stream(model: str, version: str, prompt_hash: str,
                rolling: int) -> None:
    """Fold one finished decode stream's rolling token hash."""
    note_reply(model, version, prompt_hash, f"{rolling & _MASK64:016x}")


def note_param_checksum(step: int, checksum: int,
                        version: str = "") -> None:
    """Fold one DP replica's u64 parameter checksum at ``step``."""
    note_reply(PARAMS_MODEL, version, f"step:{int(step)}",
               f"{int(checksum) & _MASK64:016x}")


def recent_digests(limit: int = _RIDER) -> Optional[dict]:
    """The lease-data rider — ``None`` when off (byte-identity)."""
    if not enabled():
        return None
    r = ring(create=False)
    if r is None:
        return None
    return r.recent(limit)


def reset() -> None:
    """Drop the ring (tests / bench config isolation)."""
    global _ring
    with _lock:
        _ring = None


# -- the sentinel: cross-replica grouping ---------------------------------
def name_divergent(per_replica: Dict[str, Optional[dict]]) -> dict:
    """Group digests by (model, version, request-hash) across replicas
    and name any replica out-voted by a strict majority.

    ``per_replica`` maps a replica key (announce key or worker name) to
    that replica's :func:`recent_digests` payload.  Returns
    ``{"groups": n, "divergent": [finding...], "suspect": [pair...]}``
    where a finding names the guilty replica, the group key, its digest
    and the majority digest.  Pure function — safe on merged fleet
    snapshots as well as live supervisor lease data.
    """
    groups: Dict[tuple, Dict[str, str]] = {}
    for rep, payload in per_replica.items():
        if not isinstance(payload, dict):
            continue
        for model, entries in payload.items():
            for ent in entries or ():
                try:
                    version, rh, digest = ent[0], ent[1], ent[2]
                except (TypeError, IndexError):
                    continue
                groups.setdefault((str(model), str(version), str(rh)),
                                  {})[str(rep)] = str(digest)
    divergent: List[dict] = []
    suspect: List[dict] = []
    checked = 0
    for (model, version, rh), by_rep in groups.items():
        if len(by_rep) < 2:
            continue
        checked += 1
        votes: Dict[str, int] = {}
        for d in by_rep.values():
            votes[d] = votes.get(d, 0) + 1
        if len(votes) == 1:
            continue
        major = max(votes, key=lambda d: votes[d])
        if votes[major] >= 2:
            for rep, d in sorted(by_rep.items()):
                if d != major:
                    divergent.append({
                        "replica": rep, "model": model,
                        "version": version, "request_hash": rh,
                        "digest": d, "majority": major,
                        "agreeing": votes[major]})
        else:
            # two replicas, two answers: someone is wrong, no quorum
            # to say who — report the pair, never guess
            suspect.append({"model": model, "version": version,
                            "request_hash": rh,
                            "replicas": dict(sorted(by_rep.items()))})
    return {"groups": checked, "divergent": divergent, "suspect": suspect}


# -- pages / riders -------------------------------------------------------
def auditz() -> dict:
    """The audit section of ``/canaryz``."""
    if not enabled():
        return {"audit": "disabled (set FLAGS_divergence_check)"}
    r = ring(create=False)
    if r is None:
        return {"audit": {"noted": 0, "models": {}}}
    return {"audit": r.snapshot(), "recent": r.recent()}


def export_state() -> Optional[dict]:
    """The STATS_PULL rider — None when off / no ring (byte-identity)."""
    if not enabled():
        return None
    r = ring(create=False)
    if r is None:
        return None
    return {"recent": r.recent(), **r.snapshot()}


def merge_states(per_worker: Dict[str, dict]) -> dict:
    """Fleet rollup: run the sentinel over every worker's recent ring —
    a divergent replica is named from one aggregator endpoint."""
    rings = {w: (snap or {}).get("recent")
             for w, snap in per_worker.items()
             if isinstance(snap, dict)}
    verdict = name_divergent(rings)
    verdict["noted"] = sum(int((s or {}).get("noted") or 0)
                           for s in per_worker.values()
                           if isinstance(s, dict))
    for f in verdict["divergent"]:
        _flight.note("divergence_named", **f)
    return verdict
