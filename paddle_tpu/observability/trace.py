"""Runtime spans: executor/transport internals in the profiler stream.

User code already records spans through ``paddle_tpu.profiler``; this
module lets the *runtime itself* feed the same event stream under a
``runtime::`` name prefix and a ``runtime`` Chrome-trace category, so
``profiler.chrome_trace()`` / ``tools/timeline.py`` show the
lower→jit→dispatch pipeline interleaved with the user's ``train_step``
spans in one Perfetto view.

Overhead discipline: a span is recorded only when the profiler is armed
AND ``FLAGS_runtime_stats`` is on; the disabled path is two dict
lookups, so instrumented hot paths cost effectively nothing by default
(the profiler starts disabled).
"""
from __future__ import annotations

import contextlib
import time

from .. import profiler as _profiler
from ..core import flags as _flags

CATEGORY = "runtime"
PREFIX = "runtime::"


def flags_on() -> bool:
    """The one FLAGS_runtime_stats gate — every instrumentation site
    (executor, lowering, transport, observability.enabled) routes
    through here so gating semantics live in a single place."""
    try:
        return bool(_flags.get_flags("runtime_stats"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


def enabled() -> bool:
    # profiler check first: it is False in steady state, so the common
    # path is one dict lookup
    return _profiler.is_profiler_enabled() and flags_on()


def emit(name: str, t0_ns: int, t1_ns: int) -> None:
    """Record an already-timed runtime span (callers that measured a
    region for stats anyway reuse the timestamps instead of nesting a
    context manager)."""
    _profiler._emit(PREFIX + name, t0_ns, t1_ns, cat=CATEGORY)


@contextlib.contextmanager
def span(name: str):
    """``with trace.span("executor::lower"): ...`` — no-op when disabled."""
    if not enabled():
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        _profiler._emit(PREFIX + name, t0, time.perf_counter_ns(),
                        cat=CATEGORY)
