"""Runtime spans: profiler-stream spans + distributed trace propagation.

Two cooperating layers live here:

**Profiler-stream spans** (the original role): the runtime feeds the
``paddle_tpu.profiler`` event stream under a ``runtime::`` name prefix
so ``profiler.chrome_trace()`` shows the lower→jit→dispatch pipeline
interleaved with user ``train_step`` spans.  Recorded only when the
profiler is armed AND ``FLAGS_runtime_stats`` is on.

**Distributed tracing** (Dapper-style): a :class:`SpanContext`
(trace id, span id, sampled bit) rides a thread-local stack; the
executor opens one *step-root* span per ``run`` (head-sampled by
``FLAGS_trace_sample_rate``), the RPC client injects the current
context into a compact wire extension on the frame
(``distributed/transport.py``), and the server opens child spans from
the inbound context — so a trainer step's ``send_vars`` and the
pserver's apply land under ONE trace id across processes.  Completed
spans go to a bounded in-memory ring (``FLAGS_trace_ring_spans``)
served over the ``TRACE_PULL`` RPC and the ``/tracez`` debug page;
``stitch_chrome_trace`` merges per-worker rings into one
Chrome/Perfetto JSON with real ``pid``/process-name metadata.

Overhead discipline: with sampling off (``FLAGS_trace_sample_rate=0``,
the default) ``start_span`` is a thread-local read plus two dict
lookups and returns a shared no-op — no ring writes, no wire bytes.
Span timestamps use ``time.time_ns()`` (the wall clock), the one clock
processes on a host share, so stitched timelines align without offset
fitting.
"""
from __future__ import annotations

import contextlib
import json
import os
import random as _random
import socket as _socket
import struct
import sys as _sys
import threading
import time
from collections import deque
from typing import Dict, List, Mapping, NamedTuple, Optional

from .. import profiler as _profiler
from ..core import flags as _flags

CATEGORY = "runtime"
PREFIX = "runtime::"


def flags_on() -> bool:
    """The one FLAGS_runtime_stats gate — every instrumentation site
    (executor, lowering, transport, observability.enabled) routes
    through here so gating semantics live in a single place."""
    try:
        return bool(_flags.get_flags("runtime_stats"))
    except KeyError:  # pragma: no cover - flag always defined
        return False


def enabled() -> bool:
    # profiler check first: it is False in steady state, so the common
    # path is one dict lookup
    return _profiler.is_profiler_enabled() and flags_on()


def emit(name: str, t0_ns: int, t1_ns: int) -> None:
    """Record an already-timed runtime span (callers that measured a
    region for stats anyway reuse the timestamps instead of nesting a
    context manager)."""
    _profiler._emit(PREFIX + name, t0_ns, t1_ns, cat=CATEGORY)


@contextlib.contextmanager
def span(name: str):
    """``with trace.span("executor::lower"): ...`` — no-op when disabled."""
    if not enabled():
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        _profiler._emit(PREFIX + name, t0, time.perf_counter_ns(),
                        cat=CATEGORY)


# ---------------------------------------------------------------------------
# distributed tracing: trace context, span ring, fleet stitching
# ---------------------------------------------------------------------------

_SNAPSHOT_VERSION = 1

# compact wire form of a SpanContext (the RPC frame extension):
# u64 trace_id | u64 span_id | u8 flags (bit0 = sampled)
_WIRE = struct.Struct("<QQB")
WIRE_CTX_SIZE = _WIRE.size


class SpanContext(NamedTuple):
    """What crosses a process (or thread) boundary: enough to parent a
    child span, nothing else (the Dapper trace-context shape)."""

    trace_id: int
    span_id: int
    sampled: bool = True


def ctx_to_wire(ctx: SpanContext) -> bytes:
    return _WIRE.pack(ctx.trace_id, ctx.span_id, 1 if ctx.sampled else 0)


def ctx_from_wire(data) -> Optional[SpanContext]:
    """Decode a wire extension; None for anything malformed (a peer of a
    future build must never crash the request path over trace bytes)."""
    if data is None:
        return None
    b = bytes(data)
    if len(b) != _WIRE.size:
        return None
    trace_id, span_id, fl = _WIRE.unpack(b)
    return SpanContext(trace_id, span_id, bool(fl & 1))


_tls = threading.local()


def current() -> Optional[SpanContext]:
    """The innermost active context on THIS thread (or None)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _push(ctx: SpanContext) -> None:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def _pop() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


@contextlib.contextmanager
def activate(ctx: Optional[SpanContext]):
    """Re-home a captured context onto this thread — the explicit
    handoff for fan-out pools (``RPCClient.parallel``, stripe threads),
    where thread-local context does not follow the work."""
    if ctx is None:
        yield
        return
    _push(ctx)
    try:
        yield
    finally:
        _pop()


def inject() -> Optional[bytes]:
    """Wire bytes of the current context, or None when nothing sampled
    is active — the None path is what keeps unsampled frames
    byte-identical to the pre-trace wire format."""
    c = current()
    return ctx_to_wire(c) if c is not None and c.sampled else None


def sample_rate() -> float:
    try:
        return float(_flags.get_flags("trace_sample_rate"))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return 0.0


# Private RNG: id generation and sampling draws must not consume from
# (or collide through) the process-global `random` instance — workers
# that call random.seed(K) for reproducibility would otherwise all
# generate the SAME id sequence, and enabling sampling would silently
# shift seeded training runs.  random.Random() self-seeds from urandom.
_rng = _random.Random()


def _new_id() -> int:
    # nonzero 63-bit ids: 0 is the "no parent" sentinel, and staying
    # under 2**63 keeps every JSON consumer (signed-int parsers) happy
    return _rng.getrandbits(63) | 1


# span ring: completed spans, process-wide, bounded
_ring_lock = threading.Lock()
_ring: deque = deque(maxlen=4096)
_open_spans: Dict[int, "Span"] = {}
_total_recorded = 0


def _ring_capacity() -> int:
    try:
        return max(16, int(_flags.get_flags("trace_ring_spans")))
    except (KeyError, TypeError, ValueError):  # pragma: no cover
        return 4096


class Span:
    """One traced region; context manager.  Created via
    :func:`start_span` (which owns the sample decision) — entering
    pushes this span's context for children, exiting records it into
    the ring.  In-flight spans are visible to the flight recorder."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "t0_ns", "t1_ns", "tags", "error", "lane")

    def __init__(self, name: str, cat: str, trace_id: int, parent_id: int,
                 tags: Optional[dict] = None):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.tags = dict(tags) if tags else None
        self.error = None
        self.t0_ns = 0
        self.t1_ns = 0
        self.lane = 0

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, True)

    def annotate(self, **tags) -> None:
        """Attach key→value tags (shown as Chrome-trace args)."""
        if self.tags is None:
            self.tags = {}
        self.tags.update(tags)

    def __enter__(self) -> "Span":
        self.t0_ns = time.time_ns()
        self.lane = _profiler.thread_lane()
        _push(self.context())
        with _ring_lock:
            _open_spans[self.span_id] = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.t1_ns = time.time_ns()
        if exc is not None:
            self.error = repr(exc)[:200]
        _pop()
        global _total_recorded
        with _ring_lock:
            _open_spans.pop(self.span_id, None)
            if _ring.maxlen != _ring_capacity():
                _resize_ring_locked()
            _ring.append(self)
            _total_recorded += 1
        return False

    def to_dict(self, now_ns: Optional[int] = None) -> dict:
        t1 = self.t1_ns or (now_ns if now_ns is not None else time.time_ns())
        d = {"name": self.name, "cat": self.cat,
             "trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "tid": self.lane,
             "ts_us": self.t0_ns / 1000.0,
             "dur_us": max(t1 - self.t0_ns, 0) / 1000.0}
        if not self.t1_ns:
            d["in_flight"] = True
        if self.error:
            d["error"] = self.error
        if self.tags:
            d["tags"] = dict(self.tags)
        return d


def _resize_ring_locked() -> None:
    global _ring
    _ring = deque(_ring, maxlen=_ring_capacity())


_NOOP = contextlib.nullcontext()
NOOP = _NOOP  # callers that pre-check current() reuse the shared no-op


def start_span(name: str, cat: str = "runtime",
               parent: Optional[SpanContext] = None, root: bool = True,
               tags: Optional[dict] = None):
    """Open a distributed span; returns a context manager.

    - ``parent`` given (the server side, from the wire): child of it.
    - otherwise child of this thread's current context, if any.
    - no context at all: a ROOT is head-sampled by
      ``FLAGS_trace_sample_rate`` — unless ``root=False`` (RPC client /
      host-op internals, which never start traces of their own).

    Unsampled / disabled paths return a shared no-op context manager.
    """
    p = parent if parent is not None else current()
    if p is None:
        if not root or not flags_on():
            return _NOOP
        rate = sample_rate()
        if rate <= 0.0 or (rate < 1.0 and _rng.random() >= rate):
            return _NOOP
        return Span(name, cat, _new_id(), 0, tags)
    if not p.sampled or not flags_on():
        return _NOOP
    return Span(name, cat, p.trace_id, p.span_id, tags)


def spans(limit: Optional[int] = None) -> List[dict]:
    """Completed spans (ring tail), oldest first."""
    with _ring_lock:
        out = list(_ring)
    if limit is not None and limit >= 0:
        out = out[-limit:] if limit else []
    return [s.to_dict() for s in out]


def open_spans() -> List[dict]:
    """In-flight spans (entered, not yet exited) — the post-mortem view
    the flight recorder dumps when a worker dies mid-step."""
    now = time.time_ns()
    with _ring_lock:
        live = list(_open_spans.values())
    return [s.to_dict(now_ns=now) for s in live]


def total_spans_recorded() -> int:
    with _ring_lock:
        return _total_recorded


def clear_spans() -> None:
    global _total_recorded
    with _ring_lock:
        _ring.clear()
        _open_spans.clear()
        _total_recorded = 0


def _process_role() -> str:
    return os.environ.get("PADDLE_TRAINING_ROLE", "STANDALONE")


def local_trace_snapshot(limit: Optional[int] = None) -> dict:
    """This process's span ring + identity — the ``TRACE_PULL`` response
    body and the unit :func:`stitch_chrome_trace` merges."""
    try:
        host = _socket.gethostname()
    except OSError:  # pragma: no cover
        host = "?"
    snap = {"version": _SNAPSHOT_VERSION,
            "pid": os.getpid(),
            "host": host,
            "role": _process_role(),
            "argv0": os.path.basename(_sys.argv[0]) if _sys.argv else "",
            "sample_rate": sample_rate(),
            "total_recorded": total_spans_recorded(),
            "lanes": _profiler.lane_names(),
            "spans": spans(limit=limit)}
    # memory-anatomy counter lanes (FLAGS_memory_attribution): per-pool
    # resident/parked byte series rebuilt from the allocation event
    # ring, rendered by stitch_chrome_trace as ph:"C" counter tracks.
    # Lazy import — memory.py must stay importable without trace.py.
    from . import memory as _memory
    if _memory.enabled():
        counters = _memory.counter_series()
        if counters:
            snap["counters"] = counters
    return snap


def local_snapshot_payload(limit: Optional[int] = None) -> bytes:
    return json.dumps(local_trace_snapshot(limit=limit)).encode("utf-8")


def _span_chrome_event(s: dict, pid: int) -> dict:
    args = {"trace_id": f"{int(s.get('trace_id', 0)):016x}",
            "span_id": f"{int(s.get('span_id', 0)):016x}"}
    if s.get("parent_id"):
        args["parent_id"] = f"{int(s['parent_id']):016x}"
    if s.get("error"):
        args["error"] = s["error"]
    if s.get("in_flight"):
        args["in_flight"] = True
    for k, v in (s.get("tags") or {}).items():
        args.setdefault(str(k), v)
    return {"name": s.get("name", "?"), "cat": s.get("cat", "runtime"),
            "ph": "X", "pid": pid, "tid": int(s.get("tid", 0)),
            "ts": s.get("ts_us", 0.0),
            # zero-duration spans still get a sliver so Perfetto renders
            "dur": max(float(s.get("dur_us", 0.0)), 0.001),
            "args": args}


def stitch_chrome_trace(per_worker: Mapping[str, dict]) -> dict:
    """{worker label: local_trace_snapshot()} → one Chrome/Perfetto
    JSON: every worker keeps its REAL pid (collisions across hosts get
    bumped deterministically), with ``process_name``/``thread_name``
    metadata so a trainer+pserver step renders as one labeled
    multi-process timeline."""
    events: List[dict] = []
    used_pids: set = set()
    for worker in sorted(per_worker):
        snap = per_worker[worker] or {}
        pid = int(snap.get("pid", 0))
        while pid in used_pids:
            pid += 1
        used_pids.add(pid)
        label = f"{worker} · {snap.get('role', '?')} (pid {snap.get('pid')}"
        host = snap.get("host")
        label += f" @ {host})" if host else ")"
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        for lane, lname in sorted((snap.get("lanes") or {}).items(),
                                  key=lambda kv: int(kv[0])):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": int(lane), "args": {"name": str(lname)}})
        for s in snap.get("spans", []):
            events.append(_span_chrome_event(s, pid))
        # memory counter lanes: one ph:"C" track per pool, resident +
        # parked bytes stacked, sharing the span timeline's wall-clock
        # microsecond axis (both derive from time.time())
        for c in snap.get("counters", []):
            events.append({"ph": "C", "name": f"mem:{c.get('pool')}",
                           "pid": pid, "tid": 0,
                           "ts": c.get("ts_us", 0.0),
                           "args": {"resident": c.get("resident", 0),
                                    "parked": c.get("parked", 0)}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
