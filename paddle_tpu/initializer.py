"""Initializers — emitted as ops into the startup program.

Reference: ``python/paddle/fluid/initializer.py`` (Constant/Uniform/Normal/
TruncatedNormal/Xavier/MSRA/NumpyArray, each appending a startup-program op).
The startup program is itself lowered and jitted; random initializer ops
draw from the threaded PRNG state, so initialization is reproducible from
``program.random_seed``.
"""
from __future__ import annotations

import math

import contextlib
import numpy as np


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _fan_in_out(self, var):
        shape = var.shape
        if len(shape) < 2:
            return int(shape[0]) if shape else 1, int(shape[0]) if shape else 1
        receptive = 1
        for s in shape[2:]:
            receptive *= int(s)
        fan_in = int(shape[0]) * receptive if len(shape) > 2 else int(shape[0])
        fan_out = int(shape[1]) * receptive if len(shape) > 2 else int(shape[1])
        # conv filters are OIHW: O=out, I=in
        if len(shape) > 2:
            fan_in = int(shape[1]) * receptive
            fan_out = int(shape[0]) * receptive
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            "fill_constant", {}, {"Out": [var.name]},
            {"shape": list(var.shape), "dtype": var.dtype, "value": self.value},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            "uniform_random", {}, {"Out": [var.name]},
            {"shape": list(var.shape), "dtype": var.dtype,
             "min": self.low, "max": self.high, "seed": self.seed,
             "seed_name": var.name},
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "gaussian_random", {}, {"Out": [var.name]},
            {"shape": list(var.shape), "dtype": var.dtype,
             "mean": self.loc, "std": self.scale, "seed": self.seed,
             "seed_name": var.name},
        )


class TruncatedNormalInitializer(NormalInitializer):
    def __call__(self, var, block):
        block.append_op(
            "truncated_gaussian_random", {}, {"Out": [var.name]},
            {"shape": list(var.shape), "dtype": var.dtype,
             "mean": self.loc, "std": self.scale, "seed": self.seed,
             "seed_name": var.name},
        )


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(
            "assign_value", {}, {"Out": [var.name]},
            {"shape": list(self.value.shape), "dtype": var.dtype,
             "values": self.value.reshape(-1).tolist()},
        )


class BilinearInitializer(Initializer):
    """Bilinear-upsampling kernel init for conv2d_transpose weights
    (reference initializer.py BilinearInitializer): with a [C_out, C_in,
    H, W] weight, every spatial slice becomes the standard bilinear
    interpolation kernel w[i, j] = (1 - |i/f - c|) * (1 - |j/f - c|),
    f = ceil(W/2), c = (2f - 1 - f%2) / (2f) — so a stride-f transposed
    conv initialized this way performs bilinear upsampling."""

    def __call__(self, var, block):
        shape = [int(d) for d in var.shape]
        if len(shape) != 4:
            raise ValueError("BilinearInitializer needs a 4-D weight")
        H, W = shape[2], shape[3]
        if H != W:
            raise ValueError(
                f"BilinearInitializer needs a square kernel, got "
                f"{H}x{W} (a rectangular bilinear kernel is not "
                "well-defined)")
        f = int(np.ceil(W / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        j = np.arange(W)
        i = np.arange(H)[:, None]
        kern = ((1 - np.abs(i / f - c))
                * (1 - np.abs(j / f - c))).astype("float32")
        # keep the startup program small: store the [H, W] kernel ONCE
        # and expand across [C_out, C_in] at lowering (an FCN-style
        # [21, 21, 64, 64] head would otherwise bake 1.8M duplicated
        # floats into the op attrs)
        block.append_op(
            "assign_value", {}, {"Out": [var.name + "@BILINEAR_KERN"]},
            {"shape": [1, 1, H, W], "dtype": var.dtype,
             "values": kern.reshape(-1).tolist()})
        block.create_var(name=var.name + "@BILINEAR_KERN",
                         dtype=var.dtype, shape=(1, 1, H, W))
        block.append_op(
            "expand", {"X": [var.name + "@BILINEAR_KERN"]},
            {"Out": [var.name]},
            {"expand_times": [shape[0], shape[1], 1, 1]})


def force_init_on_cpu():
    """Reference framework hint: whether initializers must run on CPU.
    The TPU executor stages all initialization through host arrays
    already, so this is always False (compat shim)."""
    return False


@contextlib.contextmanager
def init_on_cpu():
    """Reference context manager forcing CPU-side init — a no-op here
    (see force_init_on_cpu)."""
    yield


# reference-compatible aliases (initializer.py tail)
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
