"""Auto-generated unary layer wrappers (reference:
layers/layer_function_generator.py + layers/ops.py — python wrappers emitted
from OpProto; here generated from the lowering registry)."""
from __future__ import annotations

import sys

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "square", "softplus", "softsign", "relu", "relu6",
    "elu", "gelu", "leaky_relu", "soft_relu", "brelu", "pow", "stanh",
    "hard_sigmoid", "swish", "hard_shrink", "thresholded_relu", "log",
    "sign",
]

_mod = sys.modules[__name__]


def _make(op_type):
    def layer(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
        helper.append_op(op_type, {"X": [x]}, {"Out": [out]}, attrs)
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"{op_type} activation (activation_op.cc functor)."
    return layer


for _op in _UNARY_OPS:
    from ..core import registry as _registry
    if _registry.has(_op):
        setattr(_mod, _op, _make(_op))

__all__ = [op for op in _UNARY_OPS if hasattr(_mod, op)]
