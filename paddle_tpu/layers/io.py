"""Input layers (reference: layers/io.py — ``data:37``).

``data`` declares a feed var.  For variable-length sequences
(``lod_level>=1``) it also declares the companion ``<name>@LEN`` int32
length vector (see layers/nn.py module docstring for the padded-sequence
contract replacing LoDTensor).
"""
from __future__ import annotations

from ..core.program import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=None):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    if lod_level >= 1:
        # padded-sequence: runtime layout is [B, T, ...]; T is symbolic
        shape = [shape[0], -1] + shape[1:]
    main = default_main_program().global_block
    var = main.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient,
    )
    if lod_level >= 1:
        len_var = main.create_var(
            name=name + "@LEN", shape=[-1], dtype="int32", stop_gradient=True)
        main.seq_len_map[name] = len_var.name
    return var
