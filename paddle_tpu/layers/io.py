"""Input layers (reference: layers/io.py — ``data:37``).

``data`` declares a feed var.  For variable-length sequences
(``lod_level>=1``) it also declares the companion ``<name>@LEN`` int32
length vector (see layers/nn.py module docstring for the padded-sequence
contract replacing LoDTensor).
"""
from __future__ import annotations

from ..core.program import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=None):
    if lod_level > 2:
        raise NotImplementedError(
            f"lod_level={lod_level}: the padded contract covers level 1 "
            "([B,T,...] + @LEN) and level 2 ([B,S,W,...] + @LEN/@LEN2, "
            "reference lod_tensor.h:58 nesting); deeper nesting has no "
            "in-scope reference workload")
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    if lod_level == 1:
        # padded-sequence: runtime layout is [B, T, ...]; T is symbolic
        shape = [shape[0], -1] + shape[1:]
    elif lod_level == 2:
        # padded-nested: [B, S, W, ...] (samples, sentences, words)
        shape = [shape[0], -1, -1] + shape[1:]
    main = default_main_program().global_block
    var = main.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient,
    )
    if lod_level >= 1:
        len_var = main.create_var(
            name=name + "@LEN", shape=[-1], dtype="int32", stop_gradient=True)
        main.seq_len_map[name] = len_var.name
    if lod_level == 2:
        len2_var = main.create_var(
            name=name + "@LEN2", shape=[-1, -1], dtype="int32",
            stop_gradient=True)
        main.seq_len2_map[name] = len2_var.name
    return var


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Async python-fed reader (reference layers/io.py:477 py_reader +
    create_py_reader_op / lod_tensor_blocking_queue.h).

    Returns a reader object whose ``decorate_paddle_reader``/
    ``decorate_tensor_provider`` hook up a python generator; iterating the
    attached DataLoader prefetches batches on a background thread (the
    blocking-queue capacity bound), and ``read_file`` unpacks the declared
    feed vars.  On TPU the double-buffering H2D overlap is handled by the
    async dispatch of ``jax.device_put`` — the explicit double_buffer
    decorator below is a no-op wrapper kept for API parity.
    """
    from ..core import unique_name
    from ..data.loader import PyReader

    lod_levels = lod_levels or [0] * len(shapes)
    prefix = name or unique_name.generate("py_reader")
    vars_ = []
    for i, (shape, dtype, lod) in enumerate(zip(shapes, dtypes, lod_levels)):
        vars_.append(data(f"{prefix}_{i}", list(shape),
                          dtype=dtype, lod_level=lod,
                          append_batch_size=False))
    return PyReader(vars_, capacity)


def double_buffer(reader, place=None, name=None):
    """API-parity wrapper (reference layers/io.py:892): device-side double
    buffering is inherent to async dispatch + donated-buffer stepping on
    TPU, so this returns the reader unchanged."""
    return reader


def read_file(reader):
    """Unpack the feed vars declared by ``py_reader`` (reference
    layers/io.py read_file)."""
    vars_ = reader.feed_vars
    return vars_[0] if len(vars_) == 1 else list(vars_)


def get_places(device_count=0, device_type=None):
    """Reference layers/device.py get_places (the parallel_do companion,
    get_places_op.cc).  parallel_do itself is deprecated upstream and
    unported (ParallelExecutor/GSPMD replaces in-graph data parallelism);
    this shim returns the visible JAX devices for code that only
    enumerates places."""
    import jax

    from .. import platform

    devs = jax.devices()
    if device_count:
        devs = devs[:device_count]
    return [platform.TPUPlace(i) if d.platform == "tpu"
            else platform.CPUPlace() for i, d in enumerate(devs)]
