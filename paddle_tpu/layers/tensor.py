"""Tensor creation/assignment layers (reference: layers/tensor.py)."""
from __future__ import annotations

import numpy as np

from ..core.program import Variable
from ..layer_helper import LayerHelper


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Standalone trainable parameter (reference layers/tensor.py
    create_parameter)."""
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter")
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_global_variable(
        shape=None, dtype=dtype, persistable=persistable,
        name=name or helper.name)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..initializer import ConstantInitializer
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        shape=shape, dtype=dtype, persistable=persistable,
        name=name or helper.name)
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    out = out or helper.create_variable_for_type_inference(
        dtype, shape=tuple(shape), stop_gradient=True)
    helper.append_op(
        "fill_constant", {}, {"Out": [out]},
        {"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    shp = list(shape)
    shp[output_dim_idx] = input.shape[input_dim_idx]
    out = helper.create_variable_for_type_inference(dtype, shape=tuple(shp))
    helper.append_op(
        "fill_constant_batch_size_like", {"Input": [input]}, {"Out": [out]},
        {"shape": list(shape), "dtype": dtype, "value": float(value),
         "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx},
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        output = output or helper.create_variable_for_type_inference(
            input.dtype, shape=input.shape)
        helper.append_op("assign", {"X": [input]}, {"Out": [output]})
    else:
        arr = np.asarray(input)
        output = output or helper.create_variable_for_type_inference(
            str(arr.dtype), shape=arr.shape)
        helper.append_op(
            "assign_value", {}, {"Out": [output]},
            {"shape": list(arr.shape), "dtype": output.dtype,
             "values": arr.reshape(-1).tolist()},
        )
    return output


def cast(x, dtype):
    from .nn import cast as _cast
    return _cast(x, dtype)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    out = out or helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op("fill_zeros_like", {"X": [x]}, {"Out": [out]})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(
        x.dtype, shape=x.shape)
    helper.append_op("increment", {"X": [x]}, {"Out": [out]}, {"step": value})
    return out


def argmax(x, axis=0):
    from .nn import argmax as _argmax
    return _argmax(x, axis)


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    shp = tuple(s for i, s in enumerate(x.shape) if i != (axis % len(x.shape)))
    out = helper.create_variable_for_type_inference("int64", shape=shp)
    helper.append_op("arg_min", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    return out
